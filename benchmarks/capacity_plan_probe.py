"""capacity_plan: caplens' prediction contract, measured (ISSUE 20).

The capacity observatory (obs/caplens) claims its what-if planner can
predict a fleet size it has NEVER run: observe a 1-replica fleet under
a seeded arrival trace, replay the recorded ring through the
discrete-event sim at n=2, and the predicted availability should match
what a REAL 2-replica fleet measures on the identical trace. This
probe closes that loop the kv_economy way — predict at an untested
configuration, then measure it:

  * Phase A (observe): one real `node --serve_lm` replica behind the
    real router. A seeded `bursty_arrivals` trace (ISSUE 13 envelope:
    diurnal raised-cosine, burst_factor x base) drives open-loop load
    through the front door; the router's lens records every arrival,
    commit, and shed, and the replicaset's lifecycle seams fill the
    cold-start ledger from the child's boot gauges. The /capz and
    /fleetz surfaces are verified E2E over HTTP against these live
    processes (json + prom, per-stage wanted column + max rollup).
  * Predict: `plan(2, warm=2)` from Phase A's lens — the 2-replica
    verdict from 1-replica evidence (plus the plan(1) self-replay and
    the cold-debt story `plan(2, warm=1)` as row detail).
  * Phase B (measure): a real 2-replica fleet + router under the
    IDENTICAL trace (same seed, same offsets). Measured availability =
    completed-inside-timeout / submitted — sheds and timeouts both
    count against, exactly the sim's verdict.

Asserted (--assert exits nonzero when any fails):

  * |predicted - measured| 2-replica availability <= PRED_ERROR_CEIL
    (0.10 absolute — the kvlens-curve ceiling, now for capacity);
  * predicted vs measured completion-wall p95 within a factor of
    WAIT_RATIO_BOUND (3.5: the sim prices queueing but not this
    1-core host's core-sharing stretch — overlapping decodes on a
    single core each run ~2x slower, a substrate artifact a real
    multi-chip fleet does not carry — nor the router's RPC/dispatch
    overhead, a fixed ~0.1 s adder that dominates p95 on a trace
    whose pure service wall is ~30 ms; measured ~2.8x, the band is
    documented, not hidden);
  * the cold-start ledger covers >= COLDSTART_COVERAGE_FLOOR (0.95)
    of every spawn->first-token wall, with compile as its OWN bucket
    (> 0 on these fresh children — the counter is the same
    jax_compile_seconds_total the recompile census cross-checks).

Regime note (why gpt2-test, and why these rates): this host has ONE
core, so two gpt2 replicas cannot double CPU-bound throughput — a
saturating trace would make "add a replica" a lie no planner should
learn. gpt2-test decodes a request in ~tens of ms, and the trace is
sized to an AVERAGE utilization of AVG_RHO (calibrated against the
measured per-request service wall) with bursts to ~1.5x that: the
1-replica fleet sheds at its n*max_inflight admission bound during
burst clumps (the thing the sim models), while total CPU demand stays
comfortably under the core — so the 2-replica win is the DOUBLED
admission bound absorbing the clumps, and concurrent-decode episodes
(where one core makes two replicas stretch each other, a substrate
artifact the sim rightly does not model) stay rare enough not to
poison the availability prediction. AVG_RHO=0.6 was measured to leak
that artifact into the verdict (predicted 0.99 vs measured 0.85);
0.45 keeps the contract honest on this host. STUDIES carries the
measured story.

`python -m benchmarks.capacity_plan_probe [--assert] [--light]
[--require-substrate tpu|cpu]` prints one JSON row; the run_all
`capacity_plan` row rides `measure()` and honors
$DNN_TPU_REQUIRE_SUBSTRATE.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# asserted ceilings/floors (ledger ratchets read these by name)
PRED_ERROR_CEIL = 0.10          # |predicted - measured| availability
COLDSTART_COVERAGE_FLOOR = 0.95  # ledger buckets / spawn->first-token
WAIT_RATIO_BOUND = 3.5          # pred vs measured wall-p95 factor band

MODEL = "gpt2-test"  # light preset: admission-bound regime on 1 core
# (the full gpt2 at ~1.8 s/request saturates the core long before the
# admission bound binds — see the module docstring's regime note)
SLOTS = 1            # one decode slot per replica: the sim's server
MAX_INFLIGHT = 2     # router bound: 1 in service + 1 queued per replica
MAX_NEW = 24
REQ_TIMEOUT_S = 10.0
TRACE_SEED = 13
AVG_RHO = 0.45       # trace's average utilization of ONE replica
BURST = 3.0          # bursty_arrivals burst_factor (peak rho ~0.68)
PERIOD_S = 20.0      # diurnal period (3 full cycles per 60 s trace)
READY_DEADLINE_S = 240.0

# ports: distinct from fleet_serving (599[0-3]x) and chaos (594xx/595xx)
_A = (59961, 59971)        # phase A: (grpc base, metrics base), 1 replica
_A_ROUTER = 59960
_B = (59981, 59991)        # phase B: 2 replicas from here
_B_ROUTER = 59980


def _prompt():
    import numpy as np

    return (np.arange(1, 9) % 999).astype(np.int32)


def _warm_direct(address: str, deadline_s: float = READY_DEADLINE_S):
    """First request straight at a replica (pays its compile), polled
    FAST (0.1 s): the gap between child-ready and first token lands in
    the ledger's warmup bucket, so the caller must not pad it with a
    lazy poll."""
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    t_end = time.monotonic() + deadline_s
    last = "no attempt"
    while time.monotonic() < t_end:
        cl = NodeClient(address, transport="grpc", breaker=False)
        try:
            status, result = cl.send_tensor(
                np.asarray(_prompt(), np.int32),
                request_id=f"gen:{MAX_NEW}:0", timeout=120.0, retries=0)
            if result is not None:
                return
            last = str(status)
        except Exception as e:  # noqa: BLE001 — still booting
            last = f"{type(e).__name__}: {e}"
        finally:
            cl.close()
        time.sleep(0.1)
    raise RuntimeError(f"warm request never completed: {last[:200]}")


def _service_p50(address: str, k: int = 10) -> float:
    """Sequential timed requests at an idle, warmed replica: the
    per-request service wall the trace rate is calibrated against."""
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    walls = []
    cl = NodeClient(address, transport="grpc", breaker=False)
    try:
        for i in range(k):
            t0 = time.monotonic()
            _, result = cl.send_tensor(
                np.asarray(_prompt(), np.int32),
                request_id=f"gen:{MAX_NEW}:c{i}", timeout=60.0,
                retries=0)
            if result is not None:
                walls.append(time.monotonic() - t0)
    finally:
        cl.close()
    if not walls:
        raise RuntimeError("service calibration: no request completed")
    walls.sort()
    return walls[len(walls) // 2]


class _TraceGen:
    """Drive a PRECOMPUTED arrival schedule open-loop (thread per
    request, the fleet_serving pattern): both phases replay the same
    seeded offsets, so predicted and measured fleets face bit-identical
    demand. Every record ends ok / rejected / None (silently lost)."""

    def __init__(self, address: str, offsets, t0: float):
        self.address = address
        self.offsets = list(offsets)
        self.t0 = t0
        self.records: list = []

    def run(self):
        import numpy as np

        from dnn_tpu.comm.client import NodeClient

        prompt = np.asarray(_prompt(), np.int32)
        threads = []

        def one(rec):
            cl = NodeClient(self.address, transport="grpc",
                            breaker=False)
            try:
                status, result = cl.send_tensor(
                    prompt, request_id=f"gen:{MAX_NEW}:{rec['i']}",
                    timeout=REQ_TIMEOUT_S, retries=0)
                if result is not None:
                    rec["outcome"] = "ok"
                    rec["tokens"] = int(np.asarray(result).size)
                else:
                    rec["outcome"] = "rejected"
                    rec["error"] = str(status)[:120]
            except Exception as e:  # noqa: BLE001 — explicit rejection
                rec["outcome"] = "rejected"
                rec["error"] = f"{type(e).__name__}: {e}"[:120]
            finally:
                rec["t_done"] = time.monotonic() - self.t0
                cl.close()

        for i, off in enumerate(self.offsets):
            now = time.monotonic() - self.t0
            if off > now:
                time.sleep(off - now)
            rec = {"i": i, "t": off, "outcome": None, "tokens": 0}
            self.records.append(rec)
            th = threading.Thread(target=one, args=(rec,), daemon=True)
            th.start()
            threads.append(th)
        t_end = time.monotonic() + REQ_TIMEOUT_S + 10
        for th in threads:
            th.join(timeout=max(t_end - time.monotonic(), 0.1))
        return self


def _availability(records) -> float:
    ok = sum(1 for r in records if r["outcome"] == "ok")
    return ok / max(len(records), 1)


def _wall_p95(records):
    walls = sorted(r["t_done"] - r["t"] for r in records
                   if r["outcome"] == "ok" and "t_done" in r)
    if not walls:
        return None
    return walls[min(int(0.95 * len(walls)), len(walls) - 1)]


def _check_surfaces(port: int, row: dict):
    """E2E over HTTP against the live router + replicas: /capz in both
    formats, /fleetz per-stage wanted column + explicit max rollup
    (the satellite's regression, proven against real processes)."""
    from urllib.request import urlopen

    base = f"http://127.0.0.1:{port}"
    z = json.loads(urlopen(base + "/capz", timeout=10).read().decode())
    assert z["demand"]["arrivals_total"] > 0, "/capz saw no arrivals"
    assert z["capacity"]["commits_total"] > 0, "/capz saw no commits"
    assert z["coldstart"]["finalized"] >= 1, \
        "no finalized cold-start entry on /capz"
    prom = urlopen(base + "/capz?format=prom",
                   timeout=10).read().decode()
    assert "dnn_tpu_caplens_arrival_rate_hz" in prom
    assert "dnn_tpu_caplens_coldstart_coverage" in prom
    fz = json.loads(urlopen(base + "/fleetz",
                            timeout=10).read().decode())
    fl = fz["fleet"]
    by_stage = fl.get("wanted_replicas_by_stage") or {}
    assert "router" in by_stage, f"no router stage: {by_stage}"
    vals = [v for v in by_stage.values() if v is not None]
    assert fl["wanted_replicas"] == (max(vals) if vals else None), \
        f"rollup is not the stage max: {fl['wanted_replicas']} " \
        f"vs {by_stage}"
    fprom = urlopen(base + "/fleetz?format=prom",
                    timeout=10).read().decode()
    assert "dnn_tpu_fleet_stage_wanted_replicas" in fprom
    row["fleetz_wanted_by_stage"] = by_stage
    row["fleetz_wanted_rollup"] = fl["wanted_replicas"]
    row["capz_wanted"] = z["wanted_replicas"]


def _offsets_for(svc_p50: float, dur_s: float):
    """The seeded trace, sized to the MEASURED service wall: the
    raised-cosine envelope's average multiplier is (1 + burst)/2, so
    this base rate puts the time-averaged offered load at AVG_RHO of
    one replica's capacity (peaks at 1.5x that — the admission-bound
    shed regime, still under this host's one core; see the module
    docstring's regime note)."""
    from dnn_tpu.workloads.arrivals import bursty_arrivals

    base_hz = AVG_RHO / (svc_p50 * (1.0 + BURST) / 2.0)
    return bursty_arrivals(base_hz, dur_s, seed=TRACE_SEED,
                           burst_factor=BURST,
                           period_s=PERIOD_S), base_hz


def _phase(tmp, *, n_replicas: int, base_port: int, metrics_port: int,
           router_port: int, offsets, dur_s: float, collect) -> dict:
    """Spawn n real replicas + router, replay the trace, return the
    measured outcome plus whatever `collect(router, rset, out)` reads
    off the live lens before teardown. `offsets=None` (phase A) sizes
    the trace from this phase's own service calibration and returns it
    under "offsets" for phase B to replay verbatim."""
    from dnn_tpu import obs
    from dnn_tpu.control.replicaset import ReplicaSet
    from dnn_tpu.control.router import start_router_in_background
    from dnn_tpu.obs.fleet import FleetCollector

    rset = ReplicaSet.spawn_lm_fleet(
        tmp, model=MODEL, base_port=base_port,
        metrics_base_port=metrics_port, roles=["both"] * n_replicas,
        slots=SLOTS, max_len=64, kv="dense",
        ready_deadline_s=READY_DEADLINE_S)
    rset.start()
    router = rstop = srv = fleet2 = None
    try:
        if not rset.wait_serving(n_replicas, READY_DEADLINE_S):
            raise RuntimeError(
                f"{n_replicas} replica(s) never came up")
        router, rstop = start_router_in_background(
            rset, port=router_port, policy="least_queue",
            slots_hint=SLOTS, max_inflight_per_replica=MAX_INFLIGHT,
            default_deadline_s=REQ_TIMEOUT_S + 2.0)
        assert router.caplens is not None, \
            "router built without its lens (obs gated off?)"
        # direct warms pay each child's compile OFF the lens's ring;
        # the one routed warm that follows commits the ledger's first
        # token right after (fast poll — see _warm_direct)
        for h in rset.replicas.values():
            _warm_direct(h.address)
        raddr = f"127.0.0.1:{router_port}"
        _warm_direct(raddr, deadline_s=60.0)
        svc_p50 = _service_p50(f"127.0.0.1:{base_port}")
        base_hz = None
        if offsets is None:
            offsets, base_hz = _offsets_for(svc_p50, dur_s)
        # the router's own obs endpoint, as serve_router wires it —
        # /capz + /fleetz verified against THESE live processes
        srv = obs.serve_metrics(0, status=router.statusz,
                                fleet=rset.collector,
                                caplens=router.caplens)
        fleet2 = FleetCollector(
            {"router": f"http://127.0.0.1:{srv.port}",
             **{h.name: h.obs_url for h in rset.replicas.values()}},
            interval_s=1.0, poll_traces=False).start()
        t0 = time.monotonic()
        gen = _TraceGen(raddr, offsets, t0).run()
        time.sleep(2.5)  # > settle_s: let the ledger finalize + scrape
        out = {"svc_p50_s": svc_p50,
               "availability": _availability(gen.records),
               "wall_p95_s": _wall_p95(gen.records),
               "requests": len(gen.records),
               "completed": sum(1 for r in gen.records
                                if r["outcome"] == "ok"),
               "silently_lost": sum(1 for r in gen.records
                                    if r["outcome"] is None),
               "shed_total": router.shed_total,
               "offsets": offsets}
        if base_hz is not None:
            out["base_rate_hz"] = base_hz
        collect(router, rset, out)
        srv2 = obs.serve_metrics(0, fleet=fleet2,
                                 caplens=router.caplens)
        try:
            _check_surfaces(srv2.port, out)
        finally:
            srv2.close()
        return out
    finally:
        if fleet2 is not None:
            fleet2.close()
        if srv is not None:
            srv.close()
        if rstop is not None:
            rstop()
        rset.stop()


def measure(light: bool = False) -> dict:
    dur_s = 30.0 if light else 60.0
    row: dict = {"model": MODEL, "slots": SLOTS,
                 "max_inflight": MAX_INFLIGHT, "max_new": MAX_NEW,
                 "trace_seed": TRACE_SEED, "trace_s": dur_s,
                 "avg_rho": AVG_RHO, "burst_factor": BURST}

    # ---- phase A: observe 1 replica, predict 2 -----------------------
    lens_a: dict = {}

    def collect_a(router, rset, out):
        lens = router.caplens
        p2 = lens.plan(2, warm=2)
        assert p2 is not None, (
            f"planner refused: ring={len(lens._ring)} "
            f"svc={len(lens._planning_services())}")
        assert lens.plan(2, warm=2) == p2, "replay not deterministic"
        lens_a.update({"plan1": lens.plan(1), "plan2": p2,
                       "plan2_cold": lens.plan(2, warm=1),
                       "coldstart": lens.coldstart(),
                       "wanted": lens.wanted_replicas(n_live=1)})

    with tempfile.TemporaryDirectory(prefix="capplan_a_") as tmp:
        a = _phase(tmp, n_replicas=1, base_port=_A[0],
                   metrics_port=_A[1], router_port=_A_ROUTER,
                   offsets=None, dur_s=dur_s, collect=collect_a)
    offsets = a.pop("offsets")
    row.update({f"single_{k}": v for k, v in a.items()
                if not isinstance(v, dict)})
    row["trace_requests"] = len(offsets)
    p1, p2 = lens_a["plan1"], lens_a["plan2"]
    p2c = lens_a["plan2_cold"]
    row.update({
        "predicted_avail_n1": p1["availability"] if p1 else None,
        "predicted_avail_n2": p2["availability"],
        "predicted_shed_frac_n2": p2["shed_frac"],
        "predicted_wall_p95_n2_s": p2["ttft_p95_s"],
        "predicted_avail_n2_cold":
            p2c["availability"] if p2c else None,
        "predicted_coldstart_debt_s":
            p2c["coldstart_debt_s"] if p2c else None,
        "wanted_replicas_observed": lens_a["wanted"],
    })
    if p1 is not None:
        row["plan1_self_error"] = round(
            abs(p1["availability"] - a["availability"]), 4)

    # ---- phase B: measure the real 2-replica fleet -------------------
    lens_b: dict = {}

    def collect_b(router, rset, out):
        lens = router.caplens
        lens_b.update({"plan2_self": lens.plan(2, warm=2),
                       "coldstart": lens.coldstart()})

    with tempfile.TemporaryDirectory(prefix="capplan_b_") as tmp:
        b = _phase(tmp, n_replicas=2, base_port=_B[0],
                   metrics_port=_B[1], router_port=_B_ROUTER,
                   offsets=offsets, dur_s=dur_s, collect=collect_b)
    b.pop("offsets", None)
    row.update({f"fleet_{k}": v for k, v in b.items()
                if not isinstance(v, dict)})

    # ---- verdicts ----------------------------------------------------
    pred = p2["availability"]
    meas = b["availability"]
    err = abs(pred - meas)
    wall_pred = p2["ttft_p95_s"]
    wall_meas = b["wall_p95_s"]
    wall_ratio = None
    if wall_meas and wall_pred:
        wall_ratio = max(wall_pred, wall_meas) \
            / max(min(wall_pred, wall_meas), 1e-9)
    cs_entries = (lens_a["coldstart"]["entries"]
                  + lens_b["coldstart"]["entries"])
    coverages = [e["coverage"] for e in cs_entries]
    coverage_mean = (sum(coverages) / len(coverages)
                     if coverages else 0.0)
    compile_ok = bool(cs_entries) and all(
        e["buckets"]["compile_s"] > 0.0 for e in cs_entries)
    ok_pred = err <= PRED_ERROR_CEIL
    ok_wall = wall_ratio is not None and wall_ratio <= WAIT_RATIO_BOUND
    ok_cold = coverage_mean >= COLDSTART_COVERAGE_FLOOR and compile_ok
    ok_lost = (a["silently_lost"] == 0 and b["silently_lost"] == 0)
    row.update({
        "measured_avail_n2": meas,
        "value": round(err, 4),  # the ledger ratchet's field
        "prediction_error": round(err, 4),
        "wall_ratio": round(wall_ratio, 3) if wall_ratio else None,
        "coldstart_coverage": round(coverage_mean, 4),
        "coldstart_spawns_finalized": len(cs_entries),
        "coldstart_compile_bucket_ok": compile_ok,
        "coldstart_entries": cs_entries,
        "ok_prediction": bool(ok_pred),
        "ok_wall_ratio": bool(ok_wall),
        "ok_coldstart": bool(ok_cold),
        "ok_no_lost": bool(ok_lost),
        "ok": bool(ok_pred and ok_wall and ok_cold and ok_lost),
        # replica children are pinned to JAX_PLATFORMS=cpu by
        # spawn_lm_fleet (the fleet_serving probe's substrate rule)
        "platform": "cpu",
        "round_substrate": "cpu",
    })
    return row


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when a contract fails "
                         f"(|pred-measured| avail <= {PRED_ERROR_CEIL},"
                         f" wall-p95 ratio <= {WAIT_RATIO_BOUND}, "
                         f"cold-start coverage >= "
                         f"{COLDSTART_COVERAGE_FLOOR} with a nonzero "
                         "compile bucket, zero silent losses)")
    ap.add_argument("--light", action="store_true",
                    help="shortened trace (smoke use; the acceptance "
                         "configuration is the full run)")
    ap.add_argument("--require-substrate", choices=["tpu", "cpu"],
                    default=os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
                    or None,
                    help="fail the row when the probe ran on a "
                         "different substrate "
                         "($DNN_TPU_REQUIRE_SUBSTRATE is the run_all "
                         "spelling)")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    row = measure(light=args.light)
    if args.require_substrate:
        row["required_substrate"] = args.require_substrate
        if row["round_substrate"] != args.require_substrate:
            row["ok"] = False
            row["note"] = (f"required substrate "
                           f"'{args.require_substrate}' but the probe "
                           f"ran on '{row['round_substrate']}'")
    print(json.dumps(row), flush=True)
    if args.do_assert and not row["ok"]:
        print(f"ASSERT FAILED: prediction_error="
              f"{row['prediction_error']} (ceil {PRED_ERROR_CEIL}), "
              f"wall_ratio={row['wall_ratio']} (bound "
              f"{WAIT_RATIO_BOUND}), coldstart_coverage="
              f"{row['coldstart_coverage']} (floor "
              f"{COLDSTART_COVERAGE_FLOOR}, compile_ok="
              f"{row['coldstart_compile_bucket_ok']}), lost="
              f"{row['single_silently_lost']}+"
              f"{row['fleet_silently_lost']}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
