"""Step-timeline probe: the asserted phase-accounting baseline.

ROADMAP item 4 says the post-MBU 85% is serialization; this probe is
the instrument that will judge the overlap/fusion PR — it runs the
STANDARD decode configuration (STUDIES §10/§11, the same 4L/256d shape
and 4 x 120-token greedy rounds `decode_mbu_probe` asserts MBU on) with
the StepClock attached and produces three numbers:

  * **coverage** (ASSERTED >= 95%): the clock's attributed seconds
    (per-phase sums, admit included) over the round's EXTERNALLY
    measured wall clock. Phase marks are contiguous by construction, so
    this is only non-vacuous because the wall is measured OUTSIDE the
    clock: dark time (worker-loop glue, untimed submit segments,
    anything the instrumentation misses) shows up as coverage < 1.
    A decomposition that cannot account for the step wall cannot be
    trusted to attribute it.

  * **host_serialization_fraction** (RECORDED in BASELINE.md, the
    item-4 ratchet): the share of round wall NOT spent inside a decode
    step program — admit (the prefill convoy stalling every decode
    slot), host bookkeeping, commit, obs. Chunked-prefill interleave,
    double-buffered dispatch and fused sampling all push this DOWN;
    the overlap PR must move this number the way ISSUE 6 moved
    `decode_mbu` up.

  * **sync_tax / dispatch_slack**: the per-token device->host sampling
    sync's share of wall, and host work over device time (the headroom
    double-buffered dispatch would exploit).

A second leg (skipped with --light, tolerated on failure) wraps one
round in a real jax.profiler capture (obs/profile.capture_step) and
runs `timeline.analyze()` over the artifact + its sidecar meta: the
DEVICE view of the same steps — per-step device busy, device-overlap
fraction, host-gap histogram — cross-checking the host clock's story
end to end.

Standalone:  python benchmarks/step_timeline_probe.py [--assert]
             (--assert exits 1 when coverage < 95%)
Suite row:   benchmarks/run_all.py config `step_timeline`
             (cpu-runnable).
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: asserted floor: the phase accounting must cover this share of the
#: externally measured round wall (no unattributed dark time). Measured
#: ~98-99% on this host; 95% leaves scheduler-noise headroom without
#: admitting a real instrumentation hole.
COVERAGE_FLOOR = 0.95

SLOTS = 4
NEW_TOKENS = 120
PROMPT = 8


def _build():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    # the §10/§11 standard decode configuration: dense bucketed f32
    cfg = gpt.GPTConfig(block_size=256, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return ContinuousBatcher(cfg, prepared, slots=SLOTS,
                             max_len=cfg.block_size, prompt_pad=16,
                             decode_buckets=True)


def measure(light: bool = False) -> dict:
    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import PHASES, StepClock, analyze

    was = obs.enabled()
    obs.set_enabled(True)
    try:
        srv = _build()
        clock = StepClock(capacity=4096).install()
        srv.step_clock = clock
        new_tokens = 40 if light else NEW_TOKENS

        def round_():
            for i in range(SLOTS):
                srv.submit(np.arange(1, PROMPT + 1), new_tokens, seed=i)
            srv.drain()
            srv.results.clear()
            srv.finish_reasons.clear()

        round_()  # compile + absorb first-dispatch overheads
        base = clock.steps_total
        t0 = time.perf_counter()
        round_()
        wall = time.perf_counter() - t0
        n_steps = clock.steps_total - base
        recs = clock.records()[-n_steps:]
        attributed = sum(r["wall"] for r in recs)
        coverage = attributed / wall
        sums = {p: 0.0 for p in PHASES}
        for r in recs:
            for p, v in r["phases"].items():
                sums[p] = sums.get(p, 0.0) + v
        host_s = sum(sums[p] for p in ("admit", "host", "commit", "obs"))
        device_s = sums["dispatch"] + sums["wait"]
        row = {
            "coverage": round(coverage, 4),
            "wall_s": round(wall, 4),
            "attributed_s": round(attributed, 4),
            "steps": n_steps,
            # ratchet denominators are the EXTERNAL wall, not the
            # attributed seconds: a coverage drop toward the 95% floor
            # must not inflate the ratchet by the uncovered residue
            "host_serialization_fraction": round(host_s / wall, 4),
            "sync_tax_frac": round(sums["wait"] / wall, 4),
            "dispatch_slack": round(host_s / device_s, 4)
            if device_s > 0 else 0.0,
            "phases_ms_per_step": {
                p: round(sums[p] / n_steps * 1e3, 4) for p in PHASES},
            "phases_frac": {
                p: round(sums[p] / attributed, 4) for p in PHASES},
            "slots": SLOTS, "new_tokens": new_tokens,
        }
        if not light:
            # device-view cross-check: one round inside a real capture,
            # analyzed against the sidecar meta + this clock. Tolerated
            # on failure (an unwritable spool or wedged profiler must
            # not fail the asserted host-side contract above).
            try:
                from dnn_tpu.obs.profile import capture_step

                path, _ = capture_step(round_)
                a = analyze(path, clock=clock)
                st = a.get("steps") or {}
                row["capture"] = {
                    "device_busy_frac": a["device"]["busy_frac"],
                    "host_gap_p50_ms": a["host_gaps"]["p50_ms"],
                    "host_gap_total_s": a["host_gaps"]["total_s"],
                    "top_op": a["top_ops"][0]["name"]
                    if a["top_ops"] else None,
                    "aligned_steps": st.get("n_steps"),
                    "mean_step_wall_ms": st.get("mean_wall_ms"),
                    "mean_device_busy_ms": st.get("mean_device_busy_ms"),
                    "device_overlap_frac": st.get("device_overlap_frac"),
                }
            except Exception as e:  # noqa: BLE001 — the capture leg is
                row["capture"] = {"error": str(e)[:200]}  # best-effort
        row["floor"] = COVERAGE_FLOOR
        row["ok"] = bool(coverage >= COVERAGE_FLOOR)
        return row
    finally:
        obs.set_enabled(was)


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure(light="--light" in args)
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: phase accounting covers "
              f"{row['coverage'] * 100:.1f}% of measured wall < "
              f"{COVERAGE_FLOOR * 100:.0f}% floor", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
