"""Step-timeline probe: the asserted phase-accounting + overlap ratchet.

PR 10 built this instrument BEFORE the optimization on purpose: it
measured the decode round at host-serialization fraction 0.549 (admit
convoy ~0.54 of wall, per-token sync tax 0.41) and committed that
number to BASELINE.md as "the ratchet the overlap work must push
down". ISSUE 12 is that work — this probe re-measures the same gauges
with the overlap machinery live and ASSERTS the ratchet.

Workload (both legs identical): the §10/§11 model shape (4L/256d GPT,
dense bucketed f32, 4 slots), WARMED TO STEADY STATE (two full rounds,
so every bucket rung's programs — including the convoy finish and the
mixed-step programs at the top rung — are compiled before the clock
starts; the PR 10 design's single warm round let cold-rung compiles
land in the timed admit path and inflate it), then one timed
ADMISSION-HEAVY round: 16 requests x 24 greedy tokens admitted
continuously into the 4 slots. Short decodes keep admissions flowing —
the workload where the prefill convoy actually binds; the steady-state
convoy leg measures host fraction ~0.55-0.59 on this host, squarely
the committed 0.549-class baseline.

  * **convoy** (report-only): submit() runs the whole prefill inline
    (chunk program + finish + blocking first-token sync), stalling
    every decode slot — the BEFORE leg STUDIES §16 reads.

  * **mixed** (ASSERTED): the ISSUE 12 hot path — interleaved chunked
    prefill (`prefill_chunk_tokens=16`: admission rides the decode
    cadence through the mixed program + fused on-device finish, zero
    per-admit syncs) + double-buffered dispatch (`overlap=True`).
    Asserted: coverage >= 95% of externally measured wall (no
    unattributed dark time) AND host_serialization_fraction <=
    HOST_FRACTION_CEIL (0.40, from the 0.549 baseline).

A capture leg (skipped with --light, tolerated on failure) wraps one
mixed round in a real jax.profiler capture and runs timeline.analyze()
over the artifact + sidecar meta — the device view of the same steps.

Standalone:  python benchmarks/step_timeline_probe.py [--assert]
Suite row:   benchmarks/run_all.py config `step_timeline`
             (cpu-runnable).
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: asserted floor: the phase accounting must cover this share of the
#: externally measured round wall (no unattributed dark time). Measured
#: ~98-99% on this host; 95% leaves scheduler-noise headroom without
#: admitting a real instrumentation hole.
COVERAGE_FLOOR = 0.95

#: asserted ceiling on the MIXED leg's host-serialization fraction —
#: the ISSUE 12 ratchet, down from the PR 10 baseline 0.549. Measured
#: ~0.10-0.17 on this host with interleave+overlap live (the convoy leg
#: re-measures ~0.49-0.59 on the same round); 0.40 is the issue's
#: contracted rung — a regression to the convoy path FAILS with margin.
HOST_FRACTION_CEIL = 0.40

SLOTS = 4
REQUESTS = 16     # timed round: admitted continuously into the 4 slots
NEW_TOKENS = 24   # short decodes keep the admission pressure on
PROMPT = 8


def _build(mixed: bool):
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    # the §10/§11 standard decode configuration: dense bucketed f32.
    # The mixed leg adds ONLY the ISSUE 12 knobs, so the delta between
    # the legs is the overlap machinery and nothing else.
    cfg = gpt.GPTConfig(block_size=256, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    kw = {}
    if mixed:
        kw = {"prefill_chunk_tokens": 16, "overlap": True}
    return ContinuousBatcher(cfg, prepared, slots=SLOTS,
                             max_len=cfg.block_size, prompt_pad=16,
                             decode_buckets=True, **kw)


def _leg(mixed: bool, n_requests: int, new_tokens: int) -> tuple:
    """One measured leg -> (leg row dict, clock, round_ callable)."""
    import numpy as np

    from dnn_tpu.obs.timeline import PHASES, StepClock

    srv = _build(mixed)
    clock = StepClock(capacity=8192).install()
    srv.step_clock = clock

    def round_(n_req=n_requests):
        for i in range(n_req):
            while srv.free_slots() == 0:
                srv.step()
            srv.submit(np.arange(1, PROMPT + 1), new_tokens, seed=i)
        srv.drain()
        srv.results.clear()
        srv.finish_reasons.clear()

    # steady state: two warm rounds — the first grows the bucket ladder,
    # the second compiles the admission programs at the grown rungs
    # (convoy finish / mixed+fused finish alike), so the timed round
    # measures serving, not one-time compiles
    round_(SLOTS)
    round_(SLOTS)
    base = clock.steps_total
    t0 = time.perf_counter()
    round_()
    wall = time.perf_counter() - t0
    n_steps = clock.steps_total - base
    recs = clock.records()[-n_steps:]
    attributed = sum(r["wall"] for r in recs)
    coverage = attributed / wall
    sums = {p: 0.0 for p in PHASES}
    for r in recs:
        for p, v in r["phases"].items():
            sums[p] = sums.get(p, 0.0) + v
    host_s = sum(sums[p] for p in ("admit", "host", "commit", "obs"))
    device_s = sums["dispatch"] + sums["wait"]
    tokens = n_requests * new_tokens
    leg = {
        "coverage": round(coverage, 4),
        "wall_s": round(wall, 4),
        "attributed_s": round(attributed, 4),
        "steps": n_steps,
        "mixed_steps": sum(1 for r in recs if r.get("mixed")),
        "tokens_per_sec": round(tokens / wall, 1),
        # ratchet denominators are the EXTERNAL wall, not the
        # attributed seconds: a coverage drop toward the 95% floor
        # must not deflate the ratchet by the uncovered residue
        "host_serialization_fraction": round(host_s / wall, 4),
        "sync_tax_frac": round(sums["wait"] / wall, 4),
        "dispatch_slack": round(host_s / device_s, 4)
        if device_s > 0 else 0.0,
        "phases_ms_per_step": {
            p: round(sums[p] / n_steps * 1e3, 4) for p in PHASES},
        "phases_frac": {
            p: round(sums[p] / attributed, 4) for p in PHASES},
    }
    return leg, clock, round_


def measure(light: bool = False) -> dict:
    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import analyze

    was = obs.enabled()
    obs.set_enabled(True)
    try:
        n_req = 8 if light else REQUESTS
        new_tokens = 12 if light else NEW_TOKENS
        mixed, clock, round_ = _leg(mixed=True, n_requests=n_req,
                                    new_tokens=new_tokens)
        row = dict(mixed)
        row.update({
            "slots": SLOTS, "requests": n_req, "new_tokens": new_tokens,
            "leg": "interleaved prefill (chunk=16) + overlap, dense "
                   "bucketed f32 (the s10 shape + the ISSUE 12 knobs)",
            "baseline_host_fraction": 0.549,  # PR 10, BASELINE.md
        })
        if not light:
            convoy, _, _ = _leg(mixed=False, n_requests=n_req,
                                new_tokens=new_tokens)
            row["convoy"] = convoy
            row["speedup_vs_convoy"] = round(
                convoy["wall_s"] / mixed["wall_s"], 3)
            # device-view cross-check: one MIXED round inside a real
            # capture, analyzed against the sidecar meta + this clock.
            # Tolerated on failure (an unwritable spool or wedged
            # profiler must not fail the asserted host-side contract).
            try:
                from dnn_tpu.obs.profile import capture_step

                path, _ = capture_step(round_)
                a = analyze(path, clock=clock)
                st = a.get("steps") or {}
                row["capture"] = {
                    "device_busy_frac": a["device"]["busy_frac"],
                    "host_gap_p50_ms": a["host_gaps"]["p50_ms"],
                    "host_gap_total_s": a["host_gaps"]["total_s"],
                    "top_op": a["top_ops"][0]["name"]
                    if a["top_ops"] else None,
                    "aligned_steps": st.get("n_steps"),
                    "mean_step_wall_ms": st.get("mean_wall_ms"),
                    "mean_device_busy_ms": st.get("mean_device_busy_ms"),
                    "device_overlap_frac": st.get("device_overlap_frac"),
                }
            except Exception as e:  # noqa: BLE001 — the capture leg is
                row["capture"] = {"error": str(e)[:200]}  # best-effort
        row["floor"] = COVERAGE_FLOOR
        row["host_fraction_ceil"] = HOST_FRACTION_CEIL
        row["ok_coverage"] = bool(mixed["coverage"] >= COVERAGE_FLOOR)
        row["ok_host_fraction"] = bool(
            mixed["host_serialization_fraction"] <= HOST_FRACTION_CEIL)
        row["ok"] = row["ok_coverage"] and row["ok_host_fraction"]
        return row
    finally:
        obs.set_enabled(was)


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure(light="--light" in args)
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: coverage {row['coverage'] * 100:.1f}% "
              f"(floor {COVERAGE_FLOOR * 100:.0f}%), host fraction "
              f"{row['host_serialization_fraction']:.3f} "
              f"(ceil {HOST_FRACTION_CEIL:.2f})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
