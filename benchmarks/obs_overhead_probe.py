"""Observability overhead probe: instrumented vs DNN_TPU_OBS=off decode.

The obs layer promises near-zero tax on the hot serving path (ISSUE 3
satellite: < 2% on a decode step). This probe measures it honestly:

  * one ContinuousBatcher, pool kept full of TRACED requests (the
    worst-case instrumented path: per-step metrics + span bookkeeping);
  * PER-STEP interleave, PAIRED estimator: the gate alternates on
    EVERY step in ABBA order (on,off,off,on,...), each step is timed
    individually, and the verdict is the MEDIAN OF PER-PAIR
    DIFFERENCES over the median off-step — not a comparison of the
    two populations' medians. This is the fourth methodology this
    probe went through, each graduation forced by a measured artifact
    — (1) few multi-step leg pairs read "39%" of pure scheduler
    noise; (2) leg-level A/B let request retirements phase-lock with
    the leg cadence, parking cheap empty-pool steps in one population
    (a reproducible ~20% phantom); (3) even retirement-safe,
    position-balanced legs swung ±10% between IDENTICAL-work legs on
    this host; (4) population MEDIANS themselves swung ±1.5% between
    identical-work runs on a single-core VM under bursty ambient load
    — a level shift mid-run moves the two order statistics unequally.
    A paired difference subtracts the shift sample-by-sample (the two
    halves of a pair run milliseconds apart, under the same burst),
    the ABBA order cancels within-pair drift direction, and the
    median of differences kills the outlier pairs;
  * the gate flips at RUNTIME (obs.set_enabled) — producers re-check
    per call, so an OFF step runs the identical code path with every
    metric/span site degraded to its one-None-check form;
  * the obs v2 surface is in the loop too: a live watchdog heartbeat
    (both populations — the worker beats regardless of the gate). The
    flight recorder is priced where production actually calls it — per
    admission/retirement — by the kvtier/kvlens ADMISSION legs below;
    an earlier revision also fired a synthetic per-step flight event
    inside this loop, but that synthetic event is probe scaffolding,
    not serving instrumentation, and at today's ~2.5 ms step it alone
    billed ~0.2% — the contract bounds the serving stack's tax, so the
    scaffolding left the timed window;
  * interleaved admission (ISSUE 12, `prefill_chunk_tokens`) is LIVE:
    each refill enqueues its prompts and the first timed steps after it
    are MIXED steps (decode + folded prefill chunk + fused finish), so
    the overhaul's new hot path — including the deferred first-token
    commits — is priced under the same contract;
  * the step-timeline clock (ISSUE 11, obs/timeline.StepClock) is
    attached for BOTH populations the way the LM daemon attaches it:
    the ON population pays the full phase-mark + end-of-step
    histogram/gauge bill, the OFF population its one-gate-check
    degradation — so the new instrumentation is re-priced under the
    same contract, not presumed free;
  * timed steps only ever advance a FULL pool: the pool refills
    (untimed) before a request's budget could retire it mid-sequence,
    and every step syncs on the committed tokens (step() pulls
    self.tok to host), so wall time is device-honest.

Standalone:  python benchmarks/obs_overhead_probe.py [--assert]
             (--assert exits 1 when overhead >= 2%)
             --fleet adds the PR-5 surface to the loop (see below)
Suite row:   benchmarks/run_all.py configs `obs_overhead` and
             `fleet_overhead` (both cpu-runnable).

The `--fleet` variant (measure_fleet) prices the fleet-era additions on
the same per-step interleave: a GoodputTracker on the pool (per-step
MFU/MBU/SLO window updates — the marginal cost under test) AND a live
FleetCollector polling this process's real /metrics + /statusz +
/trace.jsonl endpoint at a 200 ms period in the background of BOTH
populations (the poller is a separate process in production; running it
in-process here puts its scrape-time gauge reads and GIL share INSIDE
the timed window, bounding the deployed configuration from above).
"""

from __future__ import annotations

import json
import os
import sys
import time

# script lives in benchmarks/; import dnn_tpu from the repo root
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

STEPS = 3000  # timed steps PER population (on/off alternate step-wise)
# (3000 pairs: the per-pair diff spread on this class of host is
# ~250 us sigma, so the median's standard error is ~6 us — small
# against the ~50 us signal; 1500 pairs left +-8-10 us between
# identical runs, a coin flip against a 2% ceiling)
SLOTS = 4
PROMPT = 8


def _abba_on(i: int) -> bool:
    """Gate schedule for sample i: ON,OFF,OFF,ON,ON,OFF,OFF,... —
    adjacent pairs (2k, 2k+1) always hold one ON and one OFF sample,
    in alternating order, so paired differencing cancels both ambient
    level shifts and within-pair drift direction."""
    return i % 4 in (0, 3)


def _paired_overhead(seq):
    """`seq` = [(on, wall_seconds), ...] in sample order, ABBA-gated.
    Returns (overhead_frac, med_on, med_off) where overhead_frac is
    the median of per-pair (on − off) differences over the median off
    wall — the burst-robust estimator the module docstring's
    methodology note (4) motivates."""
    on_t = sorted(dt for on, dt in seq if on)
    off_t = sorted(dt for on, dt in seq if not on)
    diffs = []
    for k in range(0, len(seq) - 1, 2):
        (a_on, a), (_b_on, b) = seq[k], seq[k + 1]
        diffs.append((a - b) if a_on else (b - a))
    diffs.sort()
    med_diff = diffs[len(diffs) // 2]
    med_off = off_t[len(off_t) // 2]
    return med_diff / med_off, on_t[len(on_t) // 2], med_off


def _build():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    # 4L/256d: a ~2-3 ms CPU decode step. Deliberately NOT the tiniest
    # test preset — at 0.6 ms/step the comparison measures icache/branch
    # noise (±5% between IDENTICAL legs), and no real serving config
    # steps that fast; this size keeps the probe honest AND cpu-cheap.
    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    # the ISSUE 12 hot path is what the daemon now serves, so the obs
    # tax is priced on it: interleaved admissions (prefill_chunk_tokens)
    # mean every refill's prompts fold into the first timed steps after
    # it as MIXED steps — the new program shape rides the same <2%
    # contract. Constrained decoding (ISSUE 16) is live too: one slot
    # per refill carries a grammar, so the timed loop prices the
    # on-device DFA walk plus the constrained_slots gauge pushes under
    # the same budget. overlap stays off here: the per-step A/B gate
    # flip needs each timed step's work attributable to that step.
    return ContinuousBatcher(cfg, prepared, slots=SLOTS,
                             max_len=cfg.block_size, prompt_pad=16,
                             prefill_chunk_tokens=16,
                             allow_constraints=True, constraint_rows=8)


_CONSTRAINT = None


def _digit_constraint(vocab_size):
    """Compile-once [0-9]+ grammar over the probe's byte vocab (no eos
    on this server, so constrained requests run to budget like every
    other slot — the refill cadence is unchanged)."""
    global _CONSTRAINT
    if _CONSTRAINT is None:
        from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab

        _CONSTRAINT = TokenConstraint.from_regex(
            r"[0-9]+", byte_vocab(vocab_size))
    return _CONSTRAINT


def _fill(srv, traced: bool):
    """Fill every free slot; traced legs parent each request's spans
    under a throwaway root (the served path's shape). The FIRST
    admission of every refill is constrained (ISSUE 16): the timed
    steps gather its mask row and walk its DFA on device, and the
    commit path runs the host finish-detection mirror — the
    constrained hot path priced under the same <2% obs contract."""
    import numpy as np

    from dnn_tpu import obs

    roots = []
    first = True
    while srv.free_slots():
        root = obs.start_span("bench.request") if traced else None
        srv.submit(np.arange(1, PROMPT + 1), srv.max_len - PROMPT - 1,
                   trace=root,
                   constraint=_digit_constraint(srv.cfg.vocab_size)
                   if first else None)
        first = False
        if root is not None:
            roots.append(root)
    return roots


def _drain_slots(srv, roots):
    for req in list(srv._slot_req):
        if req is not None:
            srv.cancel(req["rid"])
    for r in roots:
        r.end()
    srv.results.clear()
    srv.finish_reasons.clear()


def measure_fleet() -> dict:
    """obs_overhead with the fleet-era surface live: goodput tracking on
    every step + a FleetCollector polling this process's own endpoint
    throughout the timed loop. Same per-step interleave, same <2%
    contract — the poller runs in both populations (it polls regardless
    of the producer gate), the goodput feeds only in the ON one."""
    from dnn_tpu import obs
    from dnn_tpu.obs.fleet import FleetCollector
    from dnn_tpu.obs.goodput import GoodputTracker, SLOConfig, model_cost

    srv = _build()
    # explicit peaks: utilization gauges must COMPUTE on this CPU host
    # (scrapes read them), not short-circuit to 0 — price the real path
    tracker = GoodputTracker(
        model_cost(srv.cfg), peak_flops=1e12, peak_bytes=1e10,
        slo=SLOConfig(inter_token_s=0.001, availability=0.999)).install()
    srv.goodput = tracker
    endpoint = obs.serve_metrics(0)
    fleet = FleetCollector(
        {"self": f"http://127.0.0.1:{endpoint.port}"},
        interval_s=0.2).start()
    try:
        row = _measure_steps(srv)
    finally:
        fleet.close()
        endpoint.close()
    row["fleet_poll_count"] = fleet._polls
    row["mfu_live"] = round(tracker.mfu(), 6)
    row["mbu_live"] = round(tracker.mbu(), 6)
    return row


def measure() -> dict:
    srv = _build()
    return _measure_steps(srv)


def measure_kvtier() -> dict:
    """obs tax on the RADIX ADMISSION path (dnn_tpu/kvtier, ISSUE 15):
    with kv=paged + prefix_cache the per-admission bill now includes
    the radix lookup plus its obs-gated block-granular counters
    (prefix_blocks_reused / kvtier remote hits) and the kvtier gauges
    in the one bulk update. This leg alternates the gate per ADMISSION
    (submit of a store-resident prompt + cancel, the full-hit regime —
    the worst counter-to-work ratio: near-zero prefill compute, full
    obs bill) and holds the SAME <2% contract on the admission wall.
    The lookup itself runs in both populations (it is serving work,
    not obs work); the delta is exactly the observability tax."""
    import jax
    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=SLOTS,
                            max_len=cfg.block_size, prompt_pad=16,
                            kv="paged", block_len=16, prefix_cache=64)
    prompt = np.arange(1, 33)  # 2 full blocks: a block-aligned FULL
    # hit after the seeding admission (zero chunks, stored logit row)
    was = obs.enabled()
    obs.set_enabled(True)
    rid = srv.submit(prompt, 2)  # seed the store (+ compile programs)
    srv.drain()
    srv.claim(rid)
    n = 600
    seq = []
    try:
        for i in range(2 * n):
            on = _abba_on(i)
            obs.set_enabled(on)
            t0 = time.perf_counter()
            r = srv.submit(prompt, 2)
            dt = time.perf_counter() - t0
            seq.append((on, dt))
            srv.cancel(r)
    finally:
        obs.set_enabled(was)
    overhead, med_on, med_off = _paired_overhead(seq)
    return {
        "kvtier_admit_overhead_frac": overhead,
        "kvtier_admit_ms_on": round(med_on * 1e3, 4),
        "kvtier_admit_ms_off": round(med_off * 1e3, 4),
        "kvtier_admissions_per_population": n,
        "kvtier_resident_blocks": srv._prefix_store.n_blocks,
    }


def measure_kvlens() -> dict:
    """obs tax on the admission path WITH THE KVLENS TRACKER LIVE
    (ISSUE 18): the batcher is built under the gate so the reuse-
    distance lens attaches to the prefix store, then the gate
    alternates per admission over a VARIED working set (8 distinct
    2-block prompts) so every ON admission pays the full kvlens bill —
    blake2s chunk digests, the SHARDS sampling test, LRU-stack search
    + reorder, thrash-ledger lookups — while every OFF admission pays
    only the gate check inside the lens hooks. Same <2% contract on
    the admission wall as the kvtier leg; the receipts prove the lens
    really sampled (it is easy to be cheap by doing nothing)."""
    import jax
    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    was = obs.enabled()
    obs.set_enabled(True)  # BEFORE construction: the lens attaches at
    # build time only when the gate is up (overhead contract: gate-off
    # processes carry no lens at all)
    # explicit paged_blocks: the auto-sized pool (slots x rows + 1 =
    # 17 blocks) is SMALLER than the 16-block working set plus the
    # in-flight request, so every "re-admission" would secretly be a
    # prefill + insert + evict round — a different regime with a
    # different denominator. 64 + headroom keeps all 8 prompts
    # store-resident: the full-hit regime the kvtier leg prices.
    srv = ContinuousBatcher(cfg, prepared, slots=SLOTS,
                            max_len=cfg.block_size, prompt_pad=16,
                            kv="paged", block_len=16,
                            paged_blocks=64 + SLOTS * 4 + 1,
                            prefix_cache=64)
    assert srv._kvlens is not None, "lens did not attach"
    # 8 distinct 2-block prompts: enough variety that on_access walks a
    # populated LRU stack (the expensive path), small enough that every
    # prompt stays store-resident (full-hit admissions — the worst
    # counter-to-work ratio, as in the kvtier leg)
    prompts = [np.arange(1, 33) + 40 * k for k in range(8)]
    for p in prompts:  # seed the store (+ compile programs)
        rid = srv.submit(p, 2)
        srv.drain()
        srv.claim(rid)
    n = 600
    seq = []
    try:
        for i in range(2 * n):
            on = _abba_on(i)
            obs.set_enabled(on)
            # pair-constant prompt: both halves of pair (2k, 2k+1)
            # admit the SAME prompt, so the paired difference never
            # mixes two store paths (different resident depths admit
            # at measurably different walls)
            p = prompts[(i // 2) % len(prompts)]
            t0 = time.perf_counter()
            r = srv.submit(p, 2)
            dt = time.perf_counter() - t0
            seq.append((on, dt))
            srv.cancel(r)
    finally:
        obs.set_enabled(was)
    overhead, med_on, med_off = _paired_overhead(seq)
    lens = srv._kvlens
    return {
        "kvlens_admit_overhead_frac": overhead,
        "kvlens_admit_ms_on": round(med_on * 1e3, 4),
        "kvlens_admit_ms_off": round(med_off * 1e3, 4),
        "kvlens_admissions_per_population": n,
        # receipts: the ON population really exercised the tracker
        "kvlens_accesses": lens.accesses,
        "kvlens_sampled": lens.sampled,
        "kvlens_measured_hit_ratio": round(lens.measured_hit_ratio(), 4),
    }


def measure_caplens() -> dict:
    """obs tax on the PER-REQUEST path WITH THE CAPLENS LIVE (ISSUE
    20): a Router built under the gate attaches its capacity
    observatory, then the gate alternates per request over the full
    producer seam — `on_arrival` (ring append + scenario tally), the
    real `_admit` decision (policy pick over live views), the
    replica-side admission as the serving work in the window (the
    kvtier leg's store-resident full-hit submit — the CHEAPEST real
    per-request serving wall, so the fraction is an upper bound on
    deployed configs whose wall also holds an RPC + decode), and
    `on_commit` with the measured submit wall (free-slot reservoir
    push + tokens/s EMA + ledger first-token stamp — the worst-case
    commit). OFF requests run the identical path with every obs site
    degraded to its gate check, so the delta is the TOTAL obs bill on
    this wall — the kvtier counters it already carried plus the new
    caplens hooks; the contract is that the new lens keeps the
    combined tax under the same <2%. Planning/windowing stay
    scrape-side and never enter the timed window (that is the design
    claim this leg enforces). No network: one forced-serving handle
    on an unstarted ReplicaSet — nothing here waits on a socket."""
    import jax
    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import Router
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    was = obs.enabled()
    obs.set_enabled(True)  # BEFORE construction: the router attaches
    # its lens only when the gate is up (gate-off routers carry none)
    h = ReplicaHandle("r0", "127.0.0.1:1", role="both")
    h.state = "serving"
    h.t_spawn = time.monotonic() - 1.0
    h.t_ready = time.monotonic()
    rset = ReplicaSet([h], scrape=False)  # never started: no monitor
    router = Router(rset, policy="round_robin", disagg="off",
                    kvtier="off", slots_hint=SLOTS,
                    max_inflight_per_replica=2 * SLOTS)
    assert router.caplens is not None, "lens did not attach"
    lens = router.caplens
    router.start()
    # the serving work: the kvtier leg's admission regime (paged KV,
    # block-aligned store-resident prompt => full hit, near-zero
    # prefill compute — the worst counter-to-work ratio)
    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=SLOTS,
                            max_len=cfg.block_size, prompt_pad=16,
                            kv="paged", block_len=16, prefix_cache=64)
    prompt = np.arange(1, 33)
    rid = srv.submit(prompt, 2)  # seed the store (+ compile programs)
    srv.drain()
    srv.claim(rid)
    # 1200 pairs, double the kvtier/kvlens legs: the lens bill here is
    # single-digit microseconds against a ~9 ms wall, so the pair-diff
    # noise needs the larger population to keep the estimate stable
    # (600-pair runs scattered 1.5-2.0% around the same code)
    n = 1200
    seq = []
    try:
        for i in range(2 * n):
            on = _abba_on(i)
            obs.set_enabled(on)
            t0 = time.perf_counter()
            lens.on_arrival(len(prompt), scenario="gen")
            target = router._admit("decode", None, set())
            r = srv.submit(prompt, 2)
            t1 = time.perf_counter()
            lens.on_commit(target.name, role=target.role, tokens=2,
                           wall_s=t1 - t0, inflight_at_dispatch=0)
            dt = time.perf_counter() - t0
            seq.append((on, dt))
            srv.cancel(r)
    finally:
        obs.set_enabled(was)
    overhead, med_on, med_off = _paired_overhead(seq)
    return {
        "caplens_admit_overhead_frac": overhead,
        "caplens_admit_ms_on": round(med_on * 1e3, 4),
        "caplens_admit_ms_off": round(med_off * 1e3, 4),
        "caplens_admissions_per_population": n,
        # receipts: the ON population really fed the observatory
        "caplens_arrivals": lens.arrivals_total,
        "caplens_commits": lens.commits_total,
        "caplens_service_samples": len(lens._planning_services()),
    }


def _measure_steps(srv) -> dict:
    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import StepClock
    from dnn_tpu.obs.watchdog import Watchdog

    was = obs.enabled()
    obs.set_enabled(True)
    # step-timeline clock ON (ISSUE 11): the per-phase StepClock rides
    # the timed loop exactly as the LM daemon attaches it, so the new
    # instrumentation is priced inside the same <2% contract — in the
    # OFF population begin() short-circuits on the gate (one enabled()
    # check), in the ON population every phase mark + the end-of-step
    # bulk registry update (histograms + gauges) is in the bill
    srv.step_clock = StepClock().install()
    # v2 surface rides along in the timed loop: a live watchdog (no
    # device probe — its subprocess would inject real load; the
    # per-step cost under test is the heartbeat). The beat itself is
    # gate-independent (the worker beats regardless of DNN_TPU_OBS)
    # and runs in BOTH populations, so it cancels in the pairing; the
    # flight recorder is priced where production actually fires it —
    # per admission/retirement — by the kvtier/kvlens legs.
    wd = Watchdog(period_s=5.0, device_probe=None).start()
    roots = _fill(srv, traced=True)
    left = srv.max_len - PROMPT - 2  # decode steps before any retire
    for _ in range(10):  # compile + absorb first-dispatch overheads
        srv.step()
    left -= 10
    seq = []
    try:
        for i in range(2 * STEPS):
            if left < 1:
                # refill OUTSIDE the timed steps, before any request's
                # budget could retire it mid-sequence (empty/partial
                # pools step cheaper and would bias whichever
                # population they land in)
                obs.set_enabled(True)
                _drain_slots(srv, roots)
                roots = _fill(srv, traced=True)
                left = srv.max_len - PROMPT - 2
                srv.step()  # settle dispatch after the refill
                left -= 1
            on = _abba_on(i)
            obs.set_enabled(on)
            t0 = time.perf_counter()
            wd.beat()
            srv.step()
            seq.append((on, time.perf_counter() - t0))
            left -= 1
    finally:
        obs.set_enabled(was)
        wd.close()
    overhead, med_on, med_off = _paired_overhead(seq)
    on_t = sorted(dt for on, dt in seq if on)
    off_t = sorted(dt for on, dt in seq if not on)
    return {
        "overhead_frac": overhead,
        "step_ms_on": round(med_on * 1e3, 4),
        "step_ms_off": round(med_off * 1e3, 4),
        # per-population spread (p10..p90), the noise the medians tame
        "step_ms_on_p10_p90": [round(on_t[len(on_t) // 10] * 1e3, 4),
                               round(on_t[-1 - len(on_t) // 10] * 1e3, 4)],
        "step_ms_off_p10_p90": [round(off_t[len(off_t) // 10] * 1e3, 4),
                                round(off_t[-1 - len(off_t) // 10] * 1e3,
                                      4)],
        "steps_per_population": STEPS, "slots": SLOTS,
        # ISSUE 16 receipt: the timed loop really carried a grammar
        # (the StepClock gauge the /stepz scrape now exports)
        "constrained_slots_live": srv._n_constrained,
    }


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    if "--kvlens" in args:
        row = measure_kvlens()
        row["ok"] = row["kvlens_admit_overhead_frac"] < 0.02
        print(json.dumps(row), flush=True)
        if "--assert" in args and not row["ok"]:
            print(f"FAIL: kvlens admission obs overhead "
                  f"{row['kvlens_admit_overhead_frac'] * 100:.2f}% "
                  f">= 2% budget", file=sys.stderr)
            return 1
        return 0
    if "--caplens" in args:
        row = measure_caplens()
        row["ok"] = row["caplens_admit_overhead_frac"] < 0.02
        print(json.dumps(row), flush=True)
        if "--assert" in args and not row["ok"]:
            print(f"FAIL: caplens admission obs overhead "
                  f"{row['caplens_admit_overhead_frac'] * 100:.2f}% "
                  f">= 2% budget", file=sys.stderr)
            return 1
        return 0
    if "--kvtier" in args:
        row = measure_kvtier()
        row["ok"] = row["kvtier_admit_overhead_frac"] < 0.02
        print(json.dumps(row), flush=True)
        if "--assert" in args and not row["ok"]:
            print(f"FAIL: kvtier admission obs overhead "
                  f"{row['kvtier_admit_overhead_frac'] * 100:.2f}% "
                  f">= 2% budget", file=sys.stderr)
            return 1
        return 0
    row = measure_fleet() if "--fleet" in args else measure()
    row["ok"] = row["overhead_frac"] < 0.02
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: observability overhead "
              f"{row['overhead_frac'] * 100:.2f}% >= 2% budget",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
