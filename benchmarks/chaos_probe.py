"""chaos_probe: availability + p99 TTFT under injected stage failures.

ISSUE 8's regression contract — resilience as an asserted number, the
way decode_mbu asserts MBU and relay_transport asserts the bubble drop.
Open-loop load runs against a REAL 2-stage pipeline (two stage-server
subprocesses, the STUDIES §10 deployment) while the STANDARD FaultPlan
(chaos.plan.standard_plan: one stage KILL + one injected WEDGE) fires,
and each stage runs under a `chaos.supervisor.Supervisor` — the thing
being measured is the recovery machinery, end to end:

  * the kill (SIGKILL on node2) exercises exit-detection + backoff
    restart + re-warm;
  * the wedge (SIGSTOP on node1 — alive but unresponsive, the hung-
    driver shape) exercises the fresh-connection health poll, the
    wedged declaration, and the on_wedged=restart policy;
  * the probe's client runs the ISSUE-8 edge stack: circuit breaker
    (fast explicit shedding during the outage), fresh-channel rebuild,
    propagated deadlines.

Asserted floors (--assert exits nonzero when any fails):

  * availability: >= AVAILABILITY_FLOOR (99%) of submitted requests
    COMPLETED-OR-EXPLICITLY-REJECTED, and ZERO silently lost — every
    request's outcome is accounted for;
  * p99 TTFT during recovery (completed requests in the
    POST_RECOVERY_WINDOW_S after each supervisor_restart event — the
    "is it really back, warm, at quiet latency?" check) <=
    TTFT_RATIO_CEIL (10x) the quiet-window p99. The pipeline is unary,
    so request latency IS TTFT;
  * event pairing: every injected fault (chaos_inject kill_stage /
    hang_stage) pairs with its recovery (supervisor_restart for the
    same stage, later ts) IN THE DUMPED RING — the incident must be
    reconstructable from the flight recorder alone, so the assertion
    reads the dump file back, not in-process state.

`python -m benchmarks.chaos_probe [--assert] [--light]` prints one
JSON row; the full (default) run sustains >= 60 s of open-loop load —
the acceptance configuration. --light shrinks the timeline for smoke
use. The run_all `chaos_resilience` row rides `measure()`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

AVAILABILITY_FLOOR = 0.99   # handled (ok or explicit) / submitted
TTFT_RATIO_CEIL = 10.0      # recovery-window p99 vs quiet p99
POST_RECOVERY_WINDOW_S = 10.0
RECOVERY_DEADLINE_S = 150.0  # per fault: child restart incl. jax import
# loop-lag sanitizer bound (analysis/sanitize.py): the stage children
# run with DNN_TPU_LOOP_SANITIZE=1 and this probe asserts, from each
# surviving stage's served /debugz, that no event-loop callback held
# the loop longer than this. The bound tolerates first-compile GIL
# stalls on a loaded CI host; a reintroduced blocking-primitive wait
# (the ShmRing.write deadlock held the loop its full 30 s timeout)
# blows straight through it — the dynamic backstop for indirections
# the CON001 AST rule can't see.
LOOP_LAG_BOUND_MS = 5000.0

# (grpc1, grpc2, metrics1, metrics2) — distinct from the relay probe's
_PORTS = (59495, 59496, 59595, 59596)

_CHILD_SRC = """
import asyncio, sys
sys.path.insert(0, {repo!r})
from dnn_tpu.config import TopologyConfig
from dnn_tpu.runtime.engine import PipelineEngine
from dnn_tpu.comm.service import serve_stage

cfg = TopologyConfig.from_dict({cfg!r})
engine = PipelineEngine(cfg)
asyncio.run(serve_stage(engine, {node_id!r}, metrics_port={mport},
                        transport="grpc"))
"""


def _pipeline_config(p1: int, p2: int) -> dict:
    return {
        "nodes": [
            {"id": "node1", "address": f"127.0.0.1:{p1}", "part_index": 0},
            {"id": "node2", "address": f"127.0.0.1:{p2}", "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
        "device_type": "cpu",
    }


def _spawner(tmpdir: str, cfg: dict, node_id: str, mport: int):
    script = os.path.join(tmpdir, f"chaos_stage_{node_id}.py")
    with open(script, "w") as f:
        f.write(_CHILD_SRC.format(repo=REPO, cfg=cfg, node_id=node_id,
                                  mport=mport))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DNN_TPU_LOOP_SANITIZE="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)

    def spawn():
        return subprocess.Popen([sys.executable, script], env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    return spawn


def _p99(lat):
    lat = sorted(lat)
    return lat[int(0.99 * (len(lat) - 1))] if lat else None


def _wait_stage_up(port: int, deadline_s: float = 150.0) -> bool:
    from dnn_tpu.comm.client import NodeClient

    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        probe = NodeClient(f"127.0.0.1:{port}", breaker=False,
                           transport="grpc")
        try:
            if probe.health_check(timeout=2.0):
                return True
        finally:
            probe.close()
        time.sleep(0.5)
    return False


class _LoadGen:
    """Open-loop load: one request every 1/rate seconds, regardless of
    outcomes (the arrival process never waits on the system under
    test). Every request records exactly one outcome — ok / rejected —
    or stays None (silently lost: the thing the probe asserts cannot
    happen)."""

    def __init__(self, client, x, rate_hz: float, req_timeout_s: float,
                 t0: float):
        self.client = client
        self.x = x
        self.rate = rate_hz
        self.req_timeout = req_timeout_s
        self.t0 = t0
        self.records: list = []
        self._threads: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self, join_timeout: float):
        self._stop.set()
        self._thread.join(timeout=5)
        t_end = time.monotonic() + join_timeout
        for t in self._threads:
            t.join(timeout=max(t_end - time.monotonic(), 0.1))

    def _one(self, rec):
        try:
            status, result = self.client.send_tensor(
                self.x, request_id=f"chaos{rec['i']}",
                timeout=self.req_timeout, retries=0)
            rec["outcome"] = "ok" if result is not None else "rejected"
            if result is None:
                rec["error"] = status[:120]
        except Exception as e:  # noqa: BLE001 — EXPLICIT rejection:
            # breaker open, UNAVAILABLE, DEADLINE — all accounted
            rec["outcome"] = "rejected"
            rec["error"] = f"{type(e).__name__}: {e}"[:120]
        finally:
            rec["t_done"] = time.monotonic() - self.t0
            rec["latency"] = rec["t_done"] - rec["t"]

    def _run(self):
        i = 0
        next_at = time.monotonic()
        while not self._stop.is_set():
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, 0.05))
                continue
            next_at += 1.0 / self.rate
            rec = {"i": i, "t": now - self.t0, "outcome": None}
            self.records.append(rec)
            t = threading.Thread(target=self._one, args=(rec,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
            i += 1


def _await_recovery(flight_rec, stage: str, after_ts: float,
                    deadline_s: float):
    """Block until a supervisor_restart event for `stage` lands with
    ts > after_ts; returns the event or None on deadline."""
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        for ev in flight_rec.events(kind="supervisor_restart"):
            if ev.get("stage") == stage and ev["ts"] > after_ts:
                return ev
        time.sleep(0.5)
    return None


def measure(light: bool = False) -> dict:
    from dnn_tpu import obs
    from dnn_tpu.chaos.plan import standard_plan
    from dnn_tpu.chaos.supervisor import Supervisor
    from dnn_tpu.comm.client import CircuitBreaker, NodeClient
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    p1, p2, m1, m2 = _PORTS
    cfg = _pipeline_config(p1, p2)
    rate_hz = 6.0 if light else 8.0
    req_timeout = 5.0
    kill_at = 8.0 if light else 15.0
    hang_at = 16.0 if light else 40.0
    wedge_gap = 4.0 if light else 6.0   # after kill-recovery
    post_w = 6.0 if light else POST_RECOVERY_WINDOW_S
    plan = standard_plan(kill_at_s=kill_at, hang_at_s=hang_at)
    flight_rec = obs.flight.recorder()

    def warm_fn(deadline_s: float = 45.0):
        # recovery is declared only when a REAL request round-trips the
        # pipeline again — polled: a relayed error STATUS (downstream
        # gRPC socket not accepting yet — /healthz leads the data port
        # by a beat) is "not yet", not "failed". Fresh client per
        # attempt: no stale channel state can mask the recovery.
        t_end = time.monotonic() + deadline_s
        last = "no attempt"
        while time.monotonic() < t_end:
            wc = NodeClient(f"127.0.0.1:{p1}", breaker=False,
                            transport="grpc")
            try:
                status, result = wc.send_tensor(
                    x, request_id="warm", timeout=10.0, retries=1)
                if result is not None:
                    return
                last = status
            except Exception as e:  # noqa: BLE001 — front stage itself
                last = f"{type(e).__name__}: {e}"  # mid-restart
            finally:
                wc.close()
            time.sleep(0.5)
        raise RuntimeError(f"warm request failed: {last[:200]}")

    with tempfile.TemporaryDirectory(prefix="chaos_probe_") as tmpdir:
        sups = {
            "node1": Supervisor(
                _spawner(tmpdir, cfg, "node1", m1), name="node1",
                health_url=f"http://127.0.0.1:{m1}",
                health_interval_s=1.0, health_timeout_s=2.0,
                wedged_after=3, on_wedged="restart", warm=warm_fn,
                backoff_s=0.5, ready_deadline_s=150.0),
            "node2": Supervisor(
                _spawner(tmpdir, cfg, "node2", m2), name="node2",
                health_url=f"http://127.0.0.1:{m2}",
                health_interval_s=1.0, health_timeout_s=2.0,
                wedged_after=3, on_wedged="restart", warm=warm_fn,
                backoff_s=0.5, ready_deadline_s=150.0),
        }
        client = None
        gen = None
        try:
            local = PipelineEngine(TopologyConfig.from_dict(cfg))
            import numpy as np

            x = np.asarray(local.spec.example_input(batch_size=1))
            for sup in sups.values():
                sup.start()
            for port in (p1, p2):
                if not _wait_stage_up(port):
                    raise RuntimeError(f"stage on :{port} never came up")
            # the ISSUE-8 edge stack, tuned so recovery detection after
            # an outage is bounded by ~2 s of breaker cooldown, not 30
            client = NodeClient(
                f"127.0.0.1:{p1}", transport="grpc",
                breaker=CircuitBreaker(f"127.0.0.1:{p1}", threshold=5,
                                       cooldown_s=0.5,
                                       max_cooldown_s=2.0))
            warm_fn()
            t0 = time.monotonic()
            gen = _LoadGen(client, x, rate_hz, req_timeout, t0).start()

            faults = plan.process_faults()
            incidents = []
            for fault in faults:
                # serialize: a fault never fires while the previous
                # recovery is still in flight (the plan's timeline is a
                # floor, not a race)
                while time.monotonic() - t0 < fault.at_s:
                    time.sleep(0.2)
                if incidents:
                    while (time.monotonic() - incidents[-1]["abs_rec"]
                           < wedge_gap):
                        time.sleep(0.2)
                sup = sups[fault.target]
                ev = obs.flight.record(
                    "chaos_inject", fault=fault.kind,
                    target=fault.target,
                    t_rel=round(time.monotonic() - t0, 3))
                ts_inject = ev["ts"] if ev else time.time()
                if fault.kind == "kill_stage":
                    sup.inject_kill()
                else:
                    sup.inject_hang()
                rec_ev = _await_recovery(flight_rec, fault.target,
                                         ts_inject, RECOVERY_DEADLINE_S)
                if rec_ev is None:
                    raise RuntimeError(
                        f"no recovery within {RECOVERY_DEADLINE_S}s for "
                        f"{fault.kind} on {fault.target}")
                incidents.append({
                    "fault": fault.kind, "target": fault.target,
                    "t_inject": round(ts_inject - (time.time()
                                      - (time.monotonic() - t0)), 3),
                    "abs_rec": time.monotonic(),
                    "rec_rel": round(time.monotonic() - t0, 3),
                    "outage_s": round(rec_ev["ts"] - ts_inject, 2)})
            # post-recovery observation window (the TTFT-during-recovery
            # contract), then stop the load
            time.sleep(post_w + 1.0)
            run_s = time.monotonic() - t0
            gen.stop(join_timeout=req_timeout + 10.0)
            # loop-lag readback BEFORE the supervisors stop their
            # children: each surviving stage's /debugz is the artifact
            # the sanitizer assertion reads (analysis/sanitize.py)
            from dnn_tpu.analysis import sanitize as _sanitize

            loop_lag = {}
            for name, mp in (("node1", m1), ("node2", m2)):
                try:
                    loop_lag[name] = _sanitize.read_endpoint(
                        f"http://127.0.0.1:{mp}")
                except Exception as e:  # noqa: BLE001 — a stage mid-
                    # restart at readback time fails the assertion
                    # honestly rather than crashing the probe
                    loop_lag[name] = {"installed": False,
                                      "error": f"{type(e).__name__}: "
                                               f"{e}"[:120]}
        finally:
            if gen is not None and not gen._stop.is_set():
                gen.stop(join_timeout=5.0)
            if client is not None:
                client.close()
            for sup in sups.values():
                sup.stop()

    # ---- ring dump: the assertion input is the ARTIFACT, not memory --
    dump_path = os.path.join(tempfile.gettempdir(),
                             f"chaos_probe_ring_{os.getpid()}.jsonl")
    flight_rec.dump(dump_path)
    dumped = [json.loads(line) for line in open(dump_path)
              if line.strip()]
    injected = [e for e in dumped if e["kind"] == "chaos_inject"
                and e.get("fault") in ("kill_stage", "hang_stage")]
    restarts = [e for e in dumped if e["kind"] == "supervisor_restart"]
    paired = all(
        any(r.get("stage") == inj.get("target") and r["ts"] > inj["ts"]
            for r in restarts)
        for inj in injected)

    # ---- outcome accounting ------------------------------------------
    records = gen.records
    total = len(records)
    ok_n = sum(1 for r in records if r["outcome"] == "ok")
    rejected_n = sum(1 for r in records if r["outcome"] == "rejected")
    lost = total - ok_n - rejected_n
    availability = (ok_n + rejected_n) / total if total else 0.0
    quiet_lat = [r["latency"] for r in records
                 if r["outcome"] == "ok" and r.get("t_done", 1e9)
                 < kill_at]
    rec_lat = []
    for inc in incidents:
        lo, hi = inc["rec_rel"], inc["rec_rel"] + post_w
        rec_lat += [r["latency"] for r in records
                    if r["outcome"] == "ok"
                    and lo <= r.get("t_done", -1) <= hi]
    quiet_p99 = _p99(quiet_lat)
    rec_p99 = _p99(rec_lat)
    ttft_ratio = (rec_p99 / quiet_p99
                  if quiet_p99 and rec_p99 else float("inf"))
    ok_avail = availability >= AVAILABILITY_FLOOR and lost == 0
    ok_ttft = ttft_ratio <= TTFT_RATIO_CEIL
    # sanitizer bound: every stage must PROVE the sanitizer ran
    # (loop_sanitize_on in its ring — no vacuous pass) and show no
    # loop stall past the bound
    ok_loop = all(
        ll.get("installed") and ll.get("max_lag_ms", 0.0)
        <= LOOP_LAG_BOUND_MS for ll in loop_lag.values())
    slo_burn = (1.0 - availability) / (1.0 - AVAILABILITY_FLOOR) \
        if total else float("inf")
    import jax

    return {
        "requests": total,
        "completed": ok_n,
        "explicitly_rejected": rejected_n,
        "silently_lost": lost,
        "availability": round(availability, 5),
        "availability_slo_burn": round(slo_burn, 3),
        "success_rate": round(ok_n / total, 4) if total else 0.0,
        "quiet_p99_ms": round(quiet_p99 * 1e3, 2) if quiet_p99 else None,
        "recovery_p99_ms": round(rec_p99 * 1e3, 2) if rec_p99 else None,
        "ttft_recovery_ratio": round(ttft_ratio, 2),
        "incidents": [{k: v for k, v in inc.items() if k != "abs_rec"}
                      for inc in incidents],
        "events_paired": paired,
        "flight_dump": dump_path,
        "run_s": round(run_s, 1),
        "open_loop_hz": rate_hz,
        "loop_lag": loop_lag,
        "loop_lag_bound_ms": LOOP_LAG_BOUND_MS,
        "ok": bool(ok_avail and ok_ttft and paired and ok_loop),
        "ok_availability": bool(ok_avail),
        "ok_ttft": bool(ok_ttft),
        "ok_paired": bool(paired),
        "ok_loop_lag": bool(ok_loop),
        "platform": jax.default_backend(),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when a floor fails "
                         f"(availability >= {AVAILABILITY_FLOOR:.0%} "
                         "with zero silent losses, recovery p99 TTFT "
                         f"<= {TTFT_RATIO_CEIL:.0f}x quiet, every "
                         "injected fault paired with its recovery "
                         "event in the dumped ring)")
    ap.add_argument("--light", action="store_true",
                    help="shortened timeline (smoke use; the acceptance "
                         "configuration is the full >=60s run)")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    row = measure(light=args.light)
    print(json.dumps(row), flush=True)
    if args.do_assert and not row["ok"]:
        print(f"ASSERT FAILED: availability={row['availability']} "
              f"(floor {AVAILABILITY_FLOOR}, lost="
              f"{row['silently_lost']}), ttft_ratio="
              f"{row['ttft_recovery_ratio']} (ceil {TTFT_RATIO_CEIL}), "
              f"paired={row['events_paired']}, "
              f"loop_lag={row['loop_lag']} (bound "
              f"{LOOP_LAG_BOUND_MS:.0f} ms)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
