"""Probe for the XLA CPU compile-cache growth pathology.

The full test suite segfaults XLA's CPU compiler at ~85% of a single
-process run unless compiled executables drop between modules
(tests/conftest.py). This probe tries to isolate the mechanism from
pytest by compiling an endless stream of DISTINCT programs (unique
shapes so nothing cache-hits) and reporting RSS + compile count.

MEASURED FINDINGS (2026-07-31, this jaxlib build): 6000 distinct TINY
single-device programs survive with flat RSS (~0.9 GB), and 2500
distinct 8-device shard_map+psum programs (`--spmd`) survive at a flat
~1.7 GB — neither raw program count nor small SPMD programs reproduce
the crash. The suite's failure therefore involves its actual program
population (large multi-buffer programs: donated KV caches, long
scans, real model weights) — compiled-artifact VOLUME, not table
entries. Until a minimal form reproduces, the suite-scale evidence
stands on its own: the between-modules `jax.clear_caches()` fixture is
load-bearing (removing it reliably segfaults the 600-test run at
~85%), and the serving daemon's CompileCacheGuard
(dnn_tpu/utils/xla_cache.py) bounds the same accumulation for
week-long processes.

Run manually (NOT part of the suite):
    JAX_PLATFORMS=cpu python benchmarks/xla_cache_probe.py --limit 6000
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/xla_cache_probe.py --spmd --limit 2000
    JAX_PLATFORMS=cpu python benchmarks/xla_cache_probe.py --clear-every 256
"""

from __future__ import annotations

import argparse
import os
import resource
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # script lives in benchmarks/
    sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--limit", type=int, default=10_000,
                    help="stop after N distinct programs (if still alive)")
    ap.add_argument("--clear-every", type=int, default=0,
                    help="jax.clear_caches() every N programs (0 = never "
                         "— the accumulating configuration)")
    ap.add_argument("--spmd", action="store_true",
                    help="compile distinct 8-device shard_map programs "
                         "(closer to the suite's program population)")
    ap.add_argument("--report-every", type=int, default=200)
    args = ap.parse_args()

    if args.spmd:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    if args.spmd:
        from jax.sharding import PartitionSpec as P

        from dnn_tpu.parallel.mesh import DATA_AXIS, make_mesh

        mesh = make_mesh({DATA_AXIS: 8})

    for i in range(1, args.limit + 1):
        # unique shape per iteration -> a fresh compile every time
        n = 8 + (i % 509)  # co-prime walk: shapes repeat only mod 509
        m = 8 + (i // 509)

        if args.spmd:
            def body(x, _m=m):
                import jax.lax as lax

                y = (x @ x.T) * _m + jnp.tanh(x).sum()
                return lax.psum(y, DATA_AXIS)

            f = jax.jit(jax.shard_map(body, mesh=mesh,
                                      in_specs=P(DATA_AXIS),
                                      out_specs=P(), check_vma=False))
            f(jnp.ones((8, n), jnp.float32)).block_until_ready()
        else:
            @jax.jit
            def f(x, _m=m):
                return (x @ x.T) * _m + jnp.tanh(x).sum()

            f(jnp.ones((n, n), jnp.float32)).block_until_ready()
        if args.clear_every and i % args.clear_every == 0:
            jax.clear_caches()
        if i % args.report_every == 0:
            rss_mb = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024
            print(f"{i} programs, rss={rss_mb:.0f} MB", flush=True)
    print(f"survived {args.limit} programs", flush=True)


if __name__ == "__main__":
    sys.exit(main())
