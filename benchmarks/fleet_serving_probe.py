"""fleet_serving: the fleet front door's measured contract (ISSUE 13).

Open-loop many-client load (the seed of ROADMAP item 5b's generator)
drives REAL `node --serve_lm` replica subprocesses, each under its own
`chaos.supervisor.Supervisor`, in two legs at the SAME demand:

  * SINGLE (the asserted single-replica baseline row): the load hits
    one replica directly — no front door. Demand is calibrated to
    ~2x the replica's measured capacity, so its FIFO queue saturates
    and the admit-then-deadline-cancel pathology takes over: requests
    are admitted just as their propagated `dl=` budget runs out, burn
    decode on work nobody will receive, and DELIVERED tokens/sec
    collapses far below capacity.
  * FLEET: the same demand through the router over 2 replicas with
    SLO-driven admission (per-replica in-flight bound): excess
    arrivals shed EXPLICITLY (UNAVAILABLE — cheap, retriable),
    admitted work finishes inside its deadline, and ONE replica is
    SIGKILLed mid-measurement (the supervisor respawns it; the router
    routes around and sibling-retries the in-flight casualties).

Asserted floors (--assert exits nonzero when any fails):

  * availability (fleet leg): >= 99% of submitted requests COMPLETED-
    OR-EXPLICITLY-REJECTED and ZERO silently lost — through a kill;
  * fleet tokens/sec >= 1.5x the single-replica leg's — WHOLE-LEG
    delivered on both sides (the single leg keeps its healthy
    pre-saturation ramp, the fleet keeps its kill dent; the post-
    settle steady-state window rides the row as detail, where the
    single replica reads ~ZERO). On this 1-core host the win is pure
    CONTROL PLANE — admission keeping queues short enough that
    admitted work completes (the single leg wastes its capacity on
    doomed decodes); on a multi-chip substrate the same row adds the
    width win on top. STUDIES §17 has the collapse numbers;
  * the kill pairs with its `supervisor_restart` recovery event IN THE
    DUMPED RING (the incident reconstructs from the flight recorder).

`python -m benchmarks.fleet_serving_probe [--assert] [--light]
[--require-substrate tpu|cpu]` prints one JSON row; the run_all
`fleet_serving` row rides `measure()` and honors the same substrate
contract (PR 11's flag) via $DNN_TPU_REQUIRE_SUBSTRATE.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

AVAILABILITY_FLOOR = 0.99
FLEET_SPEEDUP_FLOOR = 1.5
RECOVERY_DEADLINE_S = 240.0   # gpt2 child respawn incl. jax import +
# first compile on a contended core

MODEL = "gpt2"       # the full config: ~1.8 s/request on this host —
# the regime where deadline waste is REAL (gpt2-test decodes a whole
# request in ~50 ms, far below any honest client deadline)
SLOTS = 2
MAX_LEN = 96
PROMPT_LEN = 8
MAX_NEW = 24
REQ_TIMEOUT_S = 10.0
OVERLOAD = 2.0       # open-loop demand vs the measured capacity

# ports: distinct from chaos (594xx/595xx) and relay probes
_SINGLE = (59901, 59911)            # (grpc, metrics)
_FLEET_BASE = (59921, 59931)        # 2 replicas from here
_ROUTER_PORT = 59920


def _prompt():
    import numpy as np

    return (np.arange(1, PROMPT_LEN + 1) % 999).astype(np.int32)


class _OpenLoopGen:
    """Open-loop arrivals at `rate_hz`, one thread per request (the
    chaos-probe pattern): every request records exactly one outcome —
    ok (with its token count and completion time) or rejected — or
    stays None (silently lost, the thing the probe asserts cannot
    happen). Completion-timestamped so delivered-tokens/sec can be
    windowed identically across legs."""

    def __init__(self, address: str, rate_hz: float, dur_s: float,
                 t0: float):
        self.address = address
        self.rate = float(rate_hz)
        self.dur = float(dur_s)
        self.t0 = t0
        self.records: list = []

    def run(self):
        import numpy as np

        from dnn_tpu.comm.client import NodeClient

        prompt = np.asarray(_prompt(), np.int32)
        threads = []
        stop_at = time.monotonic() + self.dur
        nxt = time.monotonic()
        i = 0

        def one(rec):
            cl = NodeClient(self.address, transport="grpc",
                            breaker=False)
            try:
                status, result = cl.send_tensor(
                    prompt, request_id=f"gen:{MAX_NEW}:{rec['i']}",
                    timeout=REQ_TIMEOUT_S, retries=0)
                if result is not None:
                    rec["outcome"] = "ok"
                    rec["tokens"] = int(np.asarray(result).size)
                else:
                    rec["outcome"] = "rejected"
                    rec["error"] = str(status)[:120]
            except Exception as e:  # noqa: BLE001 — EXPLICIT rejection
                rec["outcome"] = "rejected"
                rec["error"] = f"{type(e).__name__}: {e}"[:120]
            finally:
                rec["t_done"] = time.monotonic() - self.t0
                cl.close()

        while time.monotonic() < stop_at:
            now = time.monotonic()
            if now < nxt:
                time.sleep(min(nxt - now, 0.05))
                continue
            nxt += 1.0 / self.rate
            rec = {"i": i, "t": now - self.t0, "outcome": None,
                   "tokens": 0}
            self.records.append(rec)
            th = threading.Thread(target=one, args=(rec,), daemon=True)
            th.start()
            threads.append(th)
            i += 1
        t_end = time.monotonic() + REQ_TIMEOUT_S + 10
        for th in threads:
            th.join(timeout=max(t_end - time.monotonic(), 0.1))
        return self


def _delivered_tps(records, lo_s: float, hi_s: float) -> float:
    """Tokens of COMPLETED requests finishing inside [lo, hi) per
    second — goodput, not offered load (a deadline-cancelled request's
    decoded-then-discarded tokens count for nothing, which is exactly
    the collapse the single leg measures)."""
    toks = sum(r["tokens"] for r in records
               if r["outcome"] == "ok"
               and lo_s <= r.get("t_done", -1) < hi_s)
    return toks / max(hi_s - lo_s, 1e-9)


def _warm(address: str, deadline_s: float = 300.0):
    """First real request (pays the child's compile); polled — a
    mid-boot UNAVAILABLE is 'not yet', not 'failed'."""
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    t_end = time.monotonic() + deadline_s
    last = "no attempt"
    while time.monotonic() < t_end:
        cl = NodeClient(address, transport="grpc", breaker=False)
        try:
            status, result = cl.send_tensor(
                np.asarray(_prompt(), np.int32),
                request_id=f"gen:{MAX_NEW}:0", timeout=120.0, retries=0)
            if result is not None:
                return
            last = status
        except Exception as e:  # noqa: BLE001 — still booting
            last = f"{type(e).__name__}: {e}"
        finally:
            cl.close()
        time.sleep(1.0)
    raise RuntimeError(f"warm request never completed: {last[:200]}")


def _calibrate_capacity(address: str, secs: float) -> float:
    """Closed-loop saturation (SLOTS+1 workers) -> tokens/sec: the
    replica's real capacity on THIS host, so the open-loop demand is
    an honest multiple of it whatever silicon runs the probe."""
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    done = []
    stop_at = time.monotonic() + secs

    def w():
        cl = NodeClient(address, transport="grpc", breaker=False)
        try:
            while time.monotonic() < stop_at:
                try:
                    _, result = cl.send_tensor(
                        np.asarray(_prompt(), np.int32),
                        request_id=f"gen:{MAX_NEW}:1",
                        timeout=60.0, retries=0)
                    if result is not None:
                        done.append(int(np.asarray(result).size))
                except Exception:  # noqa: BLE001 — calibration only
                    time.sleep(0.2)
        finally:
            cl.close()

    ths = [threading.Thread(target=w, daemon=True)
           for _ in range(SLOTS + 1)]
    t0 = time.monotonic()
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=secs + 90)
    wall = time.monotonic() - t0
    return sum(done) / max(wall, 1e-9)


def measure(light: bool = False) -> dict:
    from dnn_tpu import obs
    from dnn_tpu.control.policy import wanted_replicas
    from dnn_tpu.control.replicaset import ReplicaSet
    from dnn_tpu.control.router import start_router_in_background

    settle_s = 12.0 if light else 24.0
    measure_s = 16.0 if light else 40.0
    calib_s = 6.0 if light else 10.0
    flight_rec = obs.flight.recorder()
    row: dict = {"model": MODEL, "slots": SLOTS, "max_new": MAX_NEW,
                 "req_timeout_s": REQ_TIMEOUT_S, "overload": OVERLOAD}

    # ---- leg A: one replica, no front door ---------------------------
    with tempfile.TemporaryDirectory(prefix="fleet_single_") as tmp:
        rset1 = ReplicaSet.spawn_lm_fleet(
            tmp, model=MODEL, base_port=_SINGLE[0],
            metrics_base_port=_SINGLE[1], roles=["both"], slots=SLOTS,
            max_len=MAX_LEN, kv="dense",
            ready_deadline_s=RECOVERY_DEADLINE_S)
        rset1.start()
        try:
            if not rset1.wait_serving(1, RECOVERY_DEADLINE_S):
                raise RuntimeError("single replica never came up")
            addr = f"127.0.0.1:{_SINGLE[0]}"
            _warm(addr)
            cap_tps = _calibrate_capacity(addr, calib_s)
            rate_hz = OVERLOAD * cap_tps / MAX_NEW
            t0 = time.monotonic()
            gen = _OpenLoopGen(addr, rate_hz,
                               settle_s + measure_s, t0).run()
            single_tps = _delivered_tps(gen.records, settle_s,
                                        settle_s + measure_s)
            single_whole = _delivered_tps(gen.records, 0.0,
                                          settle_s + measure_s)
            ok_n = sum(1 for r in gen.records if r["outcome"] == "ok")
            row.update({
                "capacity_tokens_per_sec": round(cap_tps, 1),
                "open_loop_hz": round(rate_hz, 2),
                "single_requests": len(gen.records),
                "single_completed": ok_n,
                "single_tokens_per_sec": round(single_tps, 1),
                "single_tokens_per_sec_whole_leg":
                    round(single_whole, 1),
                "single_delivered_frac_of_capacity":
                    round(single_tps / max(cap_tps, 1e-9), 3),
            })
        finally:
            rset1.stop()

    # ---- leg B: 3 replicas + router, kill one mid-measurement --------
    with tempfile.TemporaryDirectory(prefix="fleet_router_") as tmp:
        rset = ReplicaSet.spawn_lm_fleet(
            tmp, model=MODEL, base_port=_FLEET_BASE[0],
            metrics_base_port=_FLEET_BASE[1], roles=["both"] * 2,
            slots=SLOTS, max_len=MAX_LEN, kv="dense",
            ready_deadline_s=RECOVERY_DEADLINE_S)
        rset.start()
        router = rstop = None
        try:
            if not rset.wait_serving(2, RECOVERY_DEADLINE_S):
                raise RuntimeError("fleet replicas never all came up")
            # in-flight bound = the replica's slot count: admitted
            # work fills each replica's batch (amortizing per-step
            # overhead — measured: two batch-1 gpt2 processes thrash to
            # 9 tok/s aggregate on this host, two batch-2 recover the
            # full 22) while staying few enough to finish inside the
            # propagated deadline — the admission controller IS the
            # contract
            router, rstop = start_router_in_background(
                rset, port=_ROUTER_PORT, policy="least_queue",
                max_inflight_per_replica=SLOTS,
                default_deadline_s=REQ_TIMEOUT_S + 2.0)
            raddr = f"127.0.0.1:{_ROUTER_PORT}"
            # warm EVERY replica by address (the first generate pays
            # the child's compile — routed warmups can land on one
            # replica thrice and leave the others cold inside the
            # client deadline), then one routed round-trip
            for h in rset.replicas.values():
                _warm(h.address)
            _warm(raddr)
            rate_hz = row["open_loop_hz"]
            t0 = time.monotonic()
            gen = _OpenLoopGen(raddr, rate_hz, settle_s + measure_s, t0)
            runner = threading.Thread(target=gen.run, daemon=True)
            runner.start()
            # SIGKILL one replica halfway into the measured window
            while time.monotonic() - t0 < settle_s + measure_s / 2.0:
                time.sleep(0.2)
            victim = rset.replicas["r1"]
            ev = obs.flight.record("fleet_kill", replica="r1",
                                   t_rel=round(time.monotonic() - t0, 2))
            ts_kill = ev["ts"] if ev else time.time()
            victim.kill()
            # the autoscaling signal, sampled UNDER load (an idle
            # fleet legitimately scales down — that is not the number
            # this row reports); the router's own view: shedding-aware
            # (admission keeps replica queues short exactly when the
            # fleet is overloaded, so queue depth alone is blind)
            time.sleep(2.0)
            wanted = wanted_replicas(
                router._views(), slots_hint=SLOTS,
                shedding=router.state == "shedding")
            runner.join(timeout=settle_s + measure_s
                        + REQ_TIMEOUT_S + 60)
            fleet_tps = _delivered_tps(gen.records, settle_s,
                                       settle_s + measure_s)
            fleet_whole = _delivered_tps(gen.records, 0.0,
                                         settle_s + measure_s)
            total = len(gen.records)
            ok_n = sum(1 for r in gen.records if r["outcome"] == "ok")
            rej_n = sum(1 for r in gen.records
                        if r["outcome"] == "rejected")
            lost = total - ok_n - rej_n
            availability = (ok_n + rej_n) / total if total else 0.0
            # recovery: wait for the supervisor to bring r1 back and
            # record supervisor_restart AFTER the kill
            rec_ev = None
            t_end = time.monotonic() + RECOVERY_DEADLINE_S
            while time.monotonic() < t_end and rec_ev is None:
                for e in flight_rec.events(kind="supervisor_restart"):
                    if e.get("stage") == "r1" and e["ts"] > ts_kill:
                        rec_ev = e
                        break
                time.sleep(0.5)
            row.update({
                "fleet_replicas": 2,
                "fleet_requests": total,
                "fleet_completed": ok_n,
                "fleet_explicitly_rejected": rej_n,
                "fleet_silently_lost": lost,
                "fleet_availability": round(availability, 5),
                "fleet_tokens_per_sec": round(fleet_tps, 1),
                "fleet_tokens_per_sec_whole_leg":
                    round(fleet_whole, 1),
                "fleet_shed_total": router.shed_total,
                "kill_outage_s": (round(rec_ev["ts"] - ts_kill, 1)
                                  if rec_ev else None),
                "wanted_replicas": wanted,
            })
        finally:
            if rstop is not None:
                rstop()
            rset.stop()

    # ---- ring dump: assertions read the ARTIFACT, not memory ---------
    dump_path = os.path.join(tempfile.gettempdir(),
                             f"fleet_serving_ring_{os.getpid()}.jsonl")
    flight_rec.dump(dump_path)
    dumped = [json.loads(line) for line in open(dump_path)
              if line.strip()]
    kills = [e for e in dumped if e["kind"] == "fleet_kill"]
    restarts = [e for e in dumped
                if e["kind"] == "supervisor_restart"]
    paired = bool(kills) and all(
        any(r.get("stage") == k.get("replica") and r["ts"] > k["ts"]
            for r in restarts)
        for k in kills)

    # the asserted ratio compares WHOLE-LEG delivered tokens/sec: the
    # single leg keeps its healthy pre-saturation ramp (its best
    # behavior), the fleet leg keeps its kill dent — both legs priced
    # end to end, no degenerate zero denominators. The post-settle
    # window rides the row as the steady-state detail (the single
    # replica's steady state under sustained overload is ~ZERO — the
    # admit-then-deadline-cancel collapse STUDIES §17 walks through).
    speedup = min(row["fleet_tokens_per_sec_whole_leg"]
                  / max(row["single_tokens_per_sec_whole_leg"], 1e-9),
                  999.0)
    ok_avail = (row["fleet_availability"] >= AVAILABILITY_FLOOR
                and row["fleet_silently_lost"] == 0)
    ok_speed = speedup >= FLEET_SPEEDUP_FLOOR
    row.update({
        "fleet_vs_single": round(speedup, 2),
        "flight_dump": dump_path,
        "events_paired": paired,
        "ok_availability": bool(ok_avail),
        "ok_speedup": bool(ok_speed),
        "ok_paired": bool(paired),
        "ok": bool(ok_avail and ok_speed and paired),
        # the substrate of the MEASURED serving, not of this parent
        # process: spawn_lm_fleet pins every replica child to
        # JAX_PLATFORMS=cpu (one axon-tunnel client rule — N TPU
        # children would deadlock the chip), so a TPU parent must not
        # stamp a substrate the serving never touched
        "platform": "cpu",
        "round_substrate": "cpu",
    })
    return row


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when a floor fails "
                         f"(fleet availability >= "
                         f"{AVAILABILITY_FLOOR} with zero silent "
                         f"losses through a kill, fleet tokens/sec >= "
                         f"{FLEET_SPEEDUP_FLOOR}x the single-replica "
                         "leg, kill paired with supervisor_restart in "
                         "the dumped ring)")
    ap.add_argument("--light", action="store_true",
                    help="shortened legs (smoke use; the acceptance "
                         "configuration is the full run)")
    ap.add_argument("--require-substrate", choices=["tpu", "cpu"],
                    default=os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
                    or None,
                    help="fail the row (ok=false, nonzero exit) when "
                         "the probe ran on a different substrate — "
                         "PR 11's trajectory contract "
                         "($DNN_TPU_REQUIRE_SUBSTRATE is the run_all "
                         "spelling)")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    row = measure(light=args.light)
    if args.require_substrate:
        row["required_substrate"] = args.require_substrate
        if row["round_substrate"] != args.require_substrate:
            row["ok"] = False
            row["note"] = (f"required substrate "
                           f"'{args.require_substrate}' but the probe "
                           f"ran on '{row['round_substrate']}'")
    print(json.dumps(row), flush=True)
    if args.do_assert and not row["ok"]:
        print(f"ASSERT FAILED: availability="
              f"{row['fleet_availability']} (floor "
              f"{AVAILABILITY_FLOOR}, lost="
              f"{row['fleet_silently_lost']}), fleet_vs_single="
              f"{row['fleet_vs_single']} (floor {FLEET_SPEEDUP_FLOOR}),"
              f" paired={row['events_paired']}, ok={row['ok']}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
