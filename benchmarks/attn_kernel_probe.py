"""Cached-attention kernel vs einsum across cache lengths — find the
crossover.

RESULTS.md's attnkernel rows showed the Pallas kernel LOSING at S=256
(0.73x bf16, 0.58x int8): at short context the cache stream is a few MB
against ~250 MB of weights per decode step, and the kernel's grid
dispatch (B*H programs per layer per step) costs more than it saves.
The kernel's case is long context, where the cache stream dominates the
step. This probe times the ATTENTION OP alone (not the full decode) at
decode shapes (T=1) across S, bf16 and int8, kernel vs einsum reference,
to locate the crossover for an `attn_kernel="auto"` policy.

Usage: python benchmarks/attn_kernel_probe.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from dnn_tpu.ops.pallas.cached_attention import (
    decode_attention, reference_decode_attention,
)
from dnn_tpu.utils.timing import device_time

B, H, D = 8, 12, 64


def main():
    rng = jax.random.PRNGKey(0)
    for s_len in (256, 1024, 4096, 16384):
        # per-length keys (fold_in): the old split of the never-rebound
        # base key handed every s_len the SAME q/k/v draws (TPU003)
        kq, kk, kv = jax.random.split(jax.random.fold_in(rng, s_len), 3)
        q = jax.random.normal(kq, (B, H, 1, D), jnp.bfloat16)
        k = jax.random.normal(kk, (B, H, s_len, D), jnp.bfloat16)
        v = jax.random.normal(kv, (B, H, s_len, D), jnp.bfloat16)
        pos = jnp.full((B,), s_len - 1, jnp.int32)  # cache fully live

        kern = jax.jit(lambda *a: decode_attention(*a))
        ref = jax.jit(lambda *a: reference_decode_attention(*a))
        dt_k = device_time(kern, q, k, v, pos, n1=100, n2=400, trials=5)
        dt_r = device_time(ref, q, k, v, pos, n1=100, n2=400, trials=5)

        ki = jnp.clip(jnp.round(k.astype(jnp.float32) * 20), -127, 127
                      ).astype(jnp.int8)
        vi = jnp.clip(jnp.round(v.astype(jnp.float32) * 20), -127, 127
                      ).astype(jnp.int8)
        sc = jnp.full((B, H, s_len), 0.05, jnp.float32)
        kern_q = jax.jit(lambda qq, kk_, vv, pp, s1, s2: decode_attention(
            qq, kk_, vv, pp, ks=s1, vs=s2))
        ref_q = jax.jit(lambda qq, kk_, vv, pp, s1, s2:
                        reference_decode_attention(qq, kk_, vv, pp,
                                                   ks=s1, vs=s2))
        dt_kq = device_time(kern_q, q, ki, vi, pos, sc, sc,
                            n1=100, n2=400, trials=5)
        dt_rq = device_time(ref_q, q, ki, vi, pos, sc, sc,
                            n1=100, n2=400, trials=5)

        cache_mb = 2 * B * H * s_len * D * 2 / 1e6
        print(json.dumps({
            "s": s_len, "cache_mb_bf16": round(cache_mb, 1),
            "bf16_kernel_us": round(dt_k * 1e6, 1),
            "bf16_einsum_us": round(dt_r * 1e6, 1),
            "bf16_speedup": round(dt_r / dt_k, 3),
            "bf16_kernel_gbps": round(cache_mb / 1e3 / dt_k, 1),
            "int8_kernel_us": round(dt_kq * 1e6, 1),
            "int8_einsum_us": round(dt_rq * 1e6, 1),
            "int8_speedup": round(dt_rq / dt_kq, 3),
            "int8_kernel_gbps": round(cache_mb / 2e3 / dt_kq, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
