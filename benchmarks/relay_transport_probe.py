"""relay_transport probe: A-B nested-gRPC vs negotiated-auto hops.

ISSUE 7's regression contract on the 2-stage cifar config — the exact
deployment STUDIES.md §10 measured at 75.9% warm bubble: two REAL stage
server processes plus a client, so the numbers carry true process
isolation (an in-process simulation shares one GIL and measures
contention, not transport).

  * leg A (baseline): both stage daemons pinned to `transport="grpc"` —
    the reference behavior: nested unary chain, serialized payloads,
    every hop held open for the full downstream latency;
  * leg B (negotiated-auto): the same stages on `transport="auto"`; the
    two processes negotiate the shm rung (probe-proven same host, one
    memcpy per hop, zero serialization) and the streamed Relay path
    replaces the nested chain (ack-early MPMD overlap).

Everything is read off the EXISTING obs surfaces, never ad-hoc timers:

  * per-hop latency: the node1 daemon's
    `comm_hop_seconds{stage="node1",transport=,mode=}` summary scraped
    from its /metrics endpoint — the time the upstream was HELD per
    microbatch (mode="nested": the full downstream round trip — that is
    what nested means; mode="streamed": the handoff incl. backpressure
    stalls). Assert floor: leg B streamed p50 <= 1/5 of leg A nested p50.
  * bubble fraction: obs.fleet.FleetCollector polling all three
    processes' /trace.jsonl, NTP-style offset estimation, and
    critical_path over the stitched request — §10's pipeline, §10's
    arithmetic. Assert floor: leg B's stitched warm bubble <= 1/2 of
    leg A's (and reported against the recorded 0.759).

`python -m benchmarks.relay_transport_probe [--assert] [--light]`
prints one JSON row; --assert exits nonzero when a floor fails (the
run_all `relay_transport` row and bench.py's round attachment both ride
`measure()`).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# NOTE: no module-level JAX_PLATFORMS mutation — importers (bench.py, a
# TPU-substrate run_all parent) must not have their environment forced
# to CPU as an import side effect. The stage children pin themselves to
# CPU in _spawn_stage; the standalone CLI pins its own process in main().

HOP_RATIO_FLOOR = 5.0     # auto per-hop p50 must be <= grpc p50 / 5
BUBBLE_DROP_FLOOR = 2.0   # auto stitched bubble must be <= grpc / 2
S10_BUBBLE = 0.759        # STUDIES.md §10 recorded warm bubble (nested)
# loop-lag sanitizer bound (analysis/sanitize.py): both legs' stage
# children run with DNN_TPU_LOOP_SANITIZE=1; the probe reads each
# stage's /debugz back and asserts no event-loop callback held the
# loop past this. Sized above first-compile GIL stalls, far below the
# ShmRing 30 s blocking-wait this exists to catch reintroductions of.
LOOP_LAG_BOUND_MS = 5000.0

# (grpc_port1, grpc_port2, metrics_port1, metrics_port2) per leg
_PORTS = {"grpc": (59491, 59492, 59591, 59592),
          "auto": (59493, 59494, 59593, 59594)}

_CHILD_SRC = """
import asyncio, sys
sys.path.insert(0, {repo!r})
from dnn_tpu.config import TopologyConfig
from dnn_tpu.runtime.engine import PipelineEngine
from dnn_tpu.comm.service import serve_stage

cfg = TopologyConfig.from_dict({cfg!r})
engine = PipelineEngine(cfg)
asyncio.run(serve_stage(engine, {node_id!r}, metrics_port={mport},
                        transport={pref!r}))
"""


def _leg_config(p1: int, p2: int) -> dict:
    return {
        "nodes": [
            {"id": "node1", "address": f"127.0.0.1:{p1}", "part_index": 0},
            {"id": "node2", "address": f"127.0.0.1:{p2}", "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
        "device_type": "cpu",
    }


def _spawn_stage(tmpdir: str, cfg: dict, node_id: str, mport: int,
                 pref: str):
    script = os.path.join(tmpdir, f"stage_{node_id}_{pref}.py")
    with open(script, "w") as f:
        f.write(_CHILD_SRC.format(repo=REPO, cfg=cfg, node_id=node_id,
                                  mport=mport, pref=pref))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DNN_TPU_LOOP_SANITIZE="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen([sys.executable, script], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_up(port: int, deadline: float = 120.0) -> bool:
    """Fresh channel per poll: an early-failing channel can wedge in
    reconnect backoff and never see the late bind."""
    from dnn_tpu.comm.client import NodeClient

    t_end = time.monotonic() + deadline
    while time.monotonic() < t_end:
        probe = NodeClient(f"127.0.0.1:{port}")
        try:
            if probe.health_check(timeout=2.0):
                return True
        finally:
            probe.close()
        time.sleep(0.5)
    return False


def _scrape(samples_url: str):
    from dnn_tpu.obs.fleet import _Samples, parse_prometheus

    with urllib.request.urlopen(samples_url, timeout=10) as r:
        return _Samples(parse_prometheus(r.read().decode()))


def _hop_quantiles(metrics_url: str, mode: str):
    """(p50_ms, p99_ms, transport) for node1's downstream hop series of
    the given mode, whatever transport it negotiated."""
    s = _scrape(metrics_url + "/metrics")
    for name, labs, _v in s._samples:
        if name == "comm_hop_seconds" and labs.get("stage") == "node1" \
                and labs.get("mode") == mode and "quantile" in labs:
            tr = labs.get("transport")
            p50 = s.get("comm_hop_seconds", stage="node1", mode=mode,
                        transport=tr, quantile="0.5")
            p99 = s.get("comm_hop_seconds", stage="node1", mode=mode,
                        transport=tr, quantile="0.99")
            return (round(p50 * 1e3, 3) if p50 is not None else None,
                    round(p99 * 1e3, 3) if p99 is not None else None, tr)
    return None, None, None


def _measure_leg(pref: str, tmpdir: str, n_unary: int, n_stream: int):
    import numpy as np

    from dnn_tpu import obs
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.obs.fleet import FleetCollector
    from dnn_tpu.runtime.engine import PipelineEngine

    p1, p2, m1, m2 = _PORTS[pref]
    cfg = _leg_config(p1, p2)
    children = [
        _spawn_stage(tmpdir, cfg, "node1", m1, pref),
        _spawn_stage(tmpdir, cfg, "node2", m2, pref),
    ]
    client_srv = None
    c = None
    col = None
    try:
        for port in (p1, p2):
            if not _wait_up(port):
                raise RuntimeError(f"stage on :{port} never came up")
        # the probe process is the client; its spans are served from its
        # own obs endpoint so the fleet collector stitches all THREE
        # processes, §10-style
        client_srv = obs.serve_metrics(0)
        local = PipelineEngine(TopologyConfig.from_dict(cfg))
        x = np.asarray(local.spec.example_input(batch_size=1))
        c = NodeClient(f"127.0.0.1:{p1}",
                       transport="grpc" if pref == "grpc" else "auto")
        # warm: compiles, channels, negotiation, both code paths
        for _ in range(3):
            status, result = c.send_tensor(x, request_id="warm")
            assert result is not None, status
        c.send_tensors([x] * 2, request_id="warm_s")
        obs.collector().clear()

        unary_traces = []
        for i in range(n_unary):
            with obs.span("relay_probe.request", leg=pref) as sp:
                status, result = c.send_tensor(x, request_id=f"p{i}")
            assert result is not None, status
            unary_traces.append(sp.trace_id)
        # median of three streams: a single scheduler hiccup on a busy
        # CI host can double one stream's wall time
        stream_traces = []
        for _ in range(3):
            with obs.span("relay_probe.stream", leg=pref) as sp:
                outs = c.send_tensors([x] * n_stream, request_id="ps")
            assert all(r is not None for _, r in outs)
            stream_traces.append(sp.trace_id)

        targets = {"client": f"http://127.0.0.1:{client_srv.port}",
                   "node1": f"http://127.0.0.1:{m1}",
                   "node2": f"http://127.0.0.1:{m2}"}
        col = FleetCollector(targets, interval_s=3600.0)
        col.poll_once()

        def bubble(tid):
            rep = col.request_report(tid)
            return float(rep.get("bubble_fraction", float("nan")))

        bubbles = sorted(bubble(t) for t in unary_traces)
        s_bubbles = sorted(bubble(t) for t in stream_traces)
        nested_p50, nested_p99, tr_n = _hop_quantiles(targets["node1"],
                                                      "nested")
        stream_p50, stream_p99, tr_s = _hop_quantiles(targets["node1"],
                                                      "streamed")
        # loop-lag readback off each stage's /debugz while the children
        # are still up — the sanitizer assertion reads the artifact. A
        # stage dead at readback time fails the assertion honestly
        # (installed=False) instead of crashing the probe.
        from dnn_tpu.analysis import sanitize as _sanitize

        loop_lag = {}
        for name in ("node1", "node2"):
            try:
                loop_lag[name] = _sanitize.read_endpoint(targets[name])
            except Exception as e:  # noqa: BLE001
                loop_lag[name] = {"installed": False,
                                  "error": f"{type(e).__name__}: "
                                           f"{e}"[:120]}
        return {
            "loop_lag": loop_lag,
            "negotiated": tr_s or tr_n or "grpc",
            "hop_nested_p50_ms": nested_p50,
            "hop_nested_p99_ms": nested_p99,
            "hop_streamed_p50_ms": stream_p50,
            "hop_streamed_p99_ms": stream_p99,
            "bubble_fraction": round(bubbles[len(bubbles) // 2], 4),
            "bubble_fraction_streamed": round(
                s_bubbles[len(s_bubbles) // 2], 4),
        }
    finally:
        if col is not None:
            col.close()
        if c is not None:
            c.close()
        if client_srv is not None:
            client_srv.close()
        for child in children:
            child.terminate()
        for child in children:
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()


def measure(light: bool = False) -> dict:
    """-> one row comparing the two legs. `light` shrinks the sample
    counts (bench.py's per-round attachment)."""
    import jax

    n_unary, n_stream = (7, 8) if light else (13, 16)
    with tempfile.TemporaryDirectory(prefix="relay_transport_") as tmpdir:
        grpc_leg = _measure_leg("grpc", tmpdir, n_unary, n_stream)
        auto_leg = _measure_leg("auto", tmpdir, n_unary, n_stream)
    # the A-B contract: leg A is the reference behavior (nested unary
    # chain, grpc payloads); leg B is what negotiated-auto actually
    # serves (shm payloads + the ack-early streamed schedule)
    hop_a = grpc_leg["hop_nested_p50_ms"]
    hop_b = auto_leg["hop_streamed_p50_ms"]
    ratio = (hop_a / hop_b) if hop_a and hop_b else float("nan")
    bubble_auto = auto_leg["bubble_fraction_streamed"]
    bubble_grpc = grpc_leg["bubble_fraction"]
    ok_hop = bool(hop_a and hop_b and hop_b <= hop_a / HOP_RATIO_FLOOR)
    ok_bubble = bool(bubble_auto <= bubble_grpc / BUBBLE_DROP_FLOOR)
    # sanitizer bound over BOTH legs' stages: installed (no vacuous
    # pass) and no loop stall past the bound — the in-run dynamic
    # check for event-loop-blocking regressions (CON001's companion)
    ok_loop = all(
        ll.get("installed") and ll.get("max_lag_ms", 0.0)
        <= LOOP_LAG_BOUND_MS
        for leg in (grpc_leg, auto_leg)
        for ll in leg.get("loop_lag", {}).values())
    return {
        "loop_lag_bound_ms": LOOP_LAG_BOUND_MS,
        "ok_loop_lag": ok_loop,
        "grpc": grpc_leg,
        "auto": auto_leg,
        "hop_p50_ratio": round(ratio, 2),
        "bubble_drop": round(bubble_grpc / bubble_auto, 2)
        if bubble_auto else float("inf"),
        "vs_studies_s10": {"recorded_bubble": S10_BUBBLE,
                           "auto_bubble": bubble_auto,
                           "drop": round(S10_BUBBLE / bubble_auto, 2)
                           if bubble_auto else float("inf")},
        "ok": bool(ok_hop and ok_bubble and ok_loop),
        "ok_hop": ok_hop,
        "ok_bubble": ok_bubble,
        "platform": jax.default_backend(),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when a floor fails (hop p50 ratio "
                         f">= {HOP_RATIO_FLOOR}x, bubble drop >= "
                         f"{BUBBLE_DROP_FLOOR}x)")
    ap.add_argument("--light", action="store_true",
                    help="smaller sample counts (the bench round's "
                         "attachment)")
    args = ap.parse_args(argv)
    # standalone CLI: same-host CPU substrate by definition
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    row = measure(light=args.light)
    print(json.dumps(row), flush=True)
    if args.do_assert and not row["ok"]:
        print(f"ASSERT FAILED: hop ratio {row['hop_p50_ratio']} "
              f"(floor {HOP_RATIO_FLOOR}), bubble drop "
              f"{row['bubble_drop']} (floor {BUBBLE_DROP_FLOOR}), "
              f"loop lag ok={row['ok_loop_lag']} (bound "
              f"{LOOP_LAG_BOUND_MS:.0f} ms)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
