"""Perf-trajectory ledger: the repo's measured history as ONE table,
with every ratchet assert in ONE place.

Two artifact families record this repo's trajectory and, until now,
nothing read them together:

  * BENCH_r*.json (repo root): one headline row per round from
    bench.py — tokens/sec vs baseline, substrate, and (since PR 6/7)
    the decode-goodput and relay-transport riders;
  * the run_all rows: benchmarks/.bench_rows.jsonl when a round ran
    here, else the committed benchmarks/RESULTS.md table.

The ledger parses both into one trend view (`python
benchmarks/ledger.py`) and CENTRALIZES the ratchet asserts that were
scattered across probe modules: each ratchet names its config, the
field it reads, and the threshold IMPORTED from the probe that owns it
(single source of truth — the ledger can never drift from the gate).
`--assert` exits nonzero when any evaluated ratchet fails; a missing
row is reported as `missing`, and `--strict` fails those too (the
whole-round gate: a trajectory that silently dropped its decode_mbu
row must not read as green).
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Callable, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

STATE_PATH = os.path.join(REPO, "benchmarks", ".bench_rows.jsonl")
RESULTS_PATH = os.path.join(REPO, "benchmarks", "RESULTS.md")


# ----------------------------------------------------------------------
# parsing: BENCH_r*.json rounds
# ----------------------------------------------------------------------

def bench_rounds(repo_dir: str = REPO) -> List[dict]:
    """One dict per committed round, ascending: {round, metric, value,
    vs_baseline, substrate, mbu, hop_p50_ratio, bubble_drop, ...} with
    absent riders left out (older rounds predate them). Tolerates both
    driver shapes: a `parsed` object, or the bench line inside `tail`."""
    out = []
    for name in sorted(os.listdir(repo_dir)):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if not m:
            continue
        try:
            with open(os.path.join(repo_dir, name)) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        row = obj.get("parsed") if isinstance(obj, dict) else None
        if not isinstance(row, dict) or "metric" not in row:
            row = {}
            for line in (obj.get("tail", "") if isinstance(obj, dict)
                         else "").splitlines():
                if line.startswith("{"):
                    try:
                        cand = json.loads(line)
                        if isinstance(cand, dict) and "metric" in cand:
                            row = cand
                    except json.JSONDecodeError:
                        pass
        if not row:
            # a round that crashed before printing its row is part of
            # the trajectory too — silence would read as "no round ran"
            out.append({"round": int(m.group(1)), "metric": None,
                        "value": None,
                        "substrate": f"no row (rc={obj.get('rc')})"})
            continue
        entry = {
            "round": int(m.group(1)),
            "metric": row.get("metric"),
            "value": row.get("value"),
            "vs_baseline": row.get("vs_baseline"),
            "substrate": row.get("round_substrate", row.get("platform")),
        }
        dg = row.get("decode_goodput")
        if isinstance(dg, dict) and "mbu" in dg:
            entry["mbu"] = dg["mbu"]
        rt = row.get("relay_transport")
        if isinstance(rt, dict) and "hop_p50_ratio" in rt:
            entry["hop_p50_ratio"] = rt["hop_p50_ratio"]
            entry["bubble_drop"] = rt.get("bubble_drop")
        if row.get("stale_tpu_reference"):
            entry["stale_tpu_reference"] = True
        out.append(entry)
    return sorted(out, key=lambda e: e["round"])


# ----------------------------------------------------------------------
# parsing: run_all rows (state file first, committed table as fallback)
# ----------------------------------------------------------------------

def run_rows(state_path: str = STATE_PATH,
             results_path: str = RESULTS_PATH) -> List[dict]:
    """The latest run_all row per config: the committed RESULTS.md
    table is the floor (values + the k=v detail cells the ratchets
    read), and the machine-readable state file a local round leaves
    behind OVERLAYS it per config — a subset round (`run_all
    --scenarios`) writes only the rows it ran, and exclusivity would
    erase the committed history underneath. Later rows of one config
    supersede earlier ones."""
    latest: dict = {}
    if os.path.exists(results_path):
        _results_md_rows(results_path, latest)
    if os.path.exists(state_path):
        with open(state_path) as f:
            for line in f:
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                r = obj.get("_row")
                if isinstance(r, dict) and "config" in r:
                    latest[r["config"]] = r
    return list(latest.values())


def _results_md_rows(results_path: str, latest: dict) -> None:
    with open(results_path) as f:
        for line in f:
            cells = [c.strip() for c in line.split("|")][1:-1]
            if len(cells) != 6 or cells[0] in ("config", "---") \
                    or set(cells[0]) == {"-"}:
                continue
            config, metric, value, _mfu, platform, details = cells
            row = {"config": config, "metric": metric,
                   "platform": platform, "_details": details}
            try:
                row["value"] = float(value)
            except ValueError:
                row["value"] = value
            # the detail cell is prose-bearing ("note=..."), so k=v
            # extraction is per-key regex, never a naive comma split
            for key in ("ok", "fleet_availability", "fleet_vs_single",
                        "fleet_silently_lost", "coverage",
                        "availability", "slo_verdict", "reconstructed",
                        "host_fraction", "parity_ok",
                        "kvlens_admit_overhead_pct",
                        "caplens_admit_overhead_pct",
                        "thrash_refetch_blocks_at_B",
                        "coldstart_coverage",
                        "overhead_pct"):
                m = re.search(rf"\b{key}=([^,|]+)", details)
                if not m:
                    continue
                v = m.group(1).strip()
                if v in ("True", "False"):
                    row[key] = v == "True"
                else:
                    try:
                        row[key] = float(v)
                    except ValueError:
                        row[key] = v
            latest[config] = row


# ----------------------------------------------------------------------
# the centralized ratchets
# ----------------------------------------------------------------------

class Ratchet:
    """One regression-asserted number: read `field` off the row named
    `config`, compare against `threshold()` (a callable importing the
    floor from the probe module that owns it — one source of truth)
    with `op` ('>=' floors, '<=' ceilings, '==' exact)."""

    def __init__(self, name: str, config: str, field: str, op: str,
                 threshold: Callable[[], float], note: str = ""):
        self.name, self.config, self.field = name, config, field
        self.op, self.threshold, self.note = op, threshold, note

    def evaluate(self, rows: List[dict]) -> dict:
        row = next((r for r in rows if r.get("config") == self.config),
                   None)
        out = {"ratchet": self.name, "config": self.config,
               "field": self.field, "op": self.op, "note": self.note}
        try:
            out["threshold"] = self.threshold()
        except Exception as e:  # noqa: BLE001 — a probe module that no
            # longer imports is itself a finding, not a crash
            out.update({"status": "error",
                        "error": f"threshold import failed: {e}"})
            return out
        if row is None:
            out["status"] = "missing"
            return out
        val = row.get(self.field)
        if val is None:
            out["status"] = "missing"
            out["detail"] = f"row has no {self.field!r} field"
            return out
        out["value"] = val
        thr = out["threshold"]
        ok = {"<=": val <= thr, ">=": val >= thr, "==": val == thr}[
            self.op]
        out["status"] = "ok" if ok else "FAIL"
        return out


def _t(module: str, const: str, scale: float = 1.0):
    def read() -> float:
        import importlib

        return getattr(importlib.import_module(module), const) * scale
    return read


def _const(v: float):
    return lambda: v


RATCHETS: List[Ratchet] = [
    Ratchet("decode_mbu_floor", "decode_mbu", "value", ">=",
            _t("benchmarks.decode_mbu_probe", "MBU_FLOOR", 100.0),
            "live decode MBU %, ratcheted 5->10 (BASELINE.md)"),
    Ratchet("host_fraction_ceiling", "step_timeline", "value", "<=",
            _t("benchmarks.step_timeline_probe", "HOST_FRACTION_CEIL",
               100.0),
            "host-serialization % of decode wall, ratcheted from 54.9"),
    Ratchet("obs_overhead_budget", "obs_overhead", "value", "<=",
            _const(2.0), "obs tax % of a decode step (ISSUE 3 contract)"),
    Ratchet("fleet_overhead_budget", "fleet_overhead", "value", "<=",
            _const(2.0), "obs tax with the fleet surface live"),
    Ratchet("hop_p50_floor", "relay_transport", "value", ">=",
            _t("benchmarks.relay_transport_probe", "HOP_RATIO_FLOOR"),
            "negotiated-transport hop speedup vs nested grpc"),
    Ratchet("chaos_availability_floor", "chaos_resilience", "value",
            ">=",
            _t("benchmarks.chaos_probe", "AVAILABILITY_FLOOR", 100.0),
            "availability % under kill+wedge injection"),
    Ratchet("fleet_availability_floor", "fleet_serving",
            "fleet_availability", ">=",
            _t("benchmarks.fleet_serving_probe", "AVAILABILITY_FLOOR"),
            "router-leg availability through a replica kill"),
    Ratchet("fleet_speedup_floor", "fleet_serving", "fleet_vs_single",
            ">=",
            _t("benchmarks.fleet_serving_probe", "FLEET_SPEEDUP_FLOOR"),
            "fleet delivered tokens/sec vs the unfronted replica"),
    # the fleet KV tier (ISSUE 15): cross-replica block reuse and the
    # warm-vs-cold TTFT win, thresholds owned by the probe
    Ratchet("kvtier_cross_hit_floor", "kv_tier",
            "cross_replica_hit_ratio", ">=",
            _t("benchmarks.kv_tier_probe", "CROSS_HIT_FLOOR"),
            "block hits served from migrated (adopted) blocks"),
    Ratchet("kvtier_ttft_floor", "kv_tier", "ttft_cold_over_warm",
            ">=",
            _t("benchmarks.kv_tier_probe", "TTFT_RATIO_FLOOR"),
            "forced-cold over warm-turn TTFT p95 through the router"),
    # the workload suite: each scenario's SLO verdict is the assert —
    # `ok` carries it (inverted + bundle-verified for breach_chaos)
    Ratchet("workload_chat", "workload_chat", "ok", "==", _const(True),
            "chat scenario SLO verdict"),
    Ratchet("workload_longcontext", "workload_longcontext", "ok", "==",
            _const(True), "long-context scenario SLO verdict"),
    Ratchet("workload_json_mode", "workload_json_mode", "ok", "==",
            _const(True), "constrained-decoding scenario SLO verdict"),
    Ratchet("workload_json_mode_fast", "workload_json_mode_fast", "ok",
            "==", _const(True),
            "constrained decoding on the interleave+overlap hot path"),
    # constrained hot path (ISSUE 16): the on-device DFA walk must beat
    # convoy admission and answer to the SAME host-fraction ceiling as
    # unconstrained decode — both thresholds imported from their owners
    Ratchet("constrained_speedup_floor", "constrained_hotpath", "value",
            ">=",
            _t("benchmarks.constrained_hotpath_probe", "SPEEDUP_FLOOR"),
            "constrained hot-path tokens/sec over the convoy control"),
    Ratchet("constrained_host_fraction", "constrained_hotpath",
            "host_fraction", "<=",
            _t("benchmarks.step_timeline_probe", "HOST_FRACTION_CEIL"),
            "host-serialization fraction with constraints live"),
    # the static-analysis gate (ISSUE 17): the CI gate's wall time is a
    # perf surface too — every new pass (the sharded-program audit most
    # recently) pays against this ceiling instead of silently growing
    Ratchet("analysis_gate_wall_s", "analysis_gate", "value", "<=",
            _t("benchmarks.run_all", "ANALYSIS_GATE_WALL_CEIL_S"),
            "full `python -m dnn_tpu.analysis` gate wall seconds"),
    # the memory-economy observatory (ISSUE 18): the miss-ratio curve
    # must keep predicting ground truth at an untested pool size, the
    # pressured run must bill real thrash, and the reuse-distance
    # tracker must stay inside the admission-path obs budget
    Ratchet("mrc_prediction_error", "kv_economy", "value", "<=",
            _t("benchmarks.kv_economy_probe", "MRC_ERROR_CEIL"),
            "|predicted − measured| block-hit ratio at capacity B"),
    Ratchet("kv_economy_thrash_billed", "kv_economy",
            "thrash_refetch_blocks_at_B", ">=", _const(1.0),
            "evict→refetch blocks billed at the pressured capacity"),
    Ratchet("kvlens_overhead_budget", "obs_overhead",
            "kvlens_admit_overhead_pct", "<=", _const(2.0),
            "admission obs tax % with the reuse-distance tracker live"),
    Ratchet("workload_spec_mix", "workload_spec_mix", "ok", "==",
            _const(True), "speculative-mix scenario SLO verdict"),
    Ratchet("workload_lora", "workload_lora", "ok", "==", _const(True),
            "multi-tenant LoRA scenario SLO verdict"),
    Ratchet("workload_breach_reconstructs", "workload_breach_chaos",
            "ok", "==", _const(True),
            "forced breach produced a reconstructable incident bundle"),
    # the training-step observatory (ISSUE 19): MFU priced off the
    # pinned roofline must clear the estimator-sanity floor, the phase
    # clock must attribute (not lose) the fit wall, and the whole
    # observatory — clock + gradient sentinel — pays against the same
    # 2% obs budget every other surface answers to
    Ratchet("train_mfu_floor", "train_goodput", "value", ">=",
            _t("benchmarks.train_goodput_probe", "MFU_FLOOR"),
            "probe-fit MFU vs the PINNED 1e12 FLOP/s roofline"),
    Ratchet("train_phase_coverage", "train_goodput", "coverage", ">=",
            _t("benchmarks.train_goodput_probe", "COVERAGE_FLOOR"),
            "fraction of fit() wall attributed to a named phase"),
    Ratchet("trainlens_overhead_budget", "train_goodput",
            "overhead_pct", "<=", _const(2.0),
            "TrainClock+GradSentinel tax % of a training step"),
    # the capacity observatory (ISSUE 20): the what-if planner's
    # 2-replica prediction must keep matching the real 2-replica fleet
    # on the identical seeded trace, the cold-start ledger must keep
    # covering the spawn→first-token wall, and the demand estimator in
    # the router admission path pays the same 2% obs budget
    Ratchet("capacity_prediction_error", "capacity_plan", "value",
            "<=", _t("benchmarks.capacity_plan_probe",
                     "PRED_ERROR_CEIL"),
            "|predicted − measured| 2-replica availability"),
    Ratchet("coldstart_coverage", "capacity_plan",
            "coldstart_coverage", ">=",
            _t("benchmarks.capacity_plan_probe",
               "COLDSTART_COVERAGE_FLOOR"),
            "spawn→first-token wall attributed to a named bucket"),
    Ratchet("caplens_overhead_budget", "obs_overhead",
            "caplens_admit_overhead_pct", "<=", _const(2.0),
            "router-admission obs tax % with the demand estimator live"),
]


def check_ratchets(rows: List[dict]) -> List[dict]:
    return [r.evaluate(rows) for r in RATCHETS]


# ----------------------------------------------------------------------
# rendering
# ----------------------------------------------------------------------

def _fmt(v, nd=2) -> str:
    if v is None:
        return "—"
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.{nd}f}".rstrip("0").rstrip(".")
    return str(v)


def trend_table(rounds: List[dict]) -> str:
    """The round-over-round view BENCH_r*.json was always meant to be:
    headline + substrate + the riders, one row per round."""
    lines = ["| round | metric | value | vs_baseline | substrate | "
             "live mbu | hop ratio | bubble drop |",
             "|---|---|---|---|---|---|---|---|"]
    for e in rounds:
        sub = e.get("substrate") or "?"
        if e.get("stale_tpu_reference"):
            sub += " (stale tpu echo)"
        lines.append(
            f"| r{e['round']:02d} | {e.get('metric')} "
            f"| {_fmt(e.get('value'))} | {_fmt(e.get('vs_baseline'))} "
            f"| {sub} | {_fmt(e.get('mbu'), 3)} "
            f"| {_fmt(e.get('hop_p50_ratio'))} "
            f"| {_fmt(e.get('bubble_drop'))} |")
    return "\n".join(lines)


def ratchet_table(verdicts: List[dict]) -> str:
    lines = ["| ratchet | config.field | value | op threshold | status |",
             "|---|---|---|---|---|"]
    for v in verdicts:
        lines.append(
            f"| {v['ratchet']} | {v['config']}.{v['field']} "
            f"| {_fmt(v.get('value'))} | {v['op']} "
            f"{_fmt(v.get('threshold'))} | {v.get('status')} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when any evaluated ratchet FAILs")
    ap.add_argument("--strict", action="store_true",
                    help="with --assert: missing ratchet rows fail too "
                         "(the whole-round gate)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable dump instead of the tables")
    args = ap.parse_args(argv)

    rounds = bench_rounds()
    rows = run_rows()
    verdicts = check_ratchets(rows)
    if args.json:
        print(json.dumps({"rounds": rounds, "ratchets": verdicts},
                         indent=2))
    else:
        print(f"# Perf trajectory — {len(rounds)} committed rounds\n")
        print(trend_table(rounds))
        src = ("RESULTS.md + .bench_rows.jsonl overlay"
               if os.path.exists(STATE_PATH) else "RESULTS.md")
        print(f"\n# Ratchets (rows from {src}; thresholds imported "
              "from their probes)\n")
        print(ratchet_table(verdicts))
    bad = [v for v in verdicts if v.get("status") == "FAIL"
           or (args.strict
               and v.get("status") in ("missing", "error"))]
    if args.do_assert and bad:
        print("ASSERT FAILED: "
              + ", ".join(f"{v['ratchet']}={v.get('status')}"
                          for v in bad), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
