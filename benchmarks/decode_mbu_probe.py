"""Decode MBU probe: the regression-asserted number for ISSUE 6.

PR 5's goodput gauges put the serving decode path at MBU 2.34% on this
host (benchmarks/STUDIES.md §10) and PR 1's HLO audit said why: the
decode lowering moved whole-cache copies per step. This probe turns the
gap into a bench contract, mirroring `obs_overhead`'s <2% row: measure
live `dnn_tpu_mbu` on the DECODE HOT PATH configuration this repo now
ships, and fail (`--assert` / the run_all `decode_mbu` row) when it
regresses below the floor.

Methodology (the §10 recipe, made reproducible):

  * rooflines — on TPU, the per-generation table (utils/flops.py); on a
    CPU host they are MEASURED at probe start (jitted f32 1024^3 matmul
    for FLOPs; preallocated np.copyto, read+write charged, for memory
    bandwidth) unless DNN_TPU_PEAK_FLOPS / DNN_TPU_PEAK_HBM_BW state
    them. §10's original numbers (125.8 GFLOP/s, 15.8 GB/s) came from
    this same pair of probes; an alloc-in-loop copy probe reads ~8x low
    (page faults), which is why the copy target is preallocated;
  * four legs, same model (the §10 shape — 4L/256d GPT, 4 slots,
    4 x 120-token greedy requests, steady-state warm), each with a fresh
    GoodputTracker constructed at the timed round's start. That
    construction point is load-bearing: the tracker's Throughput
    divides by LIFETIME when it is younger than its window, so a
    tracker built before warmup (the LMServer-installed gauge §10
    scraped) silently deflates every rate it reports by the
    construction-to-scrape gap — a measurement artifact this probe
    corrects and STUDIES §11 quantifies:
      - `mbu` (ASSERTED): the ISSUE 12 decode hot path — the §10 dense
        bucketed f32 pool with interleaved chunked prefill + double-
        buffered dispatch live (`prefill_chunk_tokens=16, overlap=True`);
      - `convoy_mbu`: the same pool WITHOUT the overlap machinery (the
        pre-ISSUE-12 path), apples-to-apples with the 2.34% baseline;
      - `dense_mbu`: the plain dense pool (the pre-flag default path);
      - `paged_int8_mbu`: the serving-default paged pool with int8 KV
        and the unrolled decode scan — the quantized rung (its MBU is
        NOT comparable to the f32 legs: int8 legitimately streams
        fewer accounted bytes per position, so equal speed reads
        LOWER; its tokens/sec is the comparable number).
  * the floor applies only where it was calibrated — CPU-substrate
    rooflines (measured or env-stated); a TPU row reports but does not
    gate until a healthy chip recalibrates it (the table peaks are 2
    orders of magnitude above any toy-model CPU figure, so a shared
    floor would be meaningless on both sides).

Standalone:  python benchmarks/decode_mbu_probe.py [--assert]
Suite row:   benchmarks/run_all.py config `decode_mbu` (cpu-runnable).
bench.py attaches measure(light=True)'s gauges to every round's JSON row.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# floor for the asserted leg's MBU on CPU-substrate rooflines.
# Re-calibrated for ISSUE 12 (the overlap/fusion PR): the asserted leg
# is now the serving hot path WITH the overlap machinery live (dense
# bucketed f32 + interleaved chunked prefill + double-buffered
# dispatch) at STEADY-STATE warmup (two warm rounds — the single-warm
# design let bucket-rung recompiles land in the timed round and
# deflate the §11-recorded 15.8%), measuring ~28-29% quiet on this
# host. The floor ratchets 5% -> 10%: ~3x under the measured value so
# scheduler noise can't flake the gate, 2x above the old floor so a
# regression to the pre-overlap path under load still FAILS.
MBU_FLOOR = 0.10

SLOTS = 4
NEW_TOKENS = 120
PROMPT = 8


def host_rooflines():
    """(peak_flops, peak_bytes, source): table on TPU, env override, or
    measured on this host (the §10 probes)."""
    import jax

    from dnn_tpu.utils.flops import device_peak_flops, device_peak_hbm_bw

    if jax.default_backend() == "tpu" or (
            os.environ.get("DNN_TPU_PEAK_FLOPS")
            and os.environ.get("DNN_TPU_PEAK_HBM_BW")):
        pf, pb = device_peak_flops(), device_peak_hbm_bw()
        if pf and pb:
            return pf, pb, ("table" if jax.default_backend() == "tpu"
                            else "env")
    import numpy as np

    x = jax.random.normal(jax.random.PRNGKey(0), (1024, 1024))
    mm = jax.jit(lambda a, b: a @ b)
    mm(x, x).block_until_ready()
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 0.5:
        mm(x, x).block_until_ready()
        n += 1
    peak_f = n * 2 * 1024 ** 3 / (time.perf_counter() - t0)
    a = np.random.rand(1 << 25)
    b = np.empty_like(a)
    np.copyto(b, a)  # fault the pages OUTSIDE the timed loop
    t0 = time.perf_counter()
    m = 0
    while time.perf_counter() - t0 < 0.5:
        np.copyto(b, a)
        m += 1
    peak_b = m * a.nbytes * 2 / (time.perf_counter() - t0)
    return peak_f, peak_b, "measured"


def _build(cfg, prepared, **kw):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    return ContinuousBatcher(cfg, prepared, slots=SLOTS,
                             max_len=cfg.block_size, prompt_pad=16, **kw)


def _leg(cfg, prepared, peak_f, peak_b, *, new_tokens, kv_dtype=None,
         reps: int = 3, warm: int = 2, **kw):
    """One serving leg: `warm` rounds (two by default — the first grows
    the bucket ladder, the second compiles the admission programs at
    the grown rungs, so the timed rounds measure serving rather than
    one-time compiles), then `reps` timed rounds, each with a FRESH
    GoodputTracker whose lifetime IS its timed window; the best round
    is the leg's number (utilization is a capability measure — a
    scheduler-noise-slowed round under-reports the path, it doesn't
    refute it; the §8 lesson applied to rates)."""
    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.obs.goodput import GoodputTracker, model_cost

    srv = _build(cfg, prepared, kv_dtype=kv_dtype, **kw)

    def round_():
        for i in range(SLOTS):
            srv.submit(np.arange(1, PROMPT + 1), new_tokens, seed=i)
        srv.drain()
        srv.results.clear()
        srv.finish_reasons.clear()

    for _ in range(warm):  # compile + absorb first-dispatch overheads
        round_()
    best = None
    for _ in range(reps):
        tracker = GoodputTracker(
            model_cost(cfg, prepared, kv_dtype=kv_dtype or jnp.float32),
            peak_flops=peak_f, peak_bytes=peak_b, window_s=1e9)
        srv.goodput = tracker
        t0 = time.perf_counter()
        round_()
        dt = time.perf_counter() - t0
        row = {
            "mbu": tracker.mbu(),
            "mfu": tracker.mfu(),
            "tokens_per_sec": round(tracker.tokens_per_sec(), 1),
            "round_s": round(dt, 3),
        }
        if best is None or row["mbu"] > best["mbu"]:
            best = row
    return best


def measure(light: bool = False) -> dict:
    """Both legs -> one row. `light` (bench.py's per-round attachment)
    runs a shorter decode round and skips the baseline leg."""
    import jax

    from dnn_tpu import obs
    from dnn_tpu.models import gpt

    was = obs.enabled()
    obs.set_enabled(True)  # the tracker is fed from obs-gated blocks
    try:
        peak_f, peak_b, src = host_rooflines()
        cfg = gpt.GPTConfig(block_size=256, vocab_size=512, n_layer=4,
                            n_head=4, n_embd=256)
        prepared = gpt.prepare_stacked(
            gpt.init(jax.random.PRNGKey(0), cfg), cfg)
        new_tokens = 40 if light else NEW_TOKENS
        # the asserted leg is the post-ISSUE-12 decode hot path: the
        # s10 shape with the overlap machinery live — interleaved
        # chunked prefill + double-buffered dispatch
        s10 = _leg(cfg, prepared, peak_f, peak_b, new_tokens=new_tokens,
                   reps=2 if light else 3, decode_buckets=True,
                   prefill_chunk_tokens=16, overlap=True)
        row = {
            "mbu": round(s10["mbu"], 4),
            "mfu": round(s10["mfu"], 4),
            "tokens_per_sec": s10["tokens_per_sec"],
            "peak_flops": round(peak_f, 1),
            "peak_hbm_bw": round(peak_b, 1),
            "rooflines": src,
            "platform": jax.default_backend(),
            "slots": SLOTS, "new_tokens": new_tokens,
            "asserted_leg": "decode_buckets=True f32 + "
                            "prefill_chunk_tokens=16 + overlap (the s10 "
                            "config on the ISSUE 12 hot path)",
            "vs_studies_s10": round(s10["mbu"] / 0.0234, 2),
        }
        if not light:
            convoy = _leg(cfg, prepared, peak_f, peak_b,
                          new_tokens=new_tokens, decode_buckets=True)
            row["convoy_mbu"] = round(convoy["mbu"], 4)
            row["convoy_tokens_per_sec"] = convoy["tokens_per_sec"]
            dense = _leg(cfg, prepared, peak_f, peak_b,
                         new_tokens=new_tokens, kv="dense")
            pq = _leg(cfg, prepared, peak_f, peak_b,
                      new_tokens=new_tokens, kv="paged", kv_dtype="int8",
                      unroll_layers=True)
            row["dense_mbu"] = round(dense["mbu"], 4)
            row["paged_int8_mbu"] = round(pq["mbu"], 4)
            row["paged_int8_tokens_per_sec"] = pq["tokens_per_sec"]
        # the floor gates only the substrate it was calibrated on (see
        # module docstring); a TPU row reports honestly without gating
        gated = src != "table"
        row["floor"] = MBU_FLOOR if gated else None
        row["ok"] = bool(s10["mbu"] >= MBU_FLOOR) if gated else True
        return row
    finally:
        obs.set_enabled(was)


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure()
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: decode MBU {row['mbu'] * 100:.2f}% < "
              f"{MBU_FLOOR * 100:.0f}% floor (§10-config leg)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
