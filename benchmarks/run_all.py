"""Full benchmark suite: measures every config in BASELINE.md.

The reference publishes no numbers (SURVEY §6), so this suite produces the
framework's own measured table — one JSON line per config plus a markdown
table written to benchmarks/RESULTS.md.

Two sections:

  * device:  whatever `jax.devices()` resolves to (the real TPU chip under
    axon; CPU elsewhere) — single-chip model throughput. EACH device
    config runs in its OWN subprocess with its own timeout: the chip this
    suite runs on is documented to wedge mid-benchmark (VERDICT r4 weak
    #2 — sectioned retry lost the same tail twice, deterministically), so
    one wedging config must cost exactly that config, never the tail.
    Each config's rows persist to benchmarks/.bench_rows.jsonl the
    moment the config finishes (ok OR failed-with-salvage); `--resume`
    skips configs that completed ok and RETRIES failed ones.
  * cpu-mesh: 8 virtual CPU devices — the multi-stage pipeline forms and
    p50 inter-stage hop latency. These validate the parallel machinery;
    their absolute numbers are CPU numbers and are labeled as such. The
    <2 ms hop target is a v5e-8 ICI claim the single-chip environment
    cannot measure (BASELINE.md "north star"). This section cannot wedge
    (no chip involved), so it keeps the coarser one-subprocess salvage.

Usage:
    python benchmarks/run_all.py                   # both sections + RESULTS.md
    python benchmarks/run_all.py --resume          # skip completed configs
    python benchmarks/run_all.py --section device --config gpt2_fwd  # one
    python benchmarks/run_all.py --section cpu_mesh
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # script lives in benchmarks/; import dnn_tpu from root
    sys.path.insert(0, REPO)

STATE_PATH = os.path.join(REPO, "benchmarks", ".bench_rows.jsonl")


def _emit(results, **row):
    # provenance (ISSUE 8/12): every row that knows its platform also
    # carries the contract-named `round_substrate` alias bench.py rows
    # use, so `--require-substrate`-style trajectory filters read one
    # key across both artifacts
    if "platform" in row and "round_substrate" not in row:
        row["round_substrate"] = row["platform"]
    results.append(row)
    print(json.dumps(row), flush=True)


# ----------------------------------------------------------------------
# section: device (single chip / default platform) — one config per
# subprocess; each function stands alone and re-creates what it needs
# ----------------------------------------------------------------------

DEVICE_CONFIGS = []  # [(name, fn, tpu_only)] in table order


def device_config(name, tpu_only=False):
    def deco(fn):
        DEVICE_CONFIGS.append((name, fn, tpu_only))
        return fn
    return deco


def _platform():
    import jax

    return jax.default_backend()


def _with_mfu(row, flops_per_item, items_per_sec):
    from dnn_tpu.utils.flops import mfu

    m = mfu(flops_per_item, items_per_sec)
    if m is not None:
        row["mfu"] = round(m, 4)
    return row


@device_config("cifar_cnn_fwd")
def dev_cifar_fwd():
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import cifar
    from dnn_tpu.registry import get_model
    from dnn_tpu.utils.flops import (
        cifar_forward_bytes, cifar_forward_flops, mfu,
        roofline_items_per_sec,
    )
    from dnn_tpu.utils.timing import device_time

    results = []
    # config 1 (full-model form): CIFAR CNN forward — bf16 operands like
    # the GPT rows, so the mfu column divides a bf16-executed workload by
    # the bf16 peak table
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    # B=1024: below ~1024 images a forward is so short (<0.2 ms) that the
    # tunnel's dispatch floor dominates and the row measures host
    # overhead, not the chip (benchmarks/cifar_mfu_probe.py batch sweep)
    batch = 1024
    x = jnp.asarray(spec.example_input(batch_size=batch))
    fn = jax.jit(cifar.make_apply(compute_dtype=jnp.bfloat16))
    # sub-ms per batch: needs many reps per sample or the slope drowns in
    # sync jitter
    dt = device_time(fn, params, x, n1=100, n2=400, trials=5)
    ips = batch / dt
    row = _with_mfu({}, cifar_forward_flops(1), ips)
    # arithmetic intensity (~60 FLOPs/byte) is far below the TPU ridge
    # point, so the MFU ceiling is the ROOFLINE cap, not 100% — report
    # both (dnn_tpu/utils/flops.cifar_forward_bytes has the accounting)
    cap = roofline_items_per_sec(
        cifar_forward_flops(1), cifar_forward_bytes(batch) / batch)
    if cap is not None:
        row["mfu_roofline_cap"] = round(mfu(cifar_forward_flops(1), cap), 4)
        row["roofline_frac"] = round(ips / cap, 4)
    _emit(results, config="cifar_cnn_fwd", metric="images_per_sec",
          value=round(ips, 1), platform=_platform(), batch=batch,
          dtype="bf16", **row)
    return results


@device_config("gpt_fwd")
def dev_gpt_fwd():
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.utils.flops import gpt_forward_flops
    from dnn_tpu.utils.timing import device_time

    results = []
    # config 4/5 (full-model form): GPT-2 small + medium forward, bf16
    # operands + bf16 logit store (the serving configuration — gpt.head)
    for preset, b, s in (("gpt2", 8, 512), ("gpt2-medium", 4, 512)):
        cfg = gpt.PRESETS[preset]
        p = gpt.init(jax.random.PRNGKey(0), cfg)
        prepared = gpt.prepare_stacked(p, cfg)
        fn = jax.jit(gpt.make_apply_stacked(
            cfg, compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16))
        ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                 cfg.vocab_size, dtype=jnp.int32)
        dt = device_time(fn, prepared, ids)
        tps = b * s / dt
        _emit(results, config=f"{preset}_fwd", metric="tokens_per_sec",
              value=round(tps, 1), platform=_platform(), batch=b, seq=s,
              logits="bf16",
              **_with_mfu({}, gpt_forward_flops(cfg, b, s) / (b * s), tps))
    return results


@device_config("tinyllama_fwd", tpu_only=True)
def dev_tinyllama_fwd():
    # TPU-only: a 1.1B bf16 forward on a CPU host would blow the budget
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt, llama
    from dnn_tpu.utils.flops import llama_forward_flops
    from dnn_tpu.utils.timing import device_time

    results = []
    ll_cfg = llama.PRESETS["tinyllama-1.1b"]
    ll_prep = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), ll_cfg, dtype=jnp.bfloat16),
        ll_cfg)
    ll_fn = jax.jit(llama.make_apply_stacked(
        ll_cfg, compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16))
    b, s = 8, 512
    ll_ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                ll_cfg.vocab_size, dtype=jnp.int32)
    dt = device_time(ll_fn, ll_prep, ll_ids, n1=1, n2=3)
    tps = b * s / dt
    _emit(results, config="tinyllama_fwd", metric="tokens_per_sec",
          value=round(tps, 1), platform=_platform(), batch=b, seq=s,
          logits="bf16",
          **_with_mfu({}, llama_forward_flops(ll_cfg, b, s) / (b * s), tps))
    return results


@device_config("tinyllama_decode", tpu_only=True)
def dev_tinyllama_decode():
    # TinyLlama decode matrix — the GQA bandwidth claim, measured. The
    # cache is stored at KV-head width (llama.init_cache): KV*D = 256
    # floats/position/layer vs model width 2048, so at equal batch/seq
    # TinyLlama streams 8x fewer cache bytes per step than an MHA model
    # of its width. Rows mirror the GPT-2 matrix (same batch/new_tokens)
    # so bytes/token and MBU are directly comparable across families.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt, llama
    from dnn_tpu.quant import param_bytes, quantize_tree
    from dnn_tpu.utils.flops import mbu
    from dnn_tpu.utils.timing import device_time

    results = []
    ll_cfg = llama.PRESETS["tinyllama-1.1b"]
    ll_prep = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), ll_cfg, dtype=jnp.bfloat16),
        ll_cfg)
    db, dprompt, dnew = 8, 16, 128
    d_ids = jax.random.randint(jax.random.PRNGKey(4), (db, dprompt), 0,
                               ll_cfg.vocab_size, dtype=jnp.int32)
    d_smax = dprompt + dnew
    ll_cache_elems = (2 * ll_cfg.n_layer * db
                      * ll_cfg.n_kv_head * ll_cfg.head_dim * d_smax)
    ll_q = quantize_tree(ll_prep)
    rng_d = jax.random.PRNGKey(5)
    for name, weights, kvd, itemsize in (
            ("w_bf16_kv_bf16", ll_prep, jnp.bfloat16, 2),
            ("w_int8_kv_int8", ll_q, "int8", 1)):
        gfn = llama.make_generate(
            ll_cfg, max_new_tokens=dnew, compute_dtype=jnp.bfloat16,
            kv_dtype=kvd)
        dt = device_time(gfn, weights, d_ids, rng_d, n1=1, n2=3)
        tps = db * dnew / dt
        # int8 cache rides per-(position, kv-head) f32 scales for K and
        # V: cache_elems / head_dim scale entries x 4 bytes
        bpt = (param_bytes(weights) + ll_cache_elems * itemsize
               + (ll_cache_elems // ll_cfg.head_dim * 4
                  if kvd == "int8" else 0)) / db
        row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
        u = mbu(bpt, tps)
        if u is not None:
            row["mbu"] = round(u, 4)
        _emit(results, config=f"tinyllama_decode_{name}",
              metric="tokens_per_sec", value=round(tps, 1),
              platform=_platform(), batch=db, new_tokens=dnew, **row)
    return results


@device_config("llama_longctx_decode", tpu_only=True)
def dev_llama_longctx_decode():
    # Sliding-window ring decode (models/llama.py rolling path) vs dense
    # long-context decode — the Mistral-class long-context claim,
    # measured as a mechanism bench: at s_max = 3x the window the ring
    # streams W cache positions per step while the dense cache streams
    # s_max. GQA caches are small next to the weights, so the comparison
    # runs an MHA-width variant (n_kv_head = n_head) of the TinyLlama
    # shape where the cache is ~half the decode traffic — random-init
    # throughput probe, labeled as such.
    #
    # The dense leg runs BOTH attention paths: the XLA einsum and the
    # Pallas streaming decode kernel (ops/pallas/cached_attention
    # decode_attention) — the round-4 table showed the einsum path at 13%
    # MBU here (VERDICT r5 ask #3); the kernel leg measures whether
    # streaming the cache in few-big-DMA form closes the gap.
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt, llama
    from dnn_tpu.quant import param_bytes
    from dnn_tpu.utils.flops import mbu
    from dnn_tpu.utils.timing import device_time

    results = []
    ll_cfg = llama.PRESETS["tinyllama-1.1b"]
    swb, swprompt, swnew, sww = 8, 1024, 512, 512
    sw_smax = swprompt + swnew
    mha_cfg = _dc.replace(ll_cfg, n_kv_head=ll_cfg.n_head, block_size=2048)
    sw_prep = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(7), mha_cfg, dtype=jnp.bfloat16),
        mha_cfg)
    sw_ids = jax.random.randint(jax.random.PRNGKey(8), (swb, swprompt),
                                0, mha_cfg.vocab_size, dtype=jnp.int32)
    rng_d = jax.random.PRNGKey(5)
    for name, cfg_v, cache_pos, kernel in (
            ("dense", mha_cfg, sw_smax, False),
            ("dense_kernel", mha_cfg, sw_smax, True),
            ("ring", _dc.replace(mha_cfg, sliding_window=sww), sww, False)):
        gfn = llama.make_generate(
            cfg_v, max_new_tokens=swnew, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16, attn_kernel=kernel)
        # the 1024-token prefill would dilute a whole-call rate (~10% of
        # the call): subtract a max_new=1 run so tps counts DECODE steps
        # against decode time
        gfn1 = llama.make_generate(
            cfg_v, max_new_tokens=1, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16, attn_kernel=kernel)
        dt_full = device_time(gfn, sw_prep, sw_ids, rng_d, n1=1, n2=2)
        dt_pre = device_time(gfn1, sw_prep, sw_ids, rng_d, n1=1, n2=2)
        dt = max(dt_full - dt_pre, 1e-9)
        tps = swb * (swnew - 1) / dt
        cache_bytes = (2 * cfg_v.n_layer * swb * cfg_v.n_kv_head
                       * cfg_v.head_dim * cache_pos) * 2
        bpt = (param_bytes(sw_prep) + cache_bytes) / swb
        row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
        u = mbu(bpt, tps)
        if u is not None:
            row["mbu"] = round(u, 4)
        _emit(results, config=f"llama_mha_longctx_decode_{name}",
              metric="tokens_per_sec", value=round(tps, 1),
              platform=_platform(), batch=swb, prompt=swprompt,
              new_tokens=swnew,
              window=(sww if cfg_v.sliding_window else 0), **row)
    return results


@device_config("gpt2_train_step")
def dev_gpt2_train_step():
    # Training step (fwd + bwd + adamw update) — nothing else in the
    # table measures the backward pass. bf16 compute, f32 params/
    # optimizer, the single-chip form of train.make_train_step.
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu.models import gpt
    from dnn_tpu.train import cross_entropy, make_train_step
    from dnn_tpu.utils.flops import gpt_train_step_flops
    from dnn_tpu.utils.timing import device_time

    results = []
    t_cfg = gpt.PRESETS["gpt2"]
    t_prep = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), t_cfg),
                                 t_cfg)
    t_apply = gpt.make_apply_stacked(t_cfg, compute_dtype=jnp.bfloat16)

    def t_loss(p, batch):
        inp, tgt = batch
        return cross_entropy(t_apply(p, inp), tgt)

    t_opt = optax.adamw(1e-4)
    t_state = t_opt.init(t_prep)
    t_step = make_train_step(t_loss, t_opt)
    tb, ts = 8, 512
    t_inp = jax.random.randint(jax.random.PRNGKey(1), (tb, ts), 0,
                               t_cfg.vocab_size, dtype=jnp.int32)
    t_tgt = jax.random.randint(jax.random.PRNGKey(2), (tb, ts), 0,
                               t_cfg.vocab_size, dtype=jnp.int32)

    def t_run(p, s, b):  # time the whole step; updates discarded
        p2, s2, loss = t_step(p, s, b)
        return loss

    dt = device_time(t_run, t_prep, t_state, (t_inp, t_tgt), n1=1, n2=3)
    tps = tb * ts / dt
    _emit(results, config="gpt2_train_step", metric="tokens_per_sec",
          value=round(tps, 1), platform=_platform(), batch=tb, seq=ts,
          optimizer="adamw",
          **_with_mfu({}, gpt_train_step_flops(t_cfg, tb, ts) / (tb * ts),
                      tps))
    return results


@device_config("gpt2_generate_kvcache")
def dev_gpt2_generate_kvcache():
    # KV-cache generation throughput (the serving path the reference
    # lacks)
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    b, prompt_len, new_tokens = 8, 16, 128
    gen_fn = gen.make_generate(
        cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16)
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    dt = device_time(gen_fn, prepared, ids, rng, n1=1, n2=3)
    _emit(results, config="gpt2_generate_kvcache", metric="tokens_per_sec",
          value=round(b * new_tokens / dt, 1), platform=_platform(),
          batch=b, new_tokens=new_tokens)
    return results


def _to_bf16(tree):
    import jax.numpy as jnp
    import jax.tree as jtree

    return jtree.map(
        lambda a: a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and a.dtype == jnp.float32 and a.ndim >= 2
        else a, tree)


@device_config("gpt2_decode_matrix")
def dev_gpt2_decode_matrix():
    # quantized decode matrix: weight-storage x cache-storage. Decode is
    # HBM-bandwidth-bound (every token streams weights + cache once —
    # dnn_tpu/quant.py:1-9), so each row reports bytes/token and MBU
    # alongside tok/s: the speedup should track the byte ratio.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.quant import param_bytes, quantize_gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.utils.flops import mbu
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    b, prompt_len, new_tokens = 8, 16, 128
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    s_max = prompt_len + new_tokens
    head_dim = cfg.n_embd  # per layer: H * D = C
    cache_elems = 2 * cfg.n_layer * b * head_dim * s_max  # K and V
    q_prepared = quantize_gpt(prepared)
    q4_prepared = quantize_gpt(prepared, bits=4)  # group-wise int4
    bf16_prepared = _to_bf16(prepared)
    variants = (
        # kv dtype must be EXPLICIT f32 for the baseline: with kv=None,
        # make_generate follows compute_dtype (bf16 here) and the "f32
        # cache" row would silently run a bf16 cache
        ("w_f32_kv_f32", prepared, jnp.float32, 4),
        ("w_bf16_kv_bf16", bf16_prepared, jnp.bfloat16, 2),
        ("w_int8_kv_bf16", q_prepared, jnp.bfloat16, 2),
        ("w_int8_kv_int8", q_prepared, "int8", 1),
        # int4 weights (dnn_tpu/quant.py quantize_tensor_int4): halves
        # the weight-byte term again IF the S4 operand read really packs
        # two-per-byte on this chip — this row is the measurement that
        # decides (param_bytes charges 0.5 B/wt; a tok/s that does not
        # beat int8 falsifies the packing assumption, which the docs
        # state as a claim-to-measure, not a fact)
        ("w_int4_kv_int8", q4_prepared, "int8", 1),
    )
    for name, weights, kv, cache_itemsize in variants:
        gfn = gen.make_generate(
            cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=kv)
        dt = device_time(gfn, weights, ids, rng, n1=1, n2=3)
        tps = b * new_tokens / dt
        # bytes one token streams: its share of the weights + the full
        # static cache allocation (int8 scales ride along at 1/D per elem)
        bpt = (param_bytes(weights)
               + cache_elems * cache_itemsize
               + (cache_elems // (cfg.n_embd // cfg.n_head)
                  * 4 if kv == "int8" else 0)) / b
        row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
        u = mbu(bpt, tps)
        if u is not None:
            row["mbu"] = round(u, 4)
        _emit(results, config=f"gpt2_decode_{name}",
              metric="tokens_per_sec", value=round(tps, 1),
              platform=_platform(), batch=b, new_tokens=new_tokens, **row)
    return results


@device_config("gpt2_decode_attnkernel", tpu_only=True)
def dev_gpt2_decode_attnkernel():
    # Pallas cached-attention decode kernel, before/after: same weights,
    # same cache dtype, einsum vs kernel attention. Shapes chosen so the
    # cache tiles the kernel's 128-blocks (prompt 128 + 128 new = S 256).
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.quant import param_bytes, quantize_gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.utils.flops import mbu
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    q_prepared = quantize_gpt(prepared)
    bf16_prepared = _to_bf16(prepared)
    rng = jax.random.PRNGKey(2)
    head_dim = cfg.n_embd
    kb, kprompt, knew = 8, 128, 128
    k_ids = jax.random.randint(jax.random.PRNGKey(3), (kb, kprompt), 0,
                               cfg.vocab_size, dtype=jnp.int32)
    k_smax = kprompt + knew
    k_cache_elems = 2 * cfg.n_layer * kb * head_dim * k_smax
    for name, weights, kv, cache_itemsize in (
            ("w_bf16_kv_bf16", bf16_prepared, jnp.bfloat16, 2),
            ("w_int8_kv_int8", q_prepared, "int8", 1)):
        row = {}
        for mode, ak in (("einsum", False), ("kernel", True)):
            gfn = gen.make_generate(
                cfg, max_new_tokens=knew, compute_dtype=jnp.bfloat16,
                kv_dtype=kv, attn_kernel=ak)
            dt = device_time(gfn, weights, k_ids, rng, n1=1, n2=3)
            row[f"tps_{mode}"] = round(kb * knew / dt, 1)
        bpt = (param_bytes(weights) + k_cache_elems * cache_itemsize
               + (k_cache_elems // (cfg.n_embd // cfg.n_head) * 4
                  if kv == "int8" else 0)) / kb
        u = mbu(bpt, row["tps_kernel"])
        if u is not None:
            row["mbu_kernel"] = round(u, 4)
        _emit(results, config=f"gpt2_decode_attnkernel_{name}",
              metric="kernel_vs_einsum_speedup",
              value=round(row["tps_kernel"] / row["tps_einsum"], 3),
              platform=_platform(), batch=kb, prompt=kprompt,
              new_tokens=knew,
              bytes_per_token_mb=round(bpt / 1e6, 2), **row)
    return results


@device_config("gpt2_decode_top_p_tax")
def dev_gpt2_decode_top_p_tax():
    # top_p decode tax: nucleus sampling rides a static top-k prefilter
    # (generate.TOP_P_PREFILTER_K ranked candidates + an O(V) logsumexp
    # instead of a full-vocab sort per step). Both legs sample at
    # temperature=1.0 so the delta isolates the FILTER's cost.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    b, prompt_len, new_tokens = 8, 16, 128
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    tps_by_mode = {}
    for mode, tp in (("off", None), ("on", 0.9)):
        gfn = gen.make_generate(
            cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16, temperature=1.0, top_p=tp)
        dt = device_time(gfn, bf16_prepared, ids, rng, n1=1, n2=3)
        tps_by_mode[mode] = b * new_tokens / dt
    overhead = tps_by_mode["off"] / tps_by_mode["on"] - 1.0
    _emit(results, config="gpt2_decode_top_p_tax", metric="overhead_pct",
          value=round(overhead * 100, 2), platform=_platform(), batch=b,
          new_tokens=new_tokens,
          tps_top_p_off=round(tps_by_mode["off"], 1),
          tps_top_p_on=round(tps_by_mode["on"], 1),
          note=f"top_p=0.9 via top-{gen.TOP_P_PREFILTER_K} prefilter "
               "(bit-identical to the full-vocab filter when the nucleus "
               "fits inside k)")
    return results


@device_config("obs_overhead")
def dev_obs_overhead():
    # observability tax on the continuous-batching decode step:
    # instrumented (traced requests + per-step metrics) vs the
    # DNN_TPU_OBS=off gate, alternating the gate EVERY step and
    # comparing the two step-time populations' medians
    # (benchmarks/obs_overhead_probe.py documents why coarser A/B
    # designs all produced measurement artifacts on this host). The
    # layer's contract is < 2% (ISSUE 3); `ok` records the verdict.
    from benchmarks.obs_overhead_probe import (
        measure,
        measure_caplens,
        measure_kvlens,
        measure_kvtier,
    )

    results = []
    row = measure()
    overhead = row.pop("overhead_frac")
    # the KV-tier admission leg (ISSUE 15): the radix lookup + its
    # block-granular counters/gauges in the admission path, same
    # contract — all legs must hold or the row is red
    kv = measure_kvtier()
    kv_overhead = kv.pop("kvtier_admit_overhead_frac")
    row.update(kv)
    row["kvtier_admit_overhead_pct"] = round(kv_overhead * 100, 2)
    # the kvlens leg (ISSUE 18): the same admission wall with the
    # reuse-distance tracker LIVE — blake2s chunk digests + SHARDS
    # sampling + LRU-stack bookkeeping in the ON population, one gate
    # check in the OFF population; same contract
    kl = measure_kvlens()
    kl_overhead = kl.pop("kvlens_admit_overhead_frac")
    row.update(kl)
    row["kvlens_admit_overhead_pct"] = round(kl_overhead * 100, 2)
    # the caplens leg (ISSUE 20): the router admission wall with the
    # capacity observatory LIVE — arrival ring + dispersion window +
    # conditioned service reservoir in the ON population; same contract
    cl = measure_caplens()
    cl_overhead = cl.pop("caplens_admit_overhead_frac")
    row.update(cl)
    row["caplens_admit_overhead_pct"] = round(cl_overhead * 100, 2)
    _emit(results, config="obs_overhead", metric="overhead_pct",
          value=round(overhead * 100, 2), platform=_platform(),
          ok=bool(overhead < 0.02 and kv_overhead < 0.02
                  and kl_overhead < 0.02 and cl_overhead < 0.02),
          note="serving decode step, obs on (traced) vs off, per-step "
               "interleave; + kvtier radix-admission leg "
               "(per-admission interleave); + kvlens reuse-distance "
               "leg (tracker live on admission); + caplens router-"
               "admission leg (demand estimator live); contract < 2% "
               "on all",
          **row)
    return results


@device_config("fleet_overhead")
def dev_fleet_overhead():
    # fleet-era observability tax: the obs_overhead loop with the PR-5
    # surface live — per-step goodput (MFU/MBU/SLO window) updates on
    # the pool, and a real FleetCollector polling this process's own
    # /metrics + /statusz + /trace.jsonl endpoint every 200 ms through
    # the timed window. Same <2% decode-step contract.
    from benchmarks.obs_overhead_probe import measure_fleet

    results = []
    row = measure_fleet()
    overhead = row.pop("overhead_frac")
    _emit(results, config="fleet_overhead", metric="overhead_pct",
          value=round(overhead * 100, 2), platform=_platform(),
          ok=bool(overhead < 0.02),
          note="obs_overhead + goodput tracker + in-process fleet "
               "poller @200ms; contract < 2%", **row)
    return results


@device_config("relay_transport")
def dev_relay_transport():
    # ISSUE 7: the pluggable-transport A-B contract on the 2-stage cifar
    # config — real stage-server subprocesses, per-hop latency off the
    # stages' own /metrics summaries and the stitched bubble fraction
    # off the fleet collector's critical-path arithmetic (never ad-hoc
    # timers). Asserted floors: negotiated-auto streamed hop p50 <= 1/5
    # of the nested-grpc hop p50, and the stitched warm bubble <= 1/2 of
    # the nested leg's (STUDIES §10 recorded 75.9% for the baseline).
    from benchmarks.relay_transport_probe import (
        BUBBLE_DROP_FLOOR,
        HOP_RATIO_FLOOR,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    ratio = row.pop("hop_p50_ratio")
    _emit(results, config="relay_transport", metric="hop_p50_ratio",
          value=ratio, ok=ok,
          note=f"negotiated-auto ({row['auto']['negotiated']}+streamed) "
               f"vs nested-grpc per-hop p50; floors: hop ratio >= "
               f"{HOP_RATIO_FLOOR:.0f}x, stitched bubble drop >= "
               f"{BUBBLE_DROP_FLOOR:.0f}x (recorded §10 baseline 75.9%)",
          **row)
    return results


@device_config("decode_mbu")
def dev_decode_mbu():
    # ISSUE 6: live MBU of the decode hot path from the goodput gauges,
    # asserted against an absolute floor on CPU-substrate rooflines —
    # the MBU analog of the obs_overhead <2% contract. The asserted leg
    # is STUDIES §10's exact configuration (dense bucketed f32) so the
    # number is apples-to-apples with the recorded 2.34% baseline; the
    # dense and paged-int8 legs ride along unasserted. A TPU row
    # reports without gating until a healthy chip recalibrates the
    # floor (benchmarks/decode_mbu_probe.py documents the methodology).
    from benchmarks.decode_mbu_probe import MBU_FLOOR, measure

    results = []
    row = measure()
    ok = row.pop("ok")
    mbu = row.pop("mbu")
    _emit(results, config="decode_mbu", metric="mbu_pct",
          value=round(mbu * 100, 2), ok=ok,
          note=f"decode hot path live dnn_tpu_mbu (ISSUE 12: asserted "
               f"leg now runs interleaved prefill + overlap at steady-"
               f"state warm); floor {MBU_FLOOR * 100:.0f}% (ratcheted "
               "5%->10%) on CPU-substrate rooflines (report-only on "
               "TPU table peaks); §10 baseline 2.34%",
          **row)
    return results


# ISSUE 17: the gate's wall-time budget is now a RATCHET, not a note —
# ledger.py reads this ceiling against the analysis_gate row. Measured
# ~22 s CPU with the sharded-program audit live (the four compiled
# sharded programs cost ~6 s of it); the ceiling leaves headroom for
# slower CI hosts, and any future pass that blows it must either pay
# down the gate or raise the number in review, on the record.
ANALYSIS_GATE_WALL_CEIL_S = 60.0


@device_config("analysis_gate")
def dev_analysis_gate():
    # ISSUE 10: the static-analysis CI gate as a run_all row — wall
    # time (ratcheted against ANALYSIS_GATE_WALL_CEIL_S; ~22 s CPU
    # since the ISSUE 17 sharded-program audit joined) plus the
    # finding counts, nonzero subprocess exit (an UNJUSTIFIED finding)
    # recorded as ok=False. Runs the full gate: AST lint (TPU+CON+SHD
    # rules), protocol state-machine pass, jaxpr program pass, and the
    # compiled sharded-program audit (SHD007-009).
    results = []
    t0 = time.perf_counter()
    rc, stdout, stderr = None, "", ""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "dnn_tpu.analysis", "--json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     PYTHONPATH=REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", "")),
            timeout=300)
        rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # a hung gate must still emit an ok=False row, never lose the
        # config to an uncaught exception
        rc, stderr = -1, f"gate exceeded 300s: {e}"
    wall_s = time.perf_counter() - t0
    counts = {"new": -1, "suppressed": -1, "stale": -1}
    try:
        rep = json.loads(stdout)
        counts = {"new": len(rep.get("new", ())),
                  "suppressed": len(rep.get("suppressed", ())),
                  "stale": len(rep.get("stale_baseline", ()))}
    except (json.JSONDecodeError, ValueError):
        pass  # ok=False below carries the failure; stderr in the note
    _emit(results, config="analysis_gate", metric="gate_wall_s",
          value=round(wall_s, 2), platform=_platform(),
          ok=bool(rc == 0),
          findings_new=counts["new"],
          findings_suppressed=counts["suppressed"],
          baseline_stale=counts["stale"],
          exit_code=rc,
          note="python -m dnn_tpu.analysis (AST lint TPU001-006 + "
               "CON001-006 + SHD001-006, protocol machines PRO001-004, "
               "jaxpr program pass PRG001-004, sharded-program audit "
               "SHD007-009); wall ratcheted <= "
               f"{ANALYSIS_GATE_WALL_CEIL_S:.0f}s (ledger.py); nonzero "
               "exit = unjustified finding"
               + ("" if rc == 0 else f"; stderr: {stderr[-200:]}"))
    return results


@device_config("chaos_resilience")
def dev_chaos_resilience():
    # ISSUE 8: availability + p99 TTFT under the STANDARD FaultPlan
    # (one stage kill + one injected wedge) against a real supervised
    # 2-stage pipeline with open-loop load — the resilience contract as
    # a regression-asserted row, like obs_overhead's <2% and
    # relay_transport's hop floors. Floors: >=99% of requests
    # completed-or-explicitly-rejected with ZERO silently lost,
    # post-recovery p99 TTFT <= 10x quiet p99, and every injected fault
    # paired with its supervisor_restart recovery event in the dumped
    # flight ring (benchmarks/chaos_probe.py).
    from benchmarks.chaos_probe import (
        AVAILABILITY_FLOOR,
        TTFT_RATIO_CEIL,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    avail = row.pop("availability")
    _emit(results, config="chaos_resilience", metric="availability_pct",
          value=round(avail * 100, 3), ok=ok,
          note=f"open-loop load through a supervised 2-stage pipeline "
               f"under kill+wedge injection; floors: availability >= "
               f"{AVAILABILITY_FLOOR:.0%} (zero silent losses), "
               f"recovery p99 TTFT <= {TTFT_RATIO_CEIL:.0f}x quiet, "
               "inject/recovery flight events paired", **row)
    return results


@device_config("fleet_serving")
def dev_fleet_serving():
    # ISSUE 13: the fleet front door's measured contract — open-loop
    # load through the router over 2 REAL `node --serve_lm` replica
    # subprocesses (gpt2), one SIGKILLed mid-measurement. Floors: fleet-leg
    # availability >= 99% completed-or-explicitly-rejected with ZERO
    # silently lost, fleet delivered tokens/sec >= 1.5x the unfronted
    # single-replica leg at the same demand (on a 1-core host the win
    # is admission-control goodput — the single leg collapses into
    # admit-then-deadline-cancel waste; on real chips width adds on
    # top), and the kill paired with its supervisor_restart in the
    # dumped flight ring. Honors --require-substrate (PR 11's
    # trajectory contract) via $DNN_TPU_REQUIRE_SUBSTRATE.
    from benchmarks.fleet_serving_probe import (
        AVAILABILITY_FLOOR,
        FLEET_SPEEDUP_FLOOR,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    require = os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
    note = (f"router over 2 supervised replica subprocesses, one "
            f"killed mid-run; floors: availability >= "
            f"{AVAILABILITY_FLOOR:.0%} (zero silent losses), fleet "
            f"tokens/sec >= {FLEET_SPEEDUP_FLOOR}x the single-replica "
            "leg, kill/restart flight events paired")
    if require:
        row["required_substrate"] = require
        if row.get("round_substrate") != require:
            ok = False
            note += (f"; required substrate '{require}' but the probe "
                     f"ran on '{row.get('round_substrate')}'")
    tps = row.pop("fleet_tokens_per_sec")
    _emit(results, config="fleet_serving",
          metric="fleet_tokens_per_sec", value=tps, ok=ok,
          note=note, **row)
    return results


@device_config("kv_tier")
def dev_kv_tier():
    # ISSUE 15: the fleet KV tier's measured contract — router + 2
    # real paged-radix replica subprocesses under the multi-turn-chat
    # arrival schedule with affinity deliberately broken (round-robin
    # placement, kvtier="pull"): cross-replica block-hit ratio >= 0.5,
    # adopted-vs-local token parity exact (greedy + seeded-sampled),
    # warm-turn TTFT p95 >= 2x forced-cold, migrated bytes under the
    # full-KV row-handoff baseline, and the donor-death chaos leg
    # (lease expiry + kvtier_fallback read back from the dumped rings,
    # zero token divergence, zero leaked blocks).
    from benchmarks.kv_tier_probe import (
        CROSS_HIT_FLOOR,
        TTFT_RATIO_FLOOR,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    require = os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
    note = (f"router + 2 paged-radix replicas, anti-affinity chat; "
            f"floors: cross-replica block-hit >= {CROSS_HIT_FLOOR}, "
            f"warm TTFT p95 >= {TTFT_RATIO_FLOOR}x vs cold, migrated "
            "bytes < row-handoff baseline, parity exact, donor-death "
            "leg green")
    if require:
        row["required_substrate"] = require
        if row.get("round_substrate") != require:
            ok = False
            note += (f"; required substrate '{require}' but the probe "
                     f"ran on '{row.get('round_substrate')}'")
    ratio = row.pop("cross_replica_hit_ratio")
    _emit(results, config="kv_tier",
          metric="cross_replica_hit_ratio", value=ratio, ok=ok,
          note=note, cross_replica_hit_ratio=ratio, **row)
    return results


@device_config("kv_economy")
def dev_kv_economy():
    # ISSUE 18: kvlens's miss-ratio curve validated against ground
    # truth — replay the deterministic chat-arrival schedule (working
    # set 3x the pool) at capacity A, record the curve's 0.5x
    # prediction, re-run the identical trace at capacity B = A/2, and
    # assert |predicted − measured| <= MRC_ERROR_CEIL on the real
    # store's per-block hit tally. The pressured run must also bill a
    # non-zero evict→refetch thrash tax (the forensics leg).
    from benchmarks.kv_economy_probe import MRC_ERROR_CEIL, measure

    results = []
    row = measure()
    ok = row.pop("ok")
    err = row.pop("mrc_prediction_error")
    _emit(results, config="kv_economy",
          metric="mrc_prediction_error", value=err, ok=ok,
          platform=_platform(),
          note=f"curve@{row['cap_A_blocks']}blk predicts hit ratio at "
               f"{row['cap_B_blocks']}blk; ceiling "
               f"{MRC_ERROR_CEIL} absolute; thrash refetches > 0 "
               "required at the pressured capacity",
          mrc_prediction_error=err, **row)
    return results


@device_config("capacity_plan")
def dev_capacity_plan():
    # ISSUE 20: caplens's what-if planner validated against ground
    # truth — observe a 1-replica fleet under the seeded bursty trace,
    # take the lens's 2-replica prediction, then measure a REAL
    # 2-replica fleet replaying the identical trace. Floors:
    # |predicted − measured| availability <= PRED_ERROR_CEIL, wall-p95
    # ratio inside the documented bound, cold-start ledger coverage >=
    # 95% of spawn→first-token wall with compile as its own bucket,
    # zero silent losses. Honors --require-substrate via
    # $DNN_TPU_REQUIRE_SUBSTRATE.
    from benchmarks.capacity_plan_probe import (
        COLDSTART_COVERAGE_FLOOR,
        PRED_ERROR_CEIL,
        WAIT_RATIO_BOUND,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    row.pop("coldstart_entries", None)  # per-spawn detail: JSONL bloat
    require = os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
    note = (f"1-replica observations predict the 2-replica fleet on "
            f"the identical seeded trace; floors: abs(pred-measured) "
            f"availability <= {PRED_ERROR_CEIL}, wall-p95 ratio <= "
            f"{WAIT_RATIO_BOUND}x, cold-start coverage >= "
            f"{COLDSTART_COVERAGE_FLOOR:.0%} with compile bucketed, "
            "zero silent losses")
    if require:
        row["required_substrate"] = require
        if row.get("round_substrate") != require:
            ok = False
            note += (f"; required substrate '{require}' but the probe "
                     f"ran on '{row.get('round_substrate')}'")
    err = row.pop("value")
    _emit(results, config="capacity_plan",
          metric="capacity_prediction_error", value=err, ok=ok,
          note=note, **row)
    return results


@device_config("train_goodput")
def dev_train_goodput():
    # ISSUE 19: trainlens — the training-step observatory, judged
    # before the training PR it will grade. One fit() run on the
    # pinned gpt-mini with the TrainClock attached. Asserted in the
    # probe: phase accounting (data/dispatch/wait/ckpt/eval/obs)
    # covers >= COVERAGE_FLOOR of the externally measured fit wall,
    # MFU against the PINNED roofline clears the (deliberately low)
    # floor, an injected data-loader sleep lands in data_stall within
    # STALL_TOLERANCE, an injected NaN batch fires loss_nan within
    # SENTINEL_MAX_STEPS steps with the event in the dumped flight
    # ring, and the whole observatory (clock + sentinel) costs
    # <= OVERHEAD_BUDGET of step wall under ABBA pairing.
    from benchmarks.train_goodput_probe import (
        COVERAGE_FLOOR,
        MFU_FLOOR,
        OVERHEAD_BUDGET,
        PINNED_PEAK_FLOPS,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    _emit(results, config="train_goodput",
          metric="train_mfu", value=row.pop("mfu"),
          platform=_platform(), ok=ok,
          note=f"model FLOP utilization of the probe fit() against the "
               f"PINNED {PINNED_PEAK_FLOPS:.0e} FLOP/s roofline (floor "
               f"{MFU_FLOOR:g} guards the estimator, not the hardware); "
               f"ASSERTED: phase coverage >= {COVERAGE_FLOOR:.0%}, "
               f"injected stall attributed, NaN caught <= 2 steps, "
               f"observatory overhead <= {OVERHEAD_BUDGET:.0%}",
          **row)
    return results


@device_config("step_timeline")
def dev_step_timeline():
    # ISSUE 11: step-timeline attribution baseline — the §10/§11 decode
    # configuration with the StepClock attached. Asserted: phase
    # accounting (admit/host/dispatch/wait/commit/obs) covers >= 95% of
    # the externally measured round wall (no unattributed dark time).
    # Recorded: the host-serialization fraction — the number the item-4
    # overlap/fusion PR must ratchet DOWN, the way decode_mbu ratchets
    # up — plus the device-view cross-check from a real profiler
    # capture analyzed by obs/timeline.analyze().
    from benchmarks.step_timeline_probe import (
        COVERAGE_FLOOR,
        HOST_FRACTION_CEIL,
        measure,
    )

    results = []
    row = measure()
    ok = row.pop("ok")
    host_frac = row.pop("host_serialization_fraction")
    _emit(results, config="step_timeline",
          metric="host_serialization_pct",
          value=round(host_frac * 100, 2), platform=_platform(), ok=ok,
          note=f"share of decode-round wall NOT inside a decode step "
               f"program, measured on the ISSUE 12 hot path "
               f"(interleaved prefill + overlap); ASSERTED: phase "
               f"coverage >= {COVERAGE_FLOOR:.0%} of measured wall AND "
               f"host fraction <= {HOST_FRACTION_CEIL:.2f} (the item-4 "
               "ratchet, down from the PR 10 baseline 0.549; the "
               "convoy leg re-measures alongside)", **row)
    return results


@device_config("constrained_hotpath")
def dev_constrained_hotpath():
    # ISSUE 16: constrained decoding on the interleaved+overlap hot
    # path (on-device DFA walk). Paired legs, both fully grammar-
    # constrained: convoy admission (the only path constraints had
    # before the transition-table pool) vs interleave+overlap. Asserted
    # in the probe: exact token parity between the legs AND against a
    # pure-host DFA replay, hot tokens/sec >= SPEEDUP_FLOOR x convoy,
    # and host fraction <= the step_timeline ceiling — constraints
    # answer to the SAME 0.40 ratchet as unconstrained decode.
    from benchmarks.constrained_hotpath_probe import (
        SPEEDUP_FLOOR,
        measure,
    )
    from benchmarks.step_timeline_probe import HOST_FRACTION_CEIL

    results = []
    row = measure()
    ok = row.pop("ok")
    _emit(results, config="constrained_hotpath",
          metric="vs_convoy_tps", value=row.pop("vs_convoy_tps"),
          platform=_platform(), ok=ok,
          note=f"constrained hot-path tokens/sec over the convoy-"
               f"admission control, all slots grammar-constrained; "
               f"ASSERTED: token parity (cross-leg + host DFA oracle), "
               f"speedup >= {SPEEDUP_FLOOR}, host fraction <= "
               f"{HOST_FRACTION_CEIL:.2f} (the ISSUE 16 ratchet pair)",
          **row)
    return results


@device_config("substrate")
def dev_substrate():
    # ROADMAP 5a prep: ONE preflight row that probes the device (the
    # watchdog's subprocess probe via bench._backend_alive, which
    # invokes the supervisor's recover_backend on a WEDGED attempt and
    # counts a recovery as success), stamps honest provenance (commit +
    # the substrate the round will actually run on), and carries the
    # substrate contract for the WHOLE round: with --require-substrate
    # set, this row's ok says whether the round's trajectory may join
    # the on-chip trend — one gate instead of per-probe require checks.
    # Registered FIRST (see the insert below) so a full round learns
    # its substrate before spending hours measuring on it.
    from bench import _backend_alive

    from dnn_tpu import obs

    results = []
    t0 = time.perf_counter()
    # shorter ladder than bench.py's headline probe: a preflight must
    # not spend 10+ min deciding; the second attempt still allows the
    # longest healthy cold init and rides the recover_backend path
    alive = _backend_alive(deadlines_s=(60.0, 240.0))
    probe_s = time.perf_counter() - t0
    if not alive:
        import jax

        jax.config.update("jax_platforms", "cpu")
    platform = _platform()
    events = obs.flight.recorder().events()
    outcomes = {}
    for kind in ("probe_fail", "probe_recovered", "probe_exhausted"):
        n = sum(1 for e in events if e["kind"] == kind)
        if n:
            outcomes[kind] = n
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=REPO,
            timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — provenance is best-effort
        rev = "unknown"
    require = os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
    ok = True
    note = ("device probe "
            + ("ok" if alive else "exhausted -> CPU fallback")
            + "; recover_backend consulted on wedged attempts; this "
              "row's substrate is the round's provenance stamp")
    row = {"probe_alive": bool(alive),
           "probe_wall_s": round(probe_s, 1), "commit": rev,
           **outcomes}
    if require:
        row["required_substrate"] = require
        ok = platform == require
        if not ok:
            note += (f"; required substrate '{require}' but the round "
                     f"runs on '{platform}' — the whole round's rows "
                     "are off-contract")
    _emit(results, config="substrate", metric="probe_alive",
          value=bool(alive), platform=platform, ok=ok, note=note,
          **row)
    return results


# run the preflight FIRST: it was necessarily defined after the model
# configs above, but the round must learn its substrate before
# measuring on it
DEVICE_CONFIGS.insert(0, DEVICE_CONFIGS.pop(
    next(i for i, c in enumerate(DEVICE_CONFIGS)
         if c[0] == "substrate")))


# ----------------------------------------------------------------------
# the workload suite (ISSUE 14): one asserted row per scenario
# ----------------------------------------------------------------------

WORKLOAD_SCENARIOS = ("chat", "longcontext", "json_mode",
                      "json_mode_fast", "spec_mix", "lora",
                      "breach_chaos")


def _workload_config(scen: str):
    def run():
        # each scenario's SLO is asserted IN-RUN by the verdict engine
        # (obs/slo.py); the breach scenario is green only when it
        # breaches AND its incident bundle reconstructs off disk
        # (benchmarks/workload_probe.py)
        from benchmarks.workload_probe import measure

        results = []
        row = measure(scen)
        ok = row.pop("ok")
        # measure() carries its own note on some paths (e.g. a breach
        # scenario whose injection did not bite) — fold it in rather
        # than colliding on the kwarg
        extra = row.pop("note", None)
        if row.pop("expect_breach", False):
            note = ("chaos-injected breach: asserted by reading the "
                    "incident bundle back (manifest verdict + "
                    "chaos_inject events in the dumped timeline + "
                    "CLI render)")
            _emit(results, config=f"workload_{scen}",
                  metric="breach_reconstructed",
                  value=bool(row.pop("reconstructed", False)), ok=ok,
                  note=note + (f"; {extra}" if extra else ""), **row)
        else:
            note = ("open-loop scenario vs its declared SLO "
                    "(dnn_tpu/workloads); ok IS the verdict")
            _emit(results, config=f"workload_{scen}",
                  metric="goodput_tokens_per_sec",
                  value=row.pop("goodput_tokens_per_sec"), ok=ok,
                  note=note + (f"; {extra}" if extra else ""), **row)
        return results
    run.__name__ = f"dev_workload_{scen}"
    return run


for _scen in WORKLOAD_SCENARIOS:
    DEVICE_CONFIGS.append((f"workload_{_scen}",
                           _workload_config(_scen), False))


def _serve_round(srv_x, cfg, sb_new, n_requests, plen_fn, constraint=None,
                 key=9):
    """Admit-when-a-slot-frees over the pool, then drain — the
    continuous-batching arrival pattern, shared by the e2e and
    constrained-tax configs."""
    import jax
    import jax.numpy as jnp

    rng_np = jax.random.PRNGKey(key)
    rids = []
    for i in range(n_requests):
        p = jax.random.randint(jax.random.fold_in(rng_np, i),
                               (plen_fn(i),), 0, cfg.vocab_size,
                               dtype=jnp.int32)
        while srv_x.free_slots() == 0:
            srv_x.step()
        rids.append(srv_x.submit(
            jnp.asarray(p), max_new_tokens=sb_new, constraint=constraint))
    out = srv_x.drain()
    return sum(len(out[r]) for r in rids)


@device_config("gpt2_serving_e2e", tpu_only=True)
def dev_gpt2_serving_e2e():
    # Continuous-batching END-TO-END serving throughput: mixed-length
    # prompts through the slot pool (chunked prefill + per-row decode +
    # retirement), wall-clock including the host-side scheduler — the
    # number a serving user actually gets. TPU-only: the wall-clock of
    # the host loop on a CPU backend measures nothing interesting.
    import time as _time

    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    sb_new = 64
    # ONE batcher for warmup + timed round: the three step programs are
    # per-instance jit closures, so a fresh instance would recompile
    # inside the timed window and the row would measure XLA, not serving
    srv = ContinuousBatcher(cfg, bf16_prepared, slots=8, max_len=256,
                            prompt_pad=128, kv_dtype=jnp.bfloat16,
                            compute_dtype=jnp.bfloat16)
    mixed_plen = lambda i: 16 + (i * 7) % 112  # noqa: E731 — 16..121
    _serve_round(srv, cfg, sb_new, 24, mixed_plen)  # compile the programs
    t0 = _time.perf_counter()
    total = _serve_round(srv, cfg, sb_new, 24, mixed_plen)
    dt = _time.perf_counter() - t0
    _emit(results, config="gpt2_serving_e2e", metric="tokens_per_sec",
          value=round(total / dt, 1), platform=_platform(), slots=8,
          requests=24, new_tokens_per_req=sb_new,
          note="wall-clock drain of 24 mixed-length requests through the "
               "continuous batcher (chunked prefill + decode + host "
               "scheduler)")
    return results


@device_config("gpt2_serving_constrained_tax", tpu_only=True)
def dev_gpt2_serving_constrained_tax():
    # Constrained-decoding tax: every slot carries a grammar, so each
    # step pays the host-side DFA advance + the device-side bias path.
    # The [0-9]+ grammar (2 DFA states) isolates the PER-STEP mechanism
    # cost — table compile is a one-time artifact outside the window.
    import time as _time

    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab
    from dnn_tpu.runtime.serving import ContinuousBatcher

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    cons = TokenConstraint.from_regex(r"[0-9]+", byte_vocab(cfg.vocab_size))
    tps_c = {}
    for name, con in (("off", None), ("on", cons)):
        # one batcher per leg, REUSED for warmup + timed round (fresh
        # instances would recompile inside the timed window). Both legs
        # run allow_constraints=True (device mask pool allocated, bool
        # gather in the program), so the on/off delta isolates the
        # per-step host DFA walk + (slots,) state-vector flush — the
        # whole marginal cost of a live grammar in the new design.
        srv_c = ContinuousBatcher(
            cfg, bf16_prepared, slots=8, max_len=256, prompt_pad=128,
            kv_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
            allow_constraints=True, temperature=1.0)
        _serve_round(srv_c, cfg, 64, 16, lambda i: 32, constraint=con,
                     key=11)  # compile/warm
        t0 = _time.perf_counter()
        total = _serve_round(srv_c, cfg, 64, 16, lambda i: 32,
                             constraint=con, key=11)
        tps_c[name] = total / (_time.perf_counter() - t0)
    c_overhead = tps_c["off"] / tps_c["on"] - 1.0
    _emit(results, config="gpt2_serving_constrained_tax",
          metric="overhead_pct", value=round(c_overhead * 100, 2),
          platform=_platform(), slots=8,
          tps_unconstrained=round(tps_c["off"], 1),
          tps_constrained=round(tps_c["on"], 1),
          note="all 8 slots grammar-constrained ([0-9]+): per-step DFA "
               "advance + device-resident mask table")
    return results


@device_config("mixtral_decode", tpu_only=True)
def dev_mixtral_decode():
    # Mixtral-style MoE decode vs its dense-equivalent (same ACTIVE FLOPs
    # per token: top-2 of 8 experts at d_ff F == dense at 2F) — the MoE
    # serving trade measured, with int8 expert stacks as the third leg.
    # Random-init mechanism bench at a mid-size shape that fits one chip;
    # bytes/token charges the FULL expert stacks (at B=8 top-2 routing
    # touches essentially all 8 experts per layer, so the worst case IS
    # the steady state — stated, not hidden).
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt, llama, llama_moe
    from dnn_tpu.quant import param_bytes, quantize_tree
    from dnn_tpu.utils.flops import mbu
    from dnn_tpu.utils.timing import device_time

    results = []
    mx_cfg = llama_moe.MixtralConfig(
        block_size=512, vocab_size=32000, n_layer=8, n_head=16,
        n_kv_head=4, n_embd=1024, d_ff=3584, n_expert=8, router_top_k=2,
        capacity_factor=4.0)
    dense_cfg = llama.LlamaConfig(
        block_size=512, vocab_size=32000, n_layer=8, n_head=16,
        n_kv_head=4, n_embd=1024, d_ff=2 * 3584)
    b, prompt_len, new_tokens = 8, 16, 64
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             mx_cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    s_max = prompt_len + new_tokens
    cache_elems = (2 * mx_cfg.n_layer * b * mx_cfg.n_kv_head
                   * mx_cfg.head_dim * s_max)

    mx_prep = gpt.prepare_stacked(
        llama_moe.init(jax.random.PRNGKey(0), mx_cfg, dtype=jnp.bfloat16),
        mx_cfg)
    mx_q = quantize_tree(mx_prep)
    dense_prep = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), dense_cfg, dtype=jnp.bfloat16),
        dense_cfg)

    def _decode_row(config_name, make, weights, extra):
        gfn = make()
        dt = device_time(gfn, weights, ids, rng, n1=1, n2=3)
        tps = b * new_tokens / dt
        bpt = (param_bytes(weights) + cache_elems * 2) / b  # bf16 cache
        row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
        u = mbu(bpt, tps)
        if u is not None:
            row["mbu"] = round(u, 4)
        _emit(results, config=config_name, metric="tokens_per_sec",
              value=round(tps, 1), platform=_platform(), batch=b,
              new_tokens=new_tokens, **row, **extra)

    _decode_row(
        "mixtral_decode_w_bf16",
        lambda: llama_moe.make_generate(
            mx_cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16),
        mx_prep, {"experts": "8x top-2",
                  "note": "bytes charge ALL expert stacks (B=8 touches "
                          "~every expert per layer)"})
    _decode_row(
        "mixtral_decode_w_int8",
        lambda: llama_moe.make_generate(
            mx_cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16),
        mx_q, {"experts": "8x top-2 int8"})
    _decode_row(
        "mixtral_dense_equiv_decode_w_bf16",
        lambda: llama.make_generate(
            dense_cfg, max_new_tokens=new_tokens,
            compute_dtype=jnp.bfloat16, kv_dtype=jnp.bfloat16),
        dense_prep, {"note": "dense MLP at 2*d_ff = the MoE's ACTIVE "
                             "FLOPs per token"})
    return results


@device_config("speculative_decode", tpu_only=True)
def dev_speculative_decode():
    # Speculative decoding measured: acceptance rate + END-TO-END speedup
    # vs plain decode — the number the feature exists for (VERDICT r5 ask
    # #2). Random-init weights make a smaller independent draft useless
    # (near-zero agreement), so the pairs are QUANTIZED SELF-DRAFTS — the
    # target's own weights at int8/int4 (a real deployment pattern:
    # the draft shares the target's distribution but streams half/quarter
    # the bytes per proposal on a bandwidth-bound decode).
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.quant import quantize_gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.runtime.speculative import make_speculative_generate
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    q8 = quantize_gpt(prepared)
    q4 = quantize_gpt(prepared, bits=4)
    prompt_len, new_tokens, k = 32, 128, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)

    # plain-decode baseline at the same (batch-1) shape, greedy + sampled
    base_tps = {}
    for mode, temp in (("greedy", 0.0), ("sampled", 1.0)):
        gfn = gen.make_generate(
            cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16, temperature=temp)
        dt = device_time(gfn, bf16_prepared, ids, rng, n1=1, n2=3)
        base_tps[mode] = new_tokens / dt

    pairs = (("int8_draft_greedy", q8, 0.0),
             ("int8_draft_sampled", q8, 1.0),
             ("int4_draft_greedy", q4, 0.0))
    for name, draft_w, temp in pairs:
        sfn = make_speculative_generate(
            cfg, cfg, max_new_tokens=new_tokens, k=k, temperature=temp,
            compute_dtype=jnp.bfloat16, return_stats=True)
        toks, stats = sfn(bf16_prepared, draft_w, ids, rng)
        jax.block_until_ready(toks)
        accept = float(stats["accepted"]) / max(float(stats["proposed"]), 1)
        if temp == 0.0:
            # greedy speculative must equal plain greedy token-for-token
            plain = gen.make_generate(
                cfg, max_new_tokens=new_tokens,
                compute_dtype=jnp.bfloat16, kv_dtype=jnp.bfloat16)(
                bf16_prepared, ids, rng)
            assert (jnp.asarray(toks) == jnp.asarray(plain)).all(), (
                "speculative greedy diverged from plain greedy")

        def run(tw, dw, ii, rr):
            t, _ = sfn(tw, dw, ii, rr)
            return t

        dt = device_time(run, bf16_prepared, draft_w, ids, rng, n1=1, n2=3)
        tps = new_tokens / dt
        base = base_tps["greedy" if temp == 0.0 else "sampled"]
        _emit(results, config=f"speculative_{name}",
              metric="speedup_vs_plain", value=round(tps / base, 3),
              platform=_platform(), k=k, new_tokens=new_tokens,
              acceptance_rate=round(accept, 4),
              tps_speculative=round(tps, 1), tps_plain=round(base, 1),
              note="quantized self-draft (target weights at reduced "
                   "precision); greedy output token-identical to plain")
    return results


@device_config("embeddings_throughput", tpu_only=True)
def dev_embeddings_throughput():
    # Embeddings endpoint throughput: mean-pooled hidden states over
    # padded batches (runtime/embeddings.py) — the encode-side serving
    # number.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.embeddings import make_embed
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    b, t = 32, 512
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, t), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    lengths = jnp.asarray([t - (i * 13) % 256 for i in range(b)],
                          jnp.int32)
    fn = make_embed(cfg, pooling="mean", compute_dtype=jnp.bfloat16)
    dt = device_time(fn, bf16_prepared, ids, lengths, n1=1, n2=3)
    _emit(results, config="embeddings_throughput",
          metric="sequences_per_sec", value=round(b / dt, 1),
          platform=_platform(), batch=b, seq=t, pooling="mean",
          tokens_per_sec=round(b * t / dt, 1))
    return results


@device_config("beam_vs_greedy", tpu_only=True)
def dev_beam_vs_greedy():
    # Beam search cost: beam_size=4 vs greedy on the same model/batch —
    # the quality/throughput trade quantified (beams share the prompt
    # cache; each step scores K continuations).
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.runtime.beam import make_beam_generate
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.PRESETS["gpt2"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    bf16_prepared = _to_bf16(prepared)
    b, prompt_len, new_tokens, k = 4, 16, 64, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    gfn = gen.make_generate(cfg, max_new_tokens=new_tokens,
                            compute_dtype=jnp.bfloat16,
                            kv_dtype=jnp.bfloat16)
    dt_g = device_time(gfn, bf16_prepared, ids, rng, n1=1, n2=3)
    bfn = make_beam_generate(cfg, max_new_tokens=new_tokens, beam_size=k,
                             compute_dtype=jnp.bfloat16,
                             kv_dtype=jnp.bfloat16)
    dt_b = device_time(bfn, bf16_prepared, ids, n1=1, n2=3)
    tps_g = b * new_tokens / dt_g
    tps_b = b * new_tokens / dt_b  # committed tokens (best hypothesis)
    _emit(results, config="beam_vs_greedy", metric="beam_cost_ratio",
          value=round(dt_b / dt_g, 3), platform=_platform(), batch=b,
          beam_size=k, new_tokens=new_tokens,
          tps_greedy=round(tps_g, 1), tps_beam=round(tps_b, 1),
          note="cost of beam_size=4 per COMMITTED token vs greedy; beams "
               "share the prompt cache")
    return results


@device_config("decode_bucketing")
def dev_decode_bucketing():
    # Length-aware bucketed decode (runtime/decode_buckets.py), measured
    # where it matters: a serving-style max_len allocation decoded at a
    # live position <= max_len/8. The unbucketed leg is the SAME host
    #-dispatched decoder with a single max_len bucket, so the delta
    # isolates the cache-view length; greedy token identity between the
    # two programs is asserted in-run (bucket-boundary crossings
    # included). CPU-runnable: the win is bytes-per-step proportionality,
    # not a chip feature.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.decode_buckets import make_bucketed_generate
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = gpt.GPTConfig(block_size=1024, vocab_size=512, n_layer=4,
                        n_head=8, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    b, prompt_len, new_tokens, max_len = 8, 16, 56, 1024
    # live positions run 16..71 — all <= max_len/8 = 128; the bucketed
    # leg crosses the 64-bucket edge mid-decode (parity must hold there)
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    legs = {}
    for name_l, buckets in (("bucketed", None), ("unbucketed", (max_len,))):
        # attn_kernel pinned OFF for both legs: on TPU the "auto" policy
        # would route only the max_len-sized unbucketed leg through the
        # Pallas kernel and the A/B would no longer isolate the cache
        # -view length (the kernel-vs-einsum A/B is its own config,
        # gpt2_decode_attnkernel)
        gen = make_bucketed_generate(
            cfg, max_len=max_len, max_new_tokens=new_tokens,
            buckets=buckets, attn_kernel=False)
        gen1 = make_bucketed_generate(
            cfg, max_len=max_len, max_new_tokens=1, buckets=buckets,
            attn_kernel=False)
        toks = np.asarray(gen(prepared, ids, rng))
        # subtract a max_new=1 run so the rate charges DECODE steps
        # against decode time (the longctx config's technique)
        dt_full = device_time(gen, prepared, ids, rng, n1=1, n2=3)
        dt_pre = device_time(gen1, prepared, ids, rng, n1=1, n2=3)
        dt = max(dt_full - dt_pre, 1e-9)
        legs[name_l] = {"toks": toks, "dt": dt,
                        "tps": b * (new_tokens - 1) / dt,
                        "buckets": gen.buckets}
    np.testing.assert_array_equal(
        legs["bucketed"]["toks"], legs["unbucketed"]["toks"],
        err_msg="bucketed decode diverged from the unbucketed program")
    # modeled cache bytes/step: mean live bucket vs the full allocation
    # (f32 K+V, all layers)
    per_pos = 2 * cfg.n_layer * b * cfg.n_embd * 4
    steps = range(prompt_len + 1, prompt_len + new_tokens)
    ladder = legs["bucketed"]["buckets"]
    mean_bucket = sum(next(x for x in ladder if x >= s) for s in steps) \
        / len(steps)
    _emit(results, config="decode_bucketing",
          metric="decode_speedup_at_live_le_max_len_div_8",
          value=round(legs["unbucketed"]["dt"] / legs["bucketed"]["dt"], 3),
          platform=_platform(), batch=b, prompt=prompt_len,
          new_tokens=new_tokens, max_len=max_len,
          buckets=str(ladder),
          tps_bucketed=round(legs["bucketed"]["tps"], 1),
          tps_unbucketed=round(legs["unbucketed"]["tps"], 1),
          modeled_cache_mb_per_step_bucketed=round(
              per_pos * mean_bucket / 1e6, 2),
          modeled_cache_mb_per_step_unbucketed=round(
              per_pos * max_len / 1e6, 2),
          note="greedy token identity bucketed==unbucketed asserted "
               "in-run, incl. a bucket-edge crossing")
    return results


# --- platform-independent legs of the former tpu_only configs (VERDICT
# r5 weak #2): acceptance rates and RELATIVE costs are properties of the
# models/algorithms, not the chip — measured on whatever backend this
# host resolves, at shapes small enough for a CPU leg. The tpu_only
# wall-clock twins above keep the absolute numbers. ---

def _small_gpt():
    from dnn_tpu.models import gpt

    return gpt.GPTConfig(block_size=512, vocab_size=512, n_layer=4,
                         n_head=4, n_embd=128)


@device_config("speculative_relative")
def dev_speculative_relative():
    # Acceptance rate + relative speedup of quantized self-draft
    # speculation (greedy + sampled) — the pair property the tpu_only
    # config left unmeasured for two rounds.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.quant import quantize_gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.runtime.speculative import make_speculative_generate
    from dnn_tpu.utils.timing import device_time

    results = []
    from dnn_tpu.models import gpt

    cfg = _small_gpt()
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    q8 = quantize_gpt(prepared)
    prompt_len, new_tokens, k = 32, 64, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    for mode, temp in (("greedy", 0.0), ("sampled", 1.0)):
        gfn = gen.make_generate(cfg, max_new_tokens=new_tokens,
                                temperature=temp)
        dt_plain = device_time(gfn, prepared, ids, rng, n1=1, n2=3)
        sfn = make_speculative_generate(
            cfg, cfg, max_new_tokens=new_tokens, k=k, temperature=temp,
            return_stats=True)
        toks, stats = sfn(prepared, q8, ids, rng)
        jax.block_until_ready(toks)
        accept = float(stats["accepted"]) / max(float(stats["proposed"]), 1)
        if temp == 0.0:
            np.testing.assert_array_equal(
                np.asarray(toks), np.asarray(gfn(prepared, ids, rng)),
                err_msg="speculative greedy diverged from plain greedy")

        def run(tw, dw, ii, rr, _s=sfn):
            t, _ = _s(tw, dw, ii, rr)
            return t

        dt_spec = device_time(run, prepared, q8, ids, rng, n1=1, n2=3)
        _emit(results, config=f"speculative_relative_{mode}",
              metric="speedup_vs_plain",
              value=round(dt_plain / dt_spec, 3), platform=_platform(),
              k=k, new_tokens=new_tokens,
              acceptance_rate=round(accept, 4),
              note="int8 self-draft on a small random-init GPT; "
                   "acceptance is a pair property, speedup is relative "
                   "on this host's backend")
    return results


@device_config("beam_vs_greedy_relative")
def dev_beam_vs_greedy_relative():
    # beam k=4 cost per committed token RELATIVE to greedy — meaningful
    # as a ratio on any backend.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime import generate as gen
    from dnn_tpu.runtime.beam import make_beam_generate
    from dnn_tpu.utils.timing import device_time

    results = []
    cfg = _small_gpt()
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    b, prompt_len, new_tokens, k = 4, 16, 32, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    gfn = gen.make_generate(cfg, max_new_tokens=new_tokens)
    dt_g = device_time(gfn, prepared, ids, rng, n1=1, n2=3)
    bfn = make_beam_generate(cfg, max_new_tokens=new_tokens, beam_size=k)
    dt_b = device_time(bfn, prepared, ids, n1=1, n2=3)
    _emit(results, config="beam_vs_greedy_relative",
          metric="beam_cost_ratio", value=round(dt_b / dt_g, 3),
          platform=_platform(), batch=b, beam_size=k,
          new_tokens=new_tokens,
          note="relative cost of beam_size=4 per committed token on "
               "this host's backend (small random-init GPT)")
    return results


@device_config("mixtral_vs_dense_relative")
def dev_mixtral_vs_dense_relative():
    # MoE decode vs its active-FLOPs dense equivalent, as a RELATIVE
    # tokens/s ratio — the routing tax is an algorithmic property.
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt, llama, llama_moe
    from dnn_tpu.utils.timing import device_time

    results = []
    mx_cfg = llama_moe.PRESETS["mixtral-test"]
    dense_cfg = llama.LlamaConfig(
        block_size=mx_cfg.block_size, vocab_size=mx_cfg.vocab_size,
        n_layer=mx_cfg.n_layer, n_head=mx_cfg.n_head,
        n_kv_head=mx_cfg.n_kv_head, n_embd=mx_cfg.n_embd,
        d_ff=mx_cfg.router_top_k * mx_cfg.d_ff)
    b, prompt_len, new_tokens = 8, 8, 32
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             mx_cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    mx_prep = gpt.prepare_stacked(
        llama_moe.init(jax.random.PRNGKey(0), mx_cfg), mx_cfg)
    dense_prep = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), dense_cfg), dense_cfg)
    mx_fn = llama_moe.make_generate(mx_cfg, max_new_tokens=new_tokens)
    dn_fn = llama.make_generate(dense_cfg, max_new_tokens=new_tokens)
    dt_mx = device_time(mx_fn, mx_prep, ids, rng, n1=1, n2=3)
    dt_dn = device_time(dn_fn, dense_prep, ids, rng, n1=1, n2=3)
    _emit(results, config="mixtral_vs_dense_relative",
          metric="moe_vs_dense_decode_ratio",
          value=round(dt_dn / dt_mx, 3), platform=_platform(), batch=b,
          new_tokens=new_tokens, experts=f"{mx_cfg.n_expert}x "
          f"top-{mx_cfg.router_top_k}",
          tps_moe=round(b * new_tokens / dt_mx, 1),
          tps_dense=round(b * new_tokens / dt_dn, 1),
          note="dense twin at router_top_k*d_ff = the MoE's ACTIVE "
               "FLOPs per token; >1 means MoE decodes faster than its "
               "dense equivalent on this backend")
    return results


@device_config("serving_constrained_tax_relative")
def dev_serving_constrained_tax_relative():
    # constrained-decoding tax as a ratio: per-step host DFA advance +
    # device mask gather vs the same pool unconstrained.
    import time as _time

    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab
    from dnn_tpu.runtime.serving import ContinuousBatcher

    results = []
    cfg = _small_gpt()
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    cons = TokenConstraint.from_regex(r"[0-9]+", byte_vocab(cfg.vocab_size))
    tps_c = {}
    for name, con in (("off", None), ("on", cons)):
        srv_c = ContinuousBatcher(
            cfg, prepared, slots=4, max_len=64, prompt_pad=16,
            allow_constraints=True, temperature=1.0)
        _serve_round(srv_c, cfg, 16, 8, lambda i: 12, constraint=con,
                     key=11)  # compile/warm
        t0 = _time.perf_counter()
        total = _serve_round(srv_c, cfg, 16, 8, lambda i: 12,
                             constraint=con, key=11)
        tps_c[name] = total / (_time.perf_counter() - t0)
    _emit(results, config="serving_constrained_tax_relative",
          metric="overhead_pct",
          value=round((tps_c["off"] / tps_c["on"] - 1.0) * 100, 2),
          platform=_platform(), slots=4,
          note="all slots grammar-constrained ([0-9]+) vs none, same "
               "allow_constraints=True pool — the marginal per-step cost "
               "of a live grammar, as a backend-relative ratio")
    return results


def run_device_config(name):
    """Child-process entry: run exactly one device config."""
    for cfg_name, fn, tpu_only in DEVICE_CONFIGS:
        if cfg_name == name:
            if tpu_only and _platform() != "tpu":
                _emit([], config=name, metric="skipped", value="tpu_only",
                      platform=_platform(),
                      note="TPU-only config; this process resolved a "
                           f"{_platform()} backend")
                return
            fn()
            return
    raise SystemExit(f"unknown device config {name!r}")


def run_device_section():
    """All device configs sequentially in one process (healthy-machine /
    debugging path; the orchestrated default isolates per config)."""
    for name, _, _ in DEVICE_CONFIGS:
        run_device_config(name)


# ----------------------------------------------------------------------
# section: cpu-mesh (8 virtual devices — pipeline forms)
# ----------------------------------------------------------------------

def run_cpu_mesh_section():
    # must precede first backend init: 8 virtual CPU devices
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.parallel.pipeline import (
        RelayExecutor, spmd_pipeline, spmd_pipeline_stacked,
    )
    from dnn_tpu.registry import get_model
    from dnn_tpu.utils.timing import device_time

    assert len(jax.devices()) >= 8, "need 8 virtual CPU devices"
    results = []

    # configs 2 & 3: CIFAR 2-part / 4-part SPMD pipeline, microbatched
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    batch = 64
    x = jnp.asarray(spec.example_input(batch_size=batch))
    for parts, mbs in ((2, 4), (4, 8)):
        stages = spec.partition(parts)
        mesh = make_mesh({STAGE_AXIS: parts}, jax.devices()[:parts])
        sparams = [st.slice_params(params) for st in stages]
        sfns = [st.apply for st in stages]
        # param_placement matches what engine auto policy serves for these
        # sub-threshold models (replicated; see engine.PLACEMENT_AUTO_BYTES)
        fn = lambda xx, _s=sfns, _p=sparams, _m=mesh, _mb=mbs: spmd_pipeline(
            _s, _p, xx, mesh=_m, num_microbatches=_mb,
            param_placement="replicated",
        )
        # parity guard: the pipeline must equal the full model before we
        # publish its number
        np.testing.assert_allclose(
            np.asarray(fn(x)), np.asarray(spec.apply(params, x)),
            atol=1e-4, rtol=1e-4,
        )
        dt = device_time(fn, x, n1=2, n2=6)
        _emit(results, config=f"cifar_{parts}stage_pipeline",
              metric="images_per_sec", value=round(batch / dt, 1),
              platform="cpu-mesh", batch=batch, microbatches=mbs)

    # config 5 (pipeline form): 8-stage stacked-block GPT pipeline
    cfg = gpt.GPTConfig(block_size=128, vocab_size=1024, n_layer=8,
                        n_head=4, n_embd=128)
    p = gpt.init(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh({STAGE_AXIS: 8}, jax.devices()[:8])
    stacked = gpt.stack_blocks(p, range(8))
    aux = {k: v for k, v in p.items() if not k.startswith("h_")}
    b, s, mbs = 16, 64, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             cfg.vocab_size, dtype=jnp.int32)

    def pipe(ids_in):
        xx = gpt.embed(aux, ids_in, cfg=cfg)
        h = spmd_pipeline_stacked(
            lambda bp, a: gpt.block_apply(bp, a, cfg=cfg),
            stacked, xx, mesh=mesh, num_microbatches=mbs,
        )
        return gpt.head(aux, h.astype(jnp.float32), cfg=cfg)

    full = gpt.make_apply(cfg)
    np.testing.assert_allclose(
        np.asarray(pipe(ids)), np.asarray(full(p, ids)), atol=1e-4, rtol=1e-4
    )
    dt = device_time(pipe, ids, n1=2, n2=6)
    _emit(results, config="gpt_8stage_pipeline", metric="tokens_per_sec",
          value=round(b * s / dt, 1), platform="cpu-mesh", batch=b, seq=s,
          microbatches=mbs)

    # interleaved vs GPipe schedule: same 8-layer model on 4 stages, V=2.
    # The structural win is the schedule length — sub-step equivalents
    # V*(M+S-1) vs VM+S-1 — reported alongside measured wall clock (CPU
    # timings carry dispatch noise; the sub-step ratio is the claim)
    from dnn_tpu.parallel.pipeline import (
        interleaved_schedule_steps, spmd_pipeline_interleaved,
    )

    s_stages, v, mbs2 = 4, 2, 8
    mesh4 = make_mesh({STAGE_AXIS: s_stages}, jax.devices()[:s_stages])
    x_emb = gpt.embed(aux, ids, cfg=cfg)
    per_st = cfg.n_layer // s_stages
    st4 = gpt.stack_blocks(p, range(cfg.n_layer))
    stage_form = jax.tree.map(
        lambda q: q.reshape(s_stages, per_st, *q.shape[1:]), st4)
    chunk_form = jax.tree.map(
        lambda q: q.reshape(v * s_stages, cfg.n_layer // (v * s_stages),
                            *q.shape[1:]), st4)

    def run_gpipe(xx):
        return spmd_pipeline_stacked(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg),
            stage_form, xx, mesh=mesh4, num_microbatches=mbs2)

    def run_inter(xx):
        return spmd_pipeline_interleaved(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg),
            chunk_form, xx, mesh=mesh4, num_microbatches=mbs2,
            virtual_stages=v)

    np.testing.assert_allclose(
        np.asarray(run_inter(x_emb)), np.asarray(run_gpipe(x_emb)),
        atol=1e-4, rtol=1e-4)
    dt_g = device_time(run_gpipe, x_emb, n1=2, n2=6)
    dt_i = device_time(run_inter, x_emb, n1=2, n2=6)
    gpipe_substeps = v * (mbs2 + s_stages - 1)
    inter_substeps = interleaved_schedule_steps(s_stages, v, mbs2)
    _emit(results, config="interleaved_vs_gpipe",
          metric="substep_ratio",
          value=round(inter_substeps / gpipe_substeps, 4),
          platform="cpu-mesh", stages=s_stages, virtual=v,
          microbatches=mbs2,
          gpipe_ms=round(dt_g * 1e3, 2), interleaved_ms=round(dt_i * 1e3, 2),
          note="schedule length V(M+S-1) -> VM+S-1. CPU wall-clock "
               "typically favors gpipe: interleaving doubles the scan "
               "steps and ring hops (per-sub-step dispatch + dynamic "
               "chunk gather dominate on CPU); the bubble win needs "
               "stage COMPUTE to dominate, i.e. real chips + real models")

    # LLaMA seq-sharded decode on a 4-device "seq" mesh: each device owns
    # a contiguous block of cache positions at GQA KV-head width; decode
    # steps combine per-shard attention with the exact distributed online
    # softmax (llama.make_generate_seq_sharded). Parity-guarded against
    # the solo decoder before the number is published.
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import SEQ_AXIS

    ll_cfg = llama.PRESETS["llama-test"]
    ll_p = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), ll_cfg), ll_cfg)
    smesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    lb, lt, lnew = 2, 8, 16
    l_ids = jax.random.randint(jax.random.PRNGKey(2), (lb, lt), 0,
                               ll_cfg.vocab_size, dtype=jnp.int32)
    l_rng = jax.random.PRNGKey(3)
    gen_seq = llama.make_generate_seq_sharded(
        ll_cfg, smesh, max_new_tokens=lnew)
    np.testing.assert_array_equal(
        np.asarray(gen_seq(ll_p, l_ids, l_rng)),
        np.asarray(llama.make_generate(ll_cfg, max_new_tokens=lnew)(
            ll_p, l_ids, l_rng)))
    dt = device_time(gen_seq, ll_p, l_ids, l_rng, n1=1, n2=3)
    _emit(results, config="llama_seq_sharded_decode",
          metric="tokens_per_sec", value=round(lb * lnew / dt, 1),
          platform="cpu-mesh", batch=lb, new_tokens=lnew, seq_shards=4,
          note="each shard holds ceil(S_max/4) cache positions at "
               "KV-head width; token-parity with the solo decoder "
               "asserted in-run")

    # Mixtral EP decode on a 4-device "expert" mesh: batch + KV cache
    # shard over the expert axis, expert stacks shard on E, tokens reach
    # their experts via all_to_all inside every decode step
    # (llama_moe.make_generate_ep). Token-parity vs the solo grouped
    # decoder asserted before the number is published; cpu-mesh value
    # validates the machinery, not the speed (VERDICT r5 ask #2/#7).
    from dnn_tpu.models import llama_moe
    from dnn_tpu.parallel.mesh import EXPERT_AXIS

    mx_cfg = llama_moe.PRESETS["mixtral-test"]
    mx_p = gpt.prepare_stacked(
        llama_moe.init(jax.random.PRNGKey(4), mx_cfg), mx_cfg)
    emesh = make_mesh({EXPERT_AXIS: 4}, jax.devices()[:4])
    mb, mt, mnew = 8, 8, 16
    m_ids = jax.random.randint(jax.random.PRNGKey(5), (mb, mt), 0,
                               mx_cfg.vocab_size, dtype=jnp.int32)
    m_rng = jax.random.PRNGKey(6)
    gen_ep = llama_moe.make_generate_ep(mx_cfg, emesh, max_new_tokens=mnew)
    np.testing.assert_array_equal(
        np.asarray(gen_ep(mx_p, m_ids, m_rng)),
        np.asarray(llama.make_generate(
            mx_cfg, max_new_tokens=mnew,
            ffn=llama_moe.make_ffn(mx_cfg, groups=4))(mx_p, m_ids, m_rng)))
    dt = device_time(gen_ep, mx_p, m_ids, m_rng, n1=1, n2=3)
    _emit(results, config="mixtral_ep_decode",
          metric="tokens_per_sec", value=round(mb * mnew / dt, 1),
          platform="cpu-mesh", batch=mb, new_tokens=mnew, expert_shards=4,
          note="all_to_all expert dispatch per decode step; token-parity "
               "with the solo grouped decoder asserted in-run")

    # p50 inter-stage hop latency (relay executor, device-to-device)
    stages = spec.partition(2)
    relay = RelayExecutor(
        [st.apply for st in stages],
        [st.slice_params(params) for st in stages],
        devices=jax.devices()[:2],
    )
    hops = []
    for _ in range(9):
        hops.extend(relay.measure_hop_latency(x))
    p50 = float(np.percentile(hops, 50))
    _emit(results, config="interstage_hop", metric="p50_latency_ms",
          value=round(p50 * 1e3, 4), platform="cpu-mesh",
          note="v5e ICI target <2ms not measurable single-chip")
    return results


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------

class _State:
    """Append-only row store at STATE_PATH: a config's rows persist as
    soon as that config finishes (the config is the resume unit — rows a
    child streamed before ITS death are salvaged by the orchestrator and
    land here with the failure marker); a `done` marker per config
    records completion. `--resume` replays markers to skip ok configs
    and retry failed ones — the crash-resume contract VERDICT r4 asked
    for."""

    def __init__(self, path=STATE_PATH, resume=False):
        self.path = path
        self.rows = []        # [(config_key, row)] in arrival order
        self.done = {}        # config_key -> status
        if resume and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn final line from a killed run
                    if "_done" in obj:
                        self.done[obj["_done"]] = obj.get("status", "ok")
                    elif "_reset" in obj:
                        # a later run retried this config: its earlier
                        # rows (failure marker included) are superseded
                        key = obj["_reset"]
                        self.done.pop(key, None)
                        self.rows = [(k, r) for k, r in self.rows
                                     if k != key]
                    elif "_row" in obj:
                        self.rows.append((obj.get("_cfg", "?"), obj["_row"]))
        elif os.path.exists(path):
            os.remove(path)
        self._f = open(path, "a")

    def add_rows(self, key, rows):
        for r in rows:
            self.rows.append((key, r))
            self._f.write(json.dumps({"_cfg": key, "_row": r}) + "\n")
        self._f.flush()

    def mark_done(self, key, status):
        self.done[key] = status
        self._f.write(json.dumps({"_done": key, "status": status}) + "\n")
        self._f.flush()

    def reset(self, key):
        """Forget a config's rows and completion marker (before a resume
        retries a previously-failed config)."""
        self.done.pop(key, None)
        self.rows = [(k, r) for k, r in self.rows if k != key]
        self._f.write(json.dumps({"_reset": key}) + "\n")
        self._f.flush()

    def all_rows(self):
        return [r for _, r in self.rows]


def _spawn_streaming(argv, extra_env, timeout):
    """Run a child, streaming stdout lines so a mid-run death keeps every
    completed measurement; returns (rows, status) with status in
    {"ok", "timeout", "crash"}. Rows are captured as they are emitted
    (_emit flushes one JSON line per row) and survive the kill — a
    parent kill of a child mid-device-op can wedge the TPU tunnel, so
    nothing here waits on a D-state child beyond a best-effort reap."""
    import threading

    env = dict(os.environ, **extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__)] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )
    out_lines, err_chunks = [], []

    def _drain(stream, sink):
        for line in stream:
            sink.append(line)

    threads = [
        threading.Thread(target=_drain, args=(proc.stdout, out_lines),
                         daemon=True),
        threading.Thread(target=_drain, args=(proc.stderr, err_chunks),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()  # best-effort; D-state children cannot be reaped
        try:
            proc.wait(timeout=10)  # reap the killed child (no zombie)
        except subprocess.TimeoutExpired:
            pass
    for t in threads:
        t.join(timeout=30)
    rows = []
    for l in out_lines:
        if not l.startswith("{"):
            continue
        try:
            rows.append(json.loads(l))
        except json.JSONDecodeError:
            pass  # SIGKILL mid-write truncates the final line; skip it
    if timed_out:
        print(f"[run_all] {' '.join(argv)} timed out after {timeout}s "
              f"with {len(rows)} completed rows. Child stderr tail:\n"
              + "".join(err_chunks[-30:]), file=sys.stderr)
        return rows, "timeout"
    if proc.returncode != 0:
        print(f"[run_all] {' '.join(argv)} child died rc={proc.returncode} "
              f"with {len(rows)} completed rows. Child stderr tail:\n"
              + "".join(err_chunks[-30:]), file=sys.stderr)
        return rows, "crash"
    return rows, "ok"


def _run_device_configs(state):
    """Each device config in its own subprocess: bounded retries, rows
    persisted as they land, and — the round-5 fix — a failure costs ONLY
    its config; the loop continues to the next one, naming the wedger in
    a per-config failure row."""
    attempts = int(os.environ.get("DNN_BENCH_CONFIG_ATTEMPTS", "2"))
    backoff = int(os.environ.get("DNN_BENCH_CONFIG_BACKOFF", "45"))
    # 1800 s: the longctx config alone compiles six decode programs
    # (3 legs x full+prefill-1) at 20-40 s each on a cold chip before
    # its timed runs even start
    timeout = int(os.environ.get("DNN_BENCH_CONFIG_TIMEOUT", "1800"))
    for name, _, _ in DEVICE_CONFIGS:
        key = f"device:{name}"
        if state.done.get(key) == "ok":
            print(f"[run_all] {name}: already ok (resume) — skipping",
                  file=sys.stderr)
            continue
        if key in state.done:
            # failed last run: a resume RETRIES it (that is the point of
            # resuming past a wedger) — supersede its salvage rows
            print(f"[run_all] {name}: failed last run — retrying",
                  file=sys.stderr)
            state.reset(key)
        best_rows, last_status = [], "unknown"
        for i in range(attempts):
            rows, status = _spawn_streaming(
                ["--section", "device", "--config", name], {}, timeout)
            if status == "ok":
                state.add_rows(key, rows)
                state.mark_done(key, "ok")
                break
            last_status = status
            if len(rows) >= len(best_rows):
                best_rows = rows
            more = i + 1 < attempts
            print(f"[run_all] config {name} attempt {i + 1}/{attempts} "
                  f"ended with {status} ({len(rows)} rows); "
                  + (f"retrying in {backoff}s" if more
                     else "salvaging and moving on"), file=sys.stderr)
            if more:
                time.sleep(backoff)
        else:
            # no attempt completed: keep the best partial rows and record
            # WHICH config failed — later configs still run
            best_rows.append({
                "config": name, "metric": "failed", "value": last_status,
                "platform": "meta",
                "note": (f"config {name!r} {last_status} on all "
                         f"{attempts} attempts; rows above it are "
                         "complete, later configs still ran — re-run "
                         "with --resume to retry only this one"),
            })
            state.add_rows(key, best_rows)
            state.mark_done(key, "failed")


def _run_cpu_mesh(state):
    key = "cpu_mesh"
    if state.done.get(key) == "ok":
        print("[run_all] cpu_mesh: already ok (resume) — skipping",
              file=sys.stderr)
        return
    if key in state.done:
        print("[run_all] cpu_mesh: failed last run — retrying",
              file=sys.stderr)
        state.reset(key)
    attempts = int(os.environ.get("DNN_BENCH_SECTION_ATTEMPTS", "2"))
    backoff = int(os.environ.get("DNN_BENCH_SECTION_BACKOFF", "60"))
    timeout = int(os.environ.get("DNN_BENCH_SECTION_TIMEOUT", "3600"))
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8").strip(),
    }
    best_rows, last_status = [], "unknown"
    for i in range(attempts):
        rows, status = _spawn_streaming(["--section", "cpu_mesh"], env,
                                        timeout)
        if status == "ok":
            state.add_rows(key, rows)
            state.mark_done(key, "ok")
            return
        last_status = status
        if len(rows) >= len(best_rows):
            best_rows = rows
        if i + 1 < attempts:
            time.sleep(backoff)
    best_rows.append({
        "config": "cpu_mesh_section", "metric": "truncated", "value": True,
        "platform": "meta",
        "note": (f"section {last_status} on all {attempts} attempts; the "
                 "rows above are complete measurements, later configs "
                 "are missing"),
    })
    state.add_rows(key, best_rows)
    state.mark_done(key, "failed")


# row-name -> device-config-name for configs that emit multiple /
# differently-named rows (used only when seeding resume state from an
# existing RESULTS.md; new configs that emit rows under their own name
# need no entry)
_ROW_TO_CONFIG = {
    "gpt2_fwd": "gpt_fwd", "gpt2-medium_fwd": "gpt_fwd",
    "tinyllama_decode_w_bf16_kv_bf16": "tinyllama_decode",
    "tinyllama_decode_w_int8_kv_int8": "tinyllama_decode",
    "llama_mha_longctx_decode_dense": "llama_longctx_decode",
    "llama_mha_longctx_decode_ring": "llama_longctx_decode",
    "gpt2_decode_w_f32_kv_f32": "gpt2_decode_matrix",
    "gpt2_decode_w_bf16_kv_bf16": "gpt2_decode_matrix",
    "gpt2_decode_w_int8_kv_bf16": "gpt2_decode_matrix",
    "gpt2_decode_w_int8_kv_int8": "gpt2_decode_matrix",
    "gpt2_decode_w_int4_kv_int8": "gpt2_decode_matrix",
    "gpt2_decode_attnkernel_w_bf16_kv_bf16": "gpt2_decode_attnkernel",
    "gpt2_decode_attnkernel_w_int8_kv_int8": "gpt2_decode_attnkernel",
    "speculative_int8_draft_greedy": "speculative_decode",
    "speculative_int8_draft_sampled": "speculative_decode",
    "speculative_int4_draft_greedy": "speculative_decode",
    "speculative_relative_greedy": "speculative_relative",
    "speculative_relative_sampled": "speculative_relative",
}


def seed_state_from_results(results_path=None, state_path=STATE_PATH):
    """Reconstruct .bench_rows.jsonl DEVICE-section entries from an
    existing RESULTS.md, so an OFF-CHIP host can `--resume` and refresh
    only what it can honestly measure (the cpu-mesh section plus
    cpu-runnable device configs) while the committed on-chip rows ride
    along UNCHANGED — each carried row gains a `provenance` detail
    naming the commit/date it was measured at, so old numbers can never
    masquerade as fresh ones. Without this, a full re-run on a CPU host
    would overwrite the tpu table with cpu-substrate values under the
    same config names — exactly the cross-substrate mixing bench.py's
    metric keys exist to prevent. Overwrites `state_path`."""
    import re

    results_path = results_path or os.path.join(REPO, "benchmarks",
                                                "RESULTS.md")
    with open(results_path) as f:
        text = f.read()
    head = re.search(r"Generated at commit `([^`]+)` on ([^;]+);", text)
    prov = (f"{head.group(1)} {head.group(2).strip()}" if head
            else "unknown")
    known = {name for name, _, _ in DEVICE_CONFIGS}
    seeded, done_keys = 0, []
    with open(state_path, "w") as out:
        for line in text.splitlines():
            cells = [c.strip() for c in line.split("|")][1:-1]
            if len(cells) != 6 or cells[0] in ("config", "---"):
                continue
            config, metric, value, mfu, platform, details = cells
            if platform in ("cpu-mesh", "cpu") or set(config) == {"-"}:
                # cpu-mesh AND cpu-substrate device rows refresh fresh —
                # carrying them "ok" would freeze exactly the rows this
                # host CAN honestly re-measure; separator rows skip
                continue
            if metric in ("failed", "skipped", "truncated"):
                # markers, not measurements: carrying one (and marking
                # its config ok) would pin a `failed | timeout` row in
                # the table forever while its own note says "re-run
                # with --resume to retry", and a carried `truncated`
                # note would keep asserting "later configs are missing"
                # after the refresh measures (or explicitly skips) them
                # — drop markers; the refresh re-establishes coverage
                continue
            if details.startswith("provenance="):
                # an already-carried row: keep its ORIGINAL measurement
                # stamp (restamping with this table's header commit
                # would let old numbers masquerade as fresh ones, and
                # the details cell would nest one level per cycle)
                emb, _, details = details.partition(", details=")
                row_prov = emb[len("provenance="):]
            else:
                row_prov = prov
            row = {"config": config, "metric": metric, "value": value,
                   "platform": platform, "provenance": row_prov}
            if details:
                row["details"] = details
            if mfu not in ("—", ""):
                try:
                    row["mfu"] = round(float(mfu.rstrip("%")) / 100, 4)
                except ValueError:
                    pass
            cfg_name = _ROW_TO_CONFIG.get(config, config)
            key = f"device:{cfg_name}" if cfg_name in known \
                else f"device:carried:{config}"
            out.write(json.dumps({"_cfg": key, "_row": row}) + "\n")
            seeded += 1
            if cfg_name in known and key not in done_keys:
                done_keys.append(key)
        for key in done_keys:
            out.write(json.dumps({"_done": key, "status": "ok"}) + "\n")
    print(f"[run_all] seeded {state_path} with {seeded} carried device "
          f"rows ({len(done_keys)} configs marked ok; provenance {prov}); "
          "now run with --resume", file=sys.stderr)
    return seeded


def _provenance():
    """Commit/date/platform stamp so a reader can always tell whether the
    table matches the harness that claims to produce it (round-3 lesson:
    RESULTS.md silently predated run_all.py's own additions)."""
    import datetime

    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, cwd=REPO, timeout=10).stdout.strip() or "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, cwd=REPO, timeout=10).stdout.strip()
        if dirty:
            rev += "-dirty"
    except Exception:
        rev = "unknown"
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")
    return rev, stamp


def write_results_md(rows, path):
    rev, stamp = _provenance()
    platforms = sorted({r.get("platform", "?") for r in rows
                        if r.get("platform") not in ("cpu-mesh", "meta")})
    lines = [
        "# Benchmark results (measured)",
        "",
        f"Generated at commit `{rev}` on {stamp}; device-section platform: "
        f"{', '.join(platforms) or 'none (device section skipped)'}.",
        "",
        "Produced by `python benchmarks/run_all.py`. The reference publishes",
        "no numbers (SURVEY §6); BASELINE.md maps these configs to its",
        "capability matrix. `cpu-mesh` rows run the multi-stage machinery on",
        "8 virtual CPU devices (no multi-chip TPU in this environment) — they",
        "validate the parallel path; absolute values are CPU-bound.",
        "",
        "| config | metric | value | mfu | platform | details |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        details = ", ".join(
            f"{k}={v}" for k, v in r.items()
            if k not in ("config", "metric", "value", "platform", "mfu")
        )
        mfu_cell = f"{r['mfu']:.1%}" if "mfu" in r else "—"
        lines.append(
            f"| {r['config']} | {r['metric']} | {r['value']} | {mfu_cell} | "
            f"{r['platform']} | {details} |"
        )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


README_BEGIN = "<!-- PERF_TABLE:BEGIN (generated by benchmarks/run_all.py --sync-readme) -->"
README_END = "<!-- PERF_TABLE:END -->"


def sync_readme(results_path=None, readme_path=None):
    """Regenerate README.md's performance table FROM benchmarks/
    RESULTS.md (between the PERF_TABLE markers): the measurement
    commit/date are stamped from the table's own provenance header, and
    a staleness warning is emitted whenever HEAD differs from the bench
    commit — no hand-copied (hence silently aging) numbers in the README
    (VERDICT r5 weak #6/#8)."""
    results_path = results_path or os.path.join(REPO, "benchmarks",
                                                "RESULTS.md")
    readme_path = readme_path or os.path.join(REPO, "README.md")
    import re

    with open(results_path) as f:
        results = f.read()
    head = re.search(r"Generated at commit `([^`]+)` on ([^;]+);", results)
    bench_rev, bench_date = (head.group(1), head.group(2).strip()) if head \
        else ("unknown", "unknown")
    table = [l for l in results.splitlines() if l.startswith("|")]
    cur_rev, _ = _provenance()
    lines = [README_BEGIN, "",
             f"Measured at commit `{bench_rev}` ({bench_date}); generated "
             "from `benchmarks/RESULTS.md` — do not hand-edit this "
             "section.", ""]
    if cur_rev.replace("-dirty", "") != bench_rev.replace("-dirty", ""):
        lines += [
            f"> **Staleness warning:** HEAD is `{cur_rev}` but these "
            f"numbers were measured at `{bench_rev}` — re-run "
            "`python benchmarks/run_all.py` (or let a healthy-chip "
            "`bench.py` run refresh them) before quoting.", ""]
    lines += table + ["", README_END]
    with open(readme_path) as f:
        readme = f.read()
    if README_BEGIN not in readme or README_END not in readme:
        raise SystemExit(
            f"README markers not found; add {README_BEGIN!r} and "
            f"{README_END!r} around the perf table once")
    pre = readme.split(README_BEGIN)[0]
    post = readme.split(README_END, 1)[1]
    with open(readme_path, "w") as f:
        f.write(pre + "\n".join(lines) + post)
    return readme_path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", choices=["device", "cpu_mesh"])
    ap.add_argument("--config", help="one device config (child mode)")
    ap.add_argument("--resume", action="store_true",
                    help="skip configs already completed in "
                         "benchmarks/.bench_rows.jsonl")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "benchmarks", "RESULTS.md"))
    ap.add_argument("--sync-readme", action="store_true",
                    help="regenerate README.md's perf table from the "
                         "existing RESULTS.md and exit (no measuring)")
    ap.add_argument("--seed-state", action="store_true",
                    help="reconstruct resume state from the existing "
                         "RESULTS.md device rows (marked with their "
                         "original provenance) and exit — an off-chip "
                         "host then refreshes only the sections it can "
                         "honestly measure via --resume")
    ap.add_argument("--scenarios", default=None,
                    help="run ONLY the workload suite: a comma list of "
                         "scenario names (or 'all') — each runs in its "
                         "own subprocess and lands in the row state "
                         "like any config, superseding its previous "
                         "row; RESULTS.md is NOT rewritten (a subset "
                         "run must not clobber the full table)")
    ap.add_argument("--require-substrate", choices=["tpu", "cpu"],
                    default=None,
                    help="substrate contract (PR 11's bench.py flag, "
                         "ROADMAP 5a): rows that honor it (the "
                         "fleet_serving probe) go ok=false when the "
                         "probe ran elsewhere — propagated to config "
                         "children via $DNN_TPU_REQUIRE_SUBSTRATE")
    args = ap.parse_args()

    if args.require_substrate:
        # children inherit the env (both the in-process config path and
        # the per-config subprocesses _spawn_streaming launches)
        os.environ["DNN_TPU_REQUIRE_SUBSTRATE"] = args.require_substrate

    if args.sync_readme:
        print(f"synced {sync_readme(results_path=args.out)}")
        return
    if args.seed_state:
        seed_state_from_results(results_path=args.out)
        return
    if args.section == "device":
        if args.config:
            run_device_config(args.config)
        else:
            run_device_section()
        return
    if args.section == "cpu_mesh":
        run_cpu_mesh_section()
        return

    if args.scenarios:
        known = {name for name, _, _ in DEVICE_CONFIGS}
        if args.scenarios.strip() == "all":
            sel = [f"workload_{s}" for s in WORKLOAD_SCENARIOS]
        else:
            sel = [s if s.startswith("workload_") else f"workload_{s}"
                   for s in (x.strip()
                             for x in args.scenarios.split(","))
                   if s]
        unknown = [s for s in sel if s not in known]
        if unknown:
            raise SystemExit(
                f"unknown scenario(s) {', '.join(unknown)}; known: "
                + ", ".join(s for s in WORKLOAD_SCENARIOS))
        # resume semantics against the existing row state, but the
        # SELECTED scenarios always re-measure (that is the point of
        # naming them). --require-substrate keeps its whole-round
        # meaning here too: the preflight row runs FIRST and gates the
        # subset run — without this, a scenario-only run would silently
        # drop the substrate contract the flag promises
        run_names = ((["substrate"] if args.require_substrate else [])
                     + sel)
        state = _State(resume=True)
        for name in run_names:
            state.reset(f"device:{name}")
        DEVICE_CONFIGS[:] = [c for c in DEVICE_CONFIGS
                             if c[0] in run_names]
        _run_device_configs(state)
        # judge ONLY the selected scenarios (a stale failing row from
        # an unselected one must not fail this run), and judge them by
        # the presence of an ok=True row — a child that crashed on all
        # attempts leaves a salvage meta-row with NO ok field, which
        # must read as failed, not green
        passed = {name: False for name in sel}
        for _, r in state.rows:
            if r.get("config") in passed and r.get("ok") is True:
                passed[r["config"]] = True
        bad = [name for name, good in passed.items() if not good]
        # the contract needs a POSITIVE substrate verdict: a preflight
        # child that crashed on every attempt leaves a salvage row with
        # no ok field, which must read as off-contract, not green
        if args.require_substrate and not any(
                r.get("config") == "substrate" and r.get("ok") is True
                for _, r in state.rows):
            bad.insert(0, "substrate (off-contract)")
        if bad:
            print("[run_all] scenario assert failed: " + ", ".join(bad),
                  file=sys.stderr)
            raise SystemExit(1)
        return

    state = _State(resume=args.resume)
    if args.resume and state.done:
        rev, _ = _provenance()
        print(f"[run_all] resuming with {len(state.done)} completed "
              f"configs at HEAD {rev}", file=sys.stderr)
    _run_device_configs(state)
    _run_cpu_mesh(state)
    write_results_md(state.all_rows(), args.out)
    sync_readme(results_path=args.out)
    print(f"wrote {args.out} (+ README perf table)")
    if args.require_substrate and not any(
            r.get("config") == "substrate" and r.get("ok") is True
            for r in state.all_rows()):
        # the preflight row IS the round gate (ROADMAP 5a): the table
        # is still written — honestly stamped — but the round fails.
        # Gated on a POSITIVE verdict: a crashed preflight child leaves
        # a salvage row with no ok field, which is not a pass
        raise SystemExit(1)


if __name__ == "__main__":
    main()
