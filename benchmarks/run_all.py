"""Full benchmark suite: measures every config in BASELINE.md.

The reference publishes no numbers (SURVEY §6), so this suite produces the
framework's own measured table — one JSON line per config plus a markdown
table written to benchmarks/RESULTS.md.

Two sections, run in separate processes because platform selection is
process-global:

  * device:  whatever `jax.devices()` resolves to (the real TPU chip under
    axon; CPU elsewhere) — single-chip model throughput (configs 1, 4, 5
    in their full-model form, plus KV-cache decode).
  * cpu-mesh: 8 virtual CPU devices — the multi-stage pipeline forms
    (configs 2, 3, 5) and p50 inter-stage hop latency. These validate the
    parallel machinery; their absolute numbers are CPU numbers and are
    labeled as such. The <2 ms hop target is a v5e-8 ICI claim the
    single-chip environment cannot measure (BASELINE.md "north star").

Usage:
    python benchmarks/run_all.py            # both sections + RESULTS.md
    python benchmarks/run_all.py --section device|cpu_mesh   # one section
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # script lives in benchmarks/; import dnn_tpu from root
    sys.path.insert(0, REPO)


def _emit(results, **row):
    results.append(row)
    print(json.dumps(row), flush=True)


# ----------------------------------------------------------------------
# section: device (single chip / default platform)
# ----------------------------------------------------------------------

def run_device_section():
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.registry import get_model
    from dnn_tpu.utils.flops import (
        cifar_forward_bytes, cifar_forward_flops, gpt_forward_flops, mfu,
        roofline_items_per_sec,
    )
    from dnn_tpu.utils.timing import device_time

    platform = jax.default_backend()
    results = []

    def _with_mfu(row, flops_per_item, items_per_sec):
        m = mfu(flops_per_item, items_per_sec)
        if m is not None:
            row["mfu"] = round(m, 4)
        return row

    # config 1 (full-model form): CIFAR CNN forward — bf16 operands like the
    # GPT rows, so the mfu column divides a bf16-executed workload by the
    # bf16 peak table (an f32 workload against the bf16 peak would not be
    # comparable across rows)
    from dnn_tpu.models import cifar

    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    # B=1024: below ~1024 images a forward is so short (<0.2 ms) that the
    # tunnel's dispatch floor dominates and the row measures host
    # overhead, not the chip (benchmarks/cifar_mfu_probe.py batch sweep)
    batch = 1024
    x = jnp.asarray(spec.example_input(batch_size=batch))
    fn = jax.jit(cifar.make_apply(compute_dtype=jnp.bfloat16))
    # the CIFAR CNN is sub-ms per batch: needs many reps per sample or the
    # slope drowns in sync jitter
    dt = device_time(fn, params, x, n1=100, n2=400, trials=5)
    ips = batch / dt
    cifar_row = _with_mfu({}, cifar_forward_flops(1), ips)
    # the CNN's arithmetic intensity (~60 FLOPs/byte) is far below the TPU
    # ridge point, so its MFU ceiling is the ROOFLINE cap, not 100% — report
    # both, plus how much of the admissible throughput we achieve
    # (dnn_tpu/utils/flops.cifar_forward_bytes has the accounting)
    cap = roofline_items_per_sec(
        cifar_forward_flops(1), cifar_forward_bytes(batch) / batch)
    if cap is not None:
        cifar_row["mfu_roofline_cap"] = round(
            mfu(cifar_forward_flops(1), cap), 4)
        cifar_row["roofline_frac"] = round(ips / cap, 4)
    _emit(results, config="cifar_cnn_fwd", metric="images_per_sec",
          value=round(ips, 1), platform=platform, batch=batch,
          dtype="bf16", **cifar_row)

    # config 4/5 (full-model form): GPT-2 small + medium forward, bf16
    # operands + bf16 logit store (the serving configuration — see gpt.head)
    for preset, b, s in (("gpt2", 8, 512), ("gpt2-medium", 4, 512)):
        cfg = gpt.PRESETS[preset]
        p = gpt.init(jax.random.PRNGKey(0), cfg)
        prepared = gpt.prepare_stacked(p, cfg)
        fn = jax.jit(gpt.make_apply_stacked(
            cfg, compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16
        ))
        ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                 cfg.vocab_size, dtype=jnp.int32)
        dt = device_time(fn, prepared, ids)
        tps = b * s / dt
        _emit(results, config=f"{preset}_fwd", metric="tokens_per_sec",
              value=round(tps, 1), platform=platform, batch=b, seq=s,
              logits="bf16",
              **_with_mfu({}, gpt_forward_flops(cfg, b, s) / (b * s), tps))

    # LLaMA family forward (TinyLlama-1.1B shape, GQA 8:1) — the second
    # LM architecture; MFU from its own analytic accounting. TPU-only: a
    # 1.1B bf16 forward on a CPU host would blow the section's budget.
    if platform == "tpu":
        from dnn_tpu.models import llama
        from dnn_tpu.utils.flops import llama_forward_flops

        ll_cfg = llama.PRESETS["tinyllama-1.1b"]
        ll_prep = gpt.prepare_stacked(
            llama.init(jax.random.PRNGKey(0), ll_cfg, dtype=jnp.bfloat16),
            ll_cfg)
        ll_fn = jax.jit(llama.make_apply_stacked(
            ll_cfg, compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16))
        b, s = 8, 512
        ll_ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                    ll_cfg.vocab_size, dtype=jnp.int32)
        dt = device_time(ll_fn, ll_prep, ll_ids, n1=1, n2=3)
        tps = b * s / dt
        _emit(results, config="tinyllama_fwd", metric="tokens_per_sec",
              value=round(tps, 1), platform=platform, batch=b, seq=s,
              logits="bf16",
              **_with_mfu({}, llama_forward_flops(ll_cfg, b, s) / (b * s), tps))

        # TinyLlama decode matrix — the GQA bandwidth claim, measured.
        # The cache is stored at KV-head width (llama.init_cache):
        # KV*D = 256 floats/position/layer vs the model width 2048, so at
        # equal batch/seq TinyLlama streams 8x fewer cache bytes per step
        # than an MHA model of its width. Rows mirror the GPT-2 matrix
        # below (same batch/new_tokens) so bytes/token and MBU are
        # directly comparable across families.
        from dnn_tpu.quant import param_bytes as _pb
        from dnn_tpu.quant import quantize_tree
        from dnn_tpu.utils.flops import mbu as _mbu

        db, dprompt, dnew = 8, 16, 128
        d_ids = jax.random.randint(jax.random.PRNGKey(4), (db, dprompt), 0,
                                   ll_cfg.vocab_size, dtype=jnp.int32)
        d_smax = dprompt + dnew
        ll_cache_elems = (2 * ll_cfg.n_layer * db
                          * ll_cfg.n_kv_head * ll_cfg.head_dim * d_smax)
        ll_q = quantize_tree(ll_prep)
        rng_d = jax.random.PRNGKey(5)
        for name, weights, kvd, itemsize in (
                ("w_bf16_kv_bf16", ll_prep, jnp.bfloat16, 2),
                ("w_int8_kv_int8", ll_q, "int8", 1)):
            gfn = llama.make_generate(
                ll_cfg, max_new_tokens=dnew, compute_dtype=jnp.bfloat16,
                kv_dtype=kvd)
            dt = device_time(gfn, weights, d_ids, rng_d, n1=1, n2=3)
            tps = db * dnew / dt
            # int8 cache rides per-(position, kv-head) f32 scales for K
            # and V: cache_elems / head_dim scale entries x 4 bytes
            bpt = (_pb(weights) + ll_cache_elems * itemsize
                   + (ll_cache_elems // ll_cfg.head_dim * 4
                      if kvd == "int8" else 0)) / db
            row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
            u = _mbu(bpt, tps)
            if u is not None:
                row["mbu"] = round(u, 4)
            _emit(results, config=f"tinyllama_decode_{name}",
                  metric="tokens_per_sec", value=round(tps, 1),
                  platform=platform, batch=db, new_tokens=dnew, **row)
        del ll_q
        del ll_prep  # 2.2 GB of bf16 weights — free before the GPT rows

        # Sliding-window ring decode (models/llama.py rolling path) — the
        # Mistral-class long-context claim, measured as a mechanism bench:
        # at s_max = 3x the window the ring streams W cache positions per
        # step while the dense cache streams s_max. GQA caches are small
        # next to the weights (the matrix above shows why), so the
        # comparison runs an MHA-width variant (n_kv_head = n_head) of
        # the TinyLlama shape where the cache is ~half the decode traffic
        # — random-init throughput probe, labeled as such.
        import dataclasses as _dc

        swb, swprompt, swnew, sww = 8, 1024, 512, 512
        sw_smax = swprompt + swnew
        mha_cfg = _dc.replace(ll_cfg, n_kv_head=ll_cfg.n_head,
                              block_size=2048)
        sw_prep = gpt.prepare_stacked(
            llama.init(jax.random.PRNGKey(7), mha_cfg, dtype=jnp.bfloat16),
            mha_cfg)
        sw_ids = jax.random.randint(jax.random.PRNGKey(8), (swb, swprompt),
                                    0, mha_cfg.vocab_size, dtype=jnp.int32)
        for name, cfg_v, cache_pos in (
                ("dense", mha_cfg, sw_smax),
                ("ring", _dc.replace(mha_cfg, sliding_window=sww), sww)):
            gfn = llama.make_generate(
                cfg_v, max_new_tokens=swnew, compute_dtype=jnp.bfloat16,
                kv_dtype=jnp.bfloat16)
            # the 1024-token prefill would dilute a whole-call rate (the
            # prompt=16 matrix rows can ignore this; here it is ~10% of
            # the call): subtract a max_new=1 run so tps counts DECODE
            # steps against decode time
            gfn1 = llama.make_generate(
                cfg_v, max_new_tokens=1, compute_dtype=jnp.bfloat16,
                kv_dtype=jnp.bfloat16)
            dt_full = device_time(gfn, sw_prep, sw_ids, rng_d, n1=1, n2=2)
            dt_pre = device_time(gfn1, sw_prep, sw_ids, rng_d, n1=1, n2=2)
            dt = max(dt_full - dt_pre, 1e-9)
            tps = swb * (swnew - 1) / dt
            cache_bytes = (2 * cfg_v.n_layer * swb * cfg_v.n_kv_head
                           * cfg_v.head_dim * cache_pos) * 2
            bpt = (_pb(sw_prep) + cache_bytes) / swb
            row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
            u = _mbu(bpt, tps)
            if u is not None:
                row["mbu"] = round(u, 4)
            _emit(results, config=f"llama_mha_longctx_decode_{name}",
                  metric="tokens_per_sec", value=round(tps, 1),
                  platform=platform, batch=swb, prompt=swprompt,
                  new_tokens=swnew,
                  window=(sww if cfg_v.sliding_window else 0), **row)
        del sw_prep

    # Training step (fwd + bwd + adamw update) — nothing else in the table
    # measures the backward pass. bf16 compute, f32 params/optimizer, the
    # single-chip form of train.make_train_step (the dp x tp and pipeline
    # steps run the same loss; their numbers belong to the cpu-mesh legs).
    import optax

    from dnn_tpu.train import cross_entropy
    from dnn_tpu.utils.flops import gpt_train_step_flops

    t_cfg = gpt.PRESETS["gpt2"]
    t_prep = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), t_cfg), t_cfg)
    t_apply = gpt.make_apply_stacked(t_cfg, compute_dtype=jnp.bfloat16)

    def t_loss(p, batch):
        inp, tgt = batch
        return cross_entropy(t_apply(p, inp), tgt)

    t_opt = optax.adamw(1e-4)
    t_state = t_opt.init(t_prep)
    from dnn_tpu.train import make_train_step

    t_step = make_train_step(t_loss, t_opt)
    tb, ts = 8, 512
    t_inp = jax.random.randint(jax.random.PRNGKey(1), (tb, ts), 0,
                               t_cfg.vocab_size, dtype=jnp.int32)
    t_tgt = jax.random.randint(jax.random.PRNGKey(2), (tb, ts), 0,
                               t_cfg.vocab_size, dtype=jnp.int32)

    def t_run(p, s, b):  # time the whole step; params/state update discarded
        p2, s2, loss = t_step(p, s, b)
        return loss

    dt = device_time(t_run, t_prep, t_state, (t_inp, t_tgt), n1=1, n2=3)
    tps = tb * ts / dt
    _emit(results, config="gpt2_train_step", metric="tokens_per_sec",
          value=round(tps, 1), platform=platform, batch=tb, seq=ts,
          optimizer="adamw",
          **_with_mfu({}, gpt_train_step_flops(t_cfg, tb, ts) / (tb * ts),
                      tps))
    del t_prep, t_state

    # KV-cache generation throughput (the serving path the reference lacks)
    from dnn_tpu.runtime import generate as gen

    cfg = gpt.PRESETS["gpt2"]
    p = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(p, cfg)
    b, prompt_len, new_tokens = 8, 16, 128
    gen_fn = gen.make_generate(
        cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16
    )
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, prompt_len), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    dt = device_time(gen_fn, prepared, ids, rng, n1=1, n2=3)
    _emit(results, config="gpt2_generate_kvcache", metric="tokens_per_sec",
          value=round(b * new_tokens / dt, 1), platform=platform, batch=b,
          new_tokens=new_tokens)

    # quantized decode matrix: weight-storage x cache-storage. Decode is
    # HBM-bandwidth-bound (every token streams weights + cache once —
    # dnn_tpu/quant.py:1-9's rationale), so each row reports bytes/token
    # and MBU alongside tok/s: the speedup should track the byte ratio.
    import jax.tree as jtree

    from dnn_tpu.quant import param_bytes, quantize_gpt
    from dnn_tpu.utils.flops import mbu

    def _to_bf16(tree):
        return jtree.map(
            lambda a: a.astype(jnp.bfloat16)
            if hasattr(a, "dtype") and a.dtype == jnp.float32 and a.ndim >= 2
            else a, tree)

    s_max = prompt_len + new_tokens
    head_dim = cfg.n_embd  # per layer: H * D = C
    cache_elems = 2 * cfg.n_layer * b * head_dim * s_max  # K and V
    q_prepared = quantize_gpt(prepared)
    q4_prepared = quantize_gpt(prepared, bits=4)  # group-wise int4
    bf16_prepared = _to_bf16(prepared)
    variants = (
        # kv dtype must be EXPLICIT f32 for the baseline: with kv=None,
        # make_generate follows compute_dtype (bf16 here) and the "f32
        # cache" row would silently run a bf16 cache
        ("w_f32_kv_f32", prepared, jnp.float32, 4),
        ("w_bf16_kv_bf16", bf16_prepared, jnp.bfloat16, 2),
        ("w_int8_kv_bf16", q_prepared, jnp.bfloat16, 2),
        ("w_int8_kv_int8", q_prepared, "int8", 1),
        # int4 weights (dnn_tpu/quant.py quantize_tensor_int4): halves
        # the weight-byte term again IF the S4 operand read really packs
        # two-per-byte on this chip — this row is the measurement that
        # decides (param_bytes charges 0.5 B/wt; a tok/s that does not
        # beat int8 falsifies the packing assumption, which the docs
        # state as a claim-to-measure, not a fact)
        ("w_int4_kv_int8", q4_prepared, "int8", 1),
    )
    for name, weights, kv, cache_itemsize in variants:
        gfn = gen.make_generate(
            cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=kv,
        )
        dt = device_time(gfn, weights, ids, rng, n1=1, n2=3)
        tps = b * new_tokens / dt
        # bytes one token streams: its share of the weights + the full
        # static cache allocation (int8 scales ride along at 1/D per elem)
        bpt = (param_bytes(weights)
               + cache_elems * cache_itemsize
               + (cache_elems // (cfg.n_embd // cfg.n_head)
                  * 4 if kv == "int8" else 0)) / b
        row = {"bytes_per_token_mb": round(bpt / 1e6, 2)}
        u = mbu(bpt, tps)
        if u is not None:
            row["mbu"] = round(u, 4)
        _emit(results, config=f"gpt2_decode_{name}", metric="tokens_per_sec",
              value=round(tps, 1), platform=platform, batch=b,
              new_tokens=new_tokens, **row)

    # Pallas cached-attention decode kernel, before/after: same weights,
    # same cache dtype, einsum vs kernel attention. Shapes chosen so the
    # cache tiles the kernel's 128-blocks (prompt 128 + 128 new = S 256);
    # TPU-only — off-TPU the kernel dispatches to the einsum fallback and
    # the row would measure nothing.
    if platform == "tpu":
        kb, kprompt, knew = 8, 128, 128
        k_ids = jax.random.randint(jax.random.PRNGKey(3), (kb, kprompt), 0,
                                   cfg.vocab_size, dtype=jnp.int32)
        k_smax = kprompt + knew
        k_cache_elems = 2 * cfg.n_layer * kb * head_dim * k_smax
        for name, weights, kv, cache_itemsize in (
                ("w_bf16_kv_bf16", bf16_prepared, jnp.bfloat16, 2),
                ("w_int8_kv_int8", q_prepared, "int8", 1)):
            row = {}
            for mode, ak in (("einsum", False), ("kernel", True)):
                gfn = gen.make_generate(
                    cfg, max_new_tokens=knew, compute_dtype=jnp.bfloat16,
                    kv_dtype=kv, attn_kernel=ak,
                )
                dt = device_time(gfn, weights, k_ids, rng, n1=1, n2=3)
                row[f"tps_{mode}"] = round(kb * knew / dt, 1)
            bpt = (param_bytes(weights) + k_cache_elems * cache_itemsize
                   + (k_cache_elems // (cfg.n_embd // cfg.n_head) * 4
                      if kv == "int8" else 0)) / kb
            u = mbu(bpt, row["tps_kernel"])
            if u is not None:
                row["mbu_kernel"] = round(u, 4)
            _emit(results, config=f"gpt2_decode_attnkernel_{name}",
                  metric="kernel_vs_einsum_speedup",
                  value=round(row["tps_kernel"] / row["tps_einsum"], 3),
                  platform=platform, batch=kb, prompt=kprompt,
                  new_tokens=knew,
                  bytes_per_token_mb=round(bpt / 1e6, 2), **row)

    # top_p decode tax: nucleus sampling rides a static top-k prefilter
    # (generate.TOP_P_PREFILTER_K ranked candidates + an O(V) logsumexp
    # instead of a full-vocab sort per step). Both legs sample at
    # temperature=1.0 so the delta isolates the FILTER's cost, not the
    # cost of stochastic sampling itself.
    tps_by_mode = {}
    for mode, tp in (("off", None), ("on", 0.9)):
        gfn = gen.make_generate(
            cfg, max_new_tokens=new_tokens, compute_dtype=jnp.bfloat16,
            kv_dtype=jnp.bfloat16, temperature=1.0, top_p=tp,
        )
        dt = device_time(gfn, bf16_prepared, ids, rng, n1=1, n2=3)
        tps_by_mode[mode] = b * new_tokens / dt
    overhead = tps_by_mode["off"] / tps_by_mode["on"] - 1.0
    _emit(results, config="gpt2_decode_top_p_tax", metric="overhead_pct",
          value=round(overhead * 100, 2), platform=platform, batch=b,
          new_tokens=new_tokens,
          tps_top_p_off=round(tps_by_mode["off"], 1),
          tps_top_p_on=round(tps_by_mode["on"], 1),
          note=f"top_p=0.9 via top-{gen.TOP_P_PREFILTER_K} prefilter "
               "(bit-identical to the full-vocab filter when the nucleus "
               "fits inside k)")

    # Continuous-batching END-TO-END serving throughput: mixed-length
    # prompts through the slot pool (chunked prefill + per-row decode +
    # retirement), wall-clock including the host-side scheduler — the
    # number a serving user actually gets, vs the pure-device decode rows
    # above. TPU-only: the wall-clock of the host loop on a CPU backend
    # measures nothing interesting.
    if platform == "tpu":
        import time as _time

        from dnn_tpu.runtime.serving import ContinuousBatcher

        sb_new = 64
        # ONE batcher for warmup + timed round: the three step programs
        # are per-instance jit closures, so a fresh instance would
        # recompile inside the timed window and the row would measure
        # XLA, not serving
        srv = ContinuousBatcher(cfg, bf16_prepared, slots=8,
                                max_len=256, prompt_pad=128,
                                kv_dtype=jnp.bfloat16,
                                compute_dtype=jnp.bfloat16)

        def _serve_round(srv_x, n_requests, plen_fn, constraint=None,
                         key=9):
            """Admit-when-a-slot-frees over the pool, then drain — the
            continuous-batching arrival pattern, shared by the e2e and
            constrained-tax rows."""
            rng_np = jax.random.PRNGKey(key)
            rids = []
            for i in range(n_requests):
                p = jax.random.randint(jax.random.fold_in(rng_np, i),
                                       (plen_fn(i),), 0, cfg.vocab_size,
                                       dtype=jnp.int32)
                while srv_x.free_slots() == 0:
                    srv_x.step()
                rids.append(srv_x.submit(
                    jnp.asarray(p), max_new_tokens=sb_new,
                    constraint=constraint))
            out = srv_x.drain()
            return sum(len(out[r]) for r in rids)

        mixed_plen = lambda i: 16 + (i * 7) % 112  # noqa: E731 — 16..121
        _serve_round(srv, 24, mixed_plen)  # compile the three programs
        t0 = _time.perf_counter()
        total = _serve_round(srv, 24, mixed_plen)
        dt = _time.perf_counter() - t0
        _emit(results, config="gpt2_serving_e2e", metric="tokens_per_sec",
              value=round(total / dt, 1), platform=platform, slots=8,
              requests=24, new_tokens_per_req=sb_new,
              note="wall-clock drain of 24 mixed-length requests through "
                   "the continuous batcher (chunked prefill + decode + "
                   "host scheduler)")

        # Constrained-decoding tax: every slot carries a grammar, so each
        # step pays the host-side DFA advance + one batched (slots, V)
        # bias update. The [0-9]+ grammar (2 DFA states) isolates the
        # PER-STEP mechanism cost — table compile is a one-time artifact
        # outside the timed window.
        from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab

        cons = TokenConstraint.from_regex(r"[0-9]+",
                                          byte_vocab(cfg.vocab_size))

        tps_c = {}
        for name, con in (("off", None), ("on", cons)):
            # one batcher per leg, REUSED for warmup + timed round (fresh
            # instances would recompile inside the timed window — same
            # lesson as the serving_e2e row). Both legs run with the bias
            # buffer enabled, so the delta isolates the per-step host DFA
            # walk + batched bias update, not the device-side bias add.
            srv_c = ContinuousBatcher(
                cfg, bf16_prepared, slots=8, max_len=256, prompt_pad=128,
                kv_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
                allow_constraints=True, temperature=1.0)
            _serve_round(srv_c, 16, lambda i: 32, constraint=con,
                         key=11)  # compile/warm
            t0 = _time.perf_counter()
            total = _serve_round(srv_c, 16, lambda i: 32, constraint=con,
                                 key=11)
            tps_c[name] = total / (_time.perf_counter() - t0)
        c_overhead = tps_c["off"] / tps_c["on"] - 1.0
        _emit(results, config="gpt2_serving_constrained_tax",
              metric="overhead_pct", value=round(c_overhead * 100, 2),
              platform=platform, slots=8,
              tps_unconstrained=round(tps_c["off"], 1),
              tps_constrained=round(tps_c["on"], 1),
              note="all 8 slots grammar-constrained ([0-9]+): per-step "
                   "DFA advance + one batched bias-row device update")
    return results


# ----------------------------------------------------------------------
# section: cpu-mesh (8 virtual devices — pipeline forms)
# ----------------------------------------------------------------------

def run_cpu_mesh_section():
    # must precede first backend init: 8 virtual CPU devices
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.parallel.pipeline import (
        RelayExecutor, spmd_pipeline, spmd_pipeline_stacked,
    )
    from dnn_tpu.registry import get_model
    from dnn_tpu.utils.timing import device_time

    assert len(jax.devices()) >= 8, "need 8 virtual CPU devices"
    results = []

    # configs 2 & 3: CIFAR 2-part / 4-part SPMD pipeline, microbatched
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    batch = 64
    x = jnp.asarray(spec.example_input(batch_size=batch))
    for parts, mbs in ((2, 4), (4, 8)):
        stages = spec.partition(parts)
        mesh = make_mesh({STAGE_AXIS: parts}, jax.devices()[:parts])
        sparams = [st.slice_params(params) for st in stages]
        sfns = [st.apply for st in stages]
        # param_placement matches what engine auto policy serves for these
        # sub-threshold models (replicated; see engine.PLACEMENT_AUTO_BYTES)
        # so the published number is the path users actually get
        fn = lambda xx, _s=sfns, _p=sparams, _m=mesh, _mb=mbs: spmd_pipeline(
            _s, _p, xx, mesh=_m, num_microbatches=_mb,
            param_placement="replicated",
        )
        # parity guard: the pipeline must equal the full model before we
        # publish its number
        np.testing.assert_allclose(
            np.asarray(fn(x)), np.asarray(spec.apply(params, x)),
            atol=1e-4, rtol=1e-4,
        )
        dt = device_time(fn, x, n1=2, n2=6)
        _emit(results, config=f"cifar_{parts}stage_pipeline",
              metric="images_per_sec", value=round(batch / dt, 1),
              platform="cpu-mesh", batch=batch, microbatches=mbs)

    # config 5 (pipeline form): 8-stage stacked-block GPT pipeline
    cfg = gpt.GPTConfig(block_size=128, vocab_size=1024, n_layer=8,
                        n_head=4, n_embd=128)
    p = gpt.init(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh({STAGE_AXIS: 8}, jax.devices()[:8])
    stacked = gpt.stack_blocks(p, range(8))
    aux = {k: v for k, v in p.items() if not k.startswith("h_")}
    b, s, mbs = 16, 64, 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             cfg.vocab_size, dtype=jnp.int32)

    def pipe(ids_in):
        xx = gpt.embed(aux, ids_in, cfg=cfg)
        h = spmd_pipeline_stacked(
            lambda bp, a: gpt.block_apply(bp, a, cfg=cfg),
            stacked, xx, mesh=mesh, num_microbatches=mbs,
        )
        return gpt.head(aux, h.astype(jnp.float32), cfg=cfg)

    full = gpt.make_apply(cfg)
    np.testing.assert_allclose(
        np.asarray(pipe(ids)), np.asarray(full(p, ids)), atol=1e-4, rtol=1e-4
    )
    dt = device_time(pipe, ids, n1=2, n2=6)
    _emit(results, config="gpt_8stage_pipeline", metric="tokens_per_sec",
          value=round(b * s / dt, 1), platform="cpu-mesh", batch=b, seq=s,
          microbatches=mbs)

    # interleaved vs GPipe schedule: same 8-layer model on 4 stages, V=2.
    # The structural win is the schedule length — sub-step equivalents
    # V*(M+S-1) vs VM+S-1 — reported alongside measured wall clock (CPU
    # timings carry dispatch noise; the sub-step ratio is the claim)
    from dnn_tpu.parallel.pipeline import (
        interleaved_schedule_steps, spmd_pipeline_interleaved,
    )

    s_stages, v, mbs2 = 4, 2, 8
    mesh4 = make_mesh({STAGE_AXIS: s_stages}, jax.devices()[:s_stages])
    x_emb = gpt.embed(aux, ids, cfg=cfg)
    per_st = cfg.n_layer // s_stages
    st4 = gpt.stack_blocks(p, range(cfg.n_layer))
    stage_form = jax.tree.map(
        lambda q: q.reshape(s_stages, per_st, *q.shape[1:]), st4)
    chunk_form = jax.tree.map(
        lambda q: q.reshape(v * s_stages, cfg.n_layer // (v * s_stages),
                            *q.shape[1:]), st4)

    def run_gpipe(xx):
        return spmd_pipeline_stacked(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg),
            stage_form, xx, mesh=mesh4, num_microbatches=mbs2)

    def run_inter(xx):
        return spmd_pipeline_interleaved(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg),
            chunk_form, xx, mesh=mesh4, num_microbatches=mbs2,
            virtual_stages=v)

    np.testing.assert_allclose(
        np.asarray(run_inter(x_emb)), np.asarray(run_gpipe(x_emb)),
        atol=1e-4, rtol=1e-4)
    dt_g = device_time(run_gpipe, x_emb, n1=2, n2=6)
    dt_i = device_time(run_inter, x_emb, n1=2, n2=6)
    gpipe_substeps = v * (mbs2 + s_stages - 1)
    inter_substeps = interleaved_schedule_steps(s_stages, v, mbs2)
    _emit(results, config="interleaved_vs_gpipe",
          metric="substep_ratio",
          value=round(inter_substeps / gpipe_substeps, 4),
          platform="cpu-mesh", stages=s_stages, virtual=v,
          microbatches=mbs2,
          gpipe_ms=round(dt_g * 1e3, 2), interleaved_ms=round(dt_i * 1e3, 2),
          note="schedule length V(M+S-1) -> VM+S-1. CPU wall-clock "
               "typically favors gpipe: interleaving doubles the scan "
               "steps and ring hops (per-sub-step dispatch + dynamic "
               "chunk gather dominate on CPU); the bubble win needs "
               "stage COMPUTE to dominate, i.e. real chips + real models")

    # LLaMA seq-sharded decode on a 4-device "seq" mesh: each device owns
    # a contiguous block of cache positions at GQA KV-head width; decode
    # steps combine per-shard attention with the exact distributed online
    # softmax (llama.make_generate_seq_sharded). Parity-guarded against
    # the solo decoder before the number is published; cpu-mesh value
    # validates the machinery, not the speed.
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import SEQ_AXIS

    ll_cfg = llama.PRESETS["llama-test"]
    ll_p = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), ll_cfg), ll_cfg)
    smesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    lb, lt, lnew = 2, 8, 16
    l_ids = jax.random.randint(jax.random.PRNGKey(2), (lb, lt), 0,
                               ll_cfg.vocab_size, dtype=jnp.int32)
    l_rng = jax.random.PRNGKey(3)
    gen_seq = llama.make_generate_seq_sharded(
        ll_cfg, smesh, max_new_tokens=lnew)
    np.testing.assert_array_equal(
        np.asarray(gen_seq(ll_p, l_ids, l_rng)),
        np.asarray(llama.make_generate(ll_cfg, max_new_tokens=lnew)(
            ll_p, l_ids, l_rng)))
    dt = device_time(gen_seq, ll_p, l_ids, l_rng, n1=1, n2=3)
    _emit(results, config="llama_seq_sharded_decode",
          metric="tokens_per_sec", value=round(lb * lnew / dt, 1),
          platform="cpu-mesh", batch=lb, new_tokens=lnew, seq_shards=4,
          note="each shard holds ceil(S_max/4) cache positions at "
               "KV-head width; token-parity with the solo decoder "
               "asserted in-run")

    # p50 inter-stage hop latency (relay executor, device-to-device)
    stages = spec.partition(2)
    relay = RelayExecutor(
        [st.apply for st in stages],
        [st.slice_params(params) for st in stages],
        devices=jax.devices()[:2],
    )
    hops = []
    for _ in range(9):
        hops.extend(relay.measure_hop_latency(x))
    p50 = float(np.percentile(hops, 50))
    _emit(results, config="interstage_hop", metric="p50_latency_ms",
          value=round(p50 * 1e3, 4), platform="cpu-mesh",
          note="v5e ICI target <2ms not measurable single-chip")
    return results


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------

def _run_subprocess(section, extra_env):
    """Run one section with bounded retries, salvaging completed rows.

    A section attempt can end three ways: ok, timeout (hang — usually the
    axon tunnel wedging mid-compile), or crash (e.g. a transient
    `UNAVAILABLE: TPU backend setup/compile error` partway through, which
    round 4 hit live after three good rows). One transient failure must
    not cost the round's table (VERDICT r3 #1), so: retry up to
    DNN_BENCH_SECTION_ATTEMPTS (default 2) with a backoff, and if no
    attempt completes, keep the attempt that measured the MOST rows and
    append an explicit truncation marker instead of throwing them away."""
    attempts = int(os.environ.get("DNN_BENCH_SECTION_ATTEMPTS", "2"))
    backoff = int(os.environ.get("DNN_BENCH_SECTION_BACKOFF", "60"))
    best_rows, last_status = [], "unknown"
    for i in range(attempts):
        rows, status = _run_subprocess_once(section, extra_env)
        if status == "ok":
            return rows
        last_status = status
        if len(rows) >= len(best_rows):
            best_rows = rows
        more = i + 1 < attempts
        print(f"[run_all] section {section} attempt {i + 1}/{attempts} "
              f"ended with {status} ({len(rows)} rows); "
              + (f"retrying in {backoff}s" if more
                 else "salvaging completed rows"), file=sys.stderr)
        if more:
            time.sleep(backoff)
    if not best_rows:
        raise RuntimeError(
            f"section {section} {last_status} with no completed rows "
            f"after {attempts} attempts")
    best_rows.append({
        "config": f"{section}_section", "metric": "truncated",
        "value": True, "platform": "meta",
        "note": (f"section {last_status} on all {attempts} attempts; the "
                 "rows above are complete measurements, later configs "
                 "are missing"),
    })
    return best_rows


def _run_subprocess_once(section, extra_env):
    """One section attempt, STREAMING its row lines so a mid-run death
    keeps every completed measurement; returns (rows, status) with
    status in {"ok", "timeout", "crash"}. Two hard-won lessons encoded
    here:
      * 1800 s proved too tight once the device section grew the decode
        matrix + train/serving rows and anything competed for the single
        host core during compilation — the timeout is now 3600 s and
        env-overridable (DNN_BENCH_SECTION_TIMEOUT);
      * a timeout used to discard the whole section's stdout AND the
        parent's kill of a child mid-device-op can wedge the TPU tunnel
        for a long time afterward (jax.devices() hanging past 300 s) —
        so rows are captured as they are emitted (_emit flushes one JSON
        line per row) and survive the kill."""
    import threading

    env = dict(os.environ, **extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__),
         "--section", section],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )
    out_lines, err_chunks = [], []

    def _drain(stream, sink):
        for line in stream:
            sink.append(line)

    threads = [
        threading.Thread(target=_drain, args=(proc.stdout, out_lines),
                         daemon=True),
        threading.Thread(target=_drain, args=(proc.stderr, err_chunks),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    timeout = int(os.environ.get("DNN_BENCH_SECTION_TIMEOUT", "3600"))
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()  # best-effort; D-state children cannot be reaped —
        # the daemon reader threads are abandoned rather than joined hard
        try:
            proc.wait(timeout=10)  # reap the killed child (no zombie)
        except subprocess.TimeoutExpired:
            pass
    for t in threads:
        t.join(timeout=30)
    rows = []
    for l in out_lines:
        if not l.startswith("{"):
            continue
        try:
            rows.append(json.loads(l))
        except json.JSONDecodeError:
            pass  # SIGKILL mid-write truncates the final line; skip it
    if timed_out:
        print(f"[run_all] section {section} timed out after {timeout}s "
              f"with {len(rows)} completed rows. Child stderr tail "
              f"(where it hung):\n" + "".join(err_chunks[-30:]),
              file=sys.stderr)
        return rows, "timeout"
    if proc.returncode != 0:
        print(f"[run_all] section {section} child died rc={proc.returncode} "
              f"with {len(rows)} completed rows. Child stderr tail:\n"
              + "".join(err_chunks[-30:]), file=sys.stderr)
        return rows, "crash"
    return rows, "ok"


def _provenance():
    """Commit/date/platform stamp so a reader can always tell whether the
    table matches the harness that claims to produce it (round-3 lesson:
    RESULTS.md silently predated run_all.py's own additions)."""
    import datetime

    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, cwd=REPO, timeout=10).stdout.strip() or "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, cwd=REPO, timeout=10).stdout.strip()
        if dirty:
            rev += "-dirty"
    except Exception:
        rev = "unknown"
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")
    return rev, stamp


def write_results_md(rows, path):
    rev, stamp = _provenance()
    platforms = sorted({r.get("platform", "?") for r in rows
                        if r.get("platform") != "cpu-mesh"})
    lines = [
        "# Benchmark results (measured)",
        "",
        f"Generated at commit `{rev}` on {stamp}; device-section platform: "
        f"{', '.join(platforms) or 'none (device section skipped)'}.",
        "",
        "Produced by `python benchmarks/run_all.py`. The reference publishes",
        "no numbers (SURVEY §6); BASELINE.md maps these configs to its",
        "capability matrix. `cpu-mesh` rows run the multi-stage machinery on",
        "8 virtual CPU devices (no multi-chip TPU in this environment) — they",
        "validate the parallel path; absolute values are CPU-bound.",
        "",
        "| config | metric | value | mfu | platform | details |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        details = ", ".join(
            f"{k}={v}" for k, v in r.items()
            if k not in ("config", "metric", "value", "platform", "mfu")
        )
        mfu_cell = f"{r['mfu']:.1%}" if "mfu" in r else "—"
        lines.append(
            f"| {r['config']} | {r['metric']} | {r['value']} | {mfu_cell} | "
            f"{r['platform']} | {details} |"
        )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", choices=["device", "cpu_mesh"])
    ap.add_argument("--out", default=os.path.join(REPO, "benchmarks", "RESULTS.md"))
    args = ap.parse_args()

    if args.section == "device":
        run_device_section()
        return
    if args.section == "cpu_mesh":
        run_cpu_mesh_section()
        return

    rows = _run_subprocess("device", {})
    rows += _run_subprocess("cpu_mesh", {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8").strip(),
    })
    write_results_md(rows, args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
