"""kv_tier: the fleet KV tier's measured contract (ISSUE 15).

Router + 2 REAL `node --serve_lm` replica subprocesses (gpt2, paged KV
+ the radix prefix store) under the PR 13 multi-turn-chat arrival
schedule with affinity DELIBERATELY BROKEN: the router runs
`kvtier="pull"` (placement by round-robin policy, never by prefix
holder) and the schedule assigns every warm chat turn to the replica
that did NOT prefill its tenant's system prompt — the worst case for
a per-replica cache, and exactly the traffic the fleet tier exists to
serve. The only thing that can save the reuse is block migration over
the lease rungs.

Asserted (--assert exits nonzero when any fails):

  * cross-replica block-hit ratio >= CROSS_HIT_FLOOR (0.5): of all
    block-granular prefix hits across the fleet, at least half were
    served from blocks ADOPTED from a sibling (read off the replicas'
    own counters — serving_kvtier_remote_block_hits_total /
    serving_prefix_blocks_reused_total);
  * adopted-block decode is TOKEN-IDENTICAL to local prefill, greedy
    AND seeded-sampled (direct replica clients, the migration forced
    with kv_pull_from);
  * warm-turn TTFT p95 is >= TTFT_RATIO_FLOOR (2.0x) better than
    forced-cold (unique-prefix) TTFT p95 — both measured as
    first-streamed-token time through the SAME router. "Warm" = the
    tier's steady state: each tenant's FIRST anti-affinity turn pays
    the one-time synchronous migration on its own TTFT and rides the
    row as `migration_ttft_p95_ms` instead (the price of moving the
    blocks is reported, not hidden — and paid once, not per turn);
  * migrated bytes per warm request < the full-KV row-handoff baseline
    (the PR 12 `prefill` endpoint's packed payload for the same
    prompt, measured on the wire);
  * the donor-death chaos leg: a lease with no adopter EXPIRES
    (lease_expire + lease_reclaim in the donor's dumped /debugz ring),
    and a pull against a SIGKILLed donor falls back loud
    (kvtier_fallback in the adopter's ring) with the follow-up
    generate completing token-identical to the donor's pre-kill output
    and the adopter's pool accounting at baseline (zero leaked
    blocks).

Prefill-FLOPs-avoided lands on the goodput gauges the replicas already
export; the row reports the fleet's prefill-chunk saving against the
cold-equivalent count.

`python -m benchmarks.kv_tier_probe [--assert] [--light]` prints one
JSON row; run_all's `kv_tier` row rides `measure()` and the ledger
imports the floors from here.
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

CROSS_HIT_FLOOR = 0.5
TTFT_RATIO_FLOOR = 2.0

MODEL = "gpt2"        # real prefill costs: the regime where skipping
# chunks is a measurable TTFT win (a toy config's prefill is noise)
SLOTS = 2
MAX_LEN = 96
PROMPT_PAD = 16
BLOCK_LEN = 8
SYS_BLOCKS = 6        # system prompt = 48 tokens = 6 shared blocks
MAX_NEW = 8
LEASE_TTL_S = 4.0
READY_DEADLINE_S = 240.0

_BASE = (59941, 59951)   # (grpc base, metrics base) for 2 replicas
_ROUTER_PORT = 59940


def _sys_prompt(tenant: int):
    import numpy as np

    return (np.arange(1, SYS_BLOCKS * BLOCK_LEN + 1) * (tenant + 3)
            % 997 + 1).astype(np.int32)


def _tail(i: int):
    import numpy as np

    n = 4 + (i * 7) % 4
    return ((np.arange(n) * 13 + i * 31) % 997 + 1).astype(np.int32)


def _scrape(port: int) -> dict:
    """Prometheus text -> {name: value} (labels folded by summation —
    enough for the counters this probe reads)."""
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ).read().decode()
    out: dict = {}
    for line in text.splitlines():
        m = re.match(r"^([a-zA-Z_:][\w:]*)(?:\{[^}]*\})? ([-+0-9.eE]+)$",
                     line)
        if m:
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(
                m.group(2))
    return out


def _rotation() -> int:
    """The in-process router's round-robin position, READ OFF ITS OWN
    COUNTERS instead of mirrored locally: every admitted request
    (outcome ok / error / deadline / unroutable — sheds never reach
    the pick) advanced the rotation exactly once in this serialized
    probe. Re-read before every placement-sensitive send, so a stray
    sibling retry (which advances the pick invisibly) mis-steers at
    most the one next turn instead of flipping the whole anti-affinity
    pattern — the drift that read 0.52 where the pattern should read
    ~1.0."""
    from dnn_tpu import obs

    m = obs.metrics()
    if m is None:
        return 0
    n = 0
    for key, val in m.snapshot()["counters"].items():
        if key.startswith("dnn_tpu_router_requests_total") \
                and 'outcome="shed"' not in key \
                and 'outcome="draining"' not in key:
            n += int(val)
    return n


def _debugz(port: int) -> list:
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debugz?format=json", timeout=10
    ).read().decode())


def _stream_ttft(address: str, prompt, rid: str,
                 timeout: float = 120.0):
    """-> (ttft_s, tokens) via GenerateStream — first token time is
    the real TTFT, not request completion."""
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    cl = NodeClient(address, transport="grpc", breaker=False)
    n = 0
    t0 = time.perf_counter()
    ttft = None
    try:
        for _resp in cl.send_tensor_stream(prompt, request_id=rid,
                                           timeout=timeout):
            if ttft is None:
                ttft = time.perf_counter() - t0
            n += 1
    finally:
        cl.close()
    return ttft, n


def _gen(address: str, prompt, *, seed=None, temperature=None,
         timeout: float = 120.0):
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    cl = NodeClient(address, transport="grpc", breaker=False)
    try:
        return np.asarray(cl.generate(
            prompt, max_new_tokens=MAX_NEW, seed=seed,
            temperature=temperature, timeout=timeout))
    finally:
        cl.close()


def _warm(address: str, deadline_s: float = 300.0):
    import numpy as np

    from dnn_tpu.comm.client import NodeClient

    t_end = time.monotonic() + deadline_s
    last = "no attempt"
    probe = (np.arange(1, 9) % 97 + 1).astype(np.int32)
    while time.monotonic() < t_end:
        cl = NodeClient(address, transport="grpc", breaker=False)
        try:
            _, result = cl.send_tensor(
                probe, request_id=f"gen:{MAX_NEW}:0", timeout=120.0,
                retries=0)
            if result is not None:
                return
        except Exception as e:  # noqa: BLE001 — still booting
            last = f"{type(e).__name__}: {e}"
        finally:
            cl.close()
        time.sleep(1.0)
    raise RuntimeError(f"warm request never completed: {last[:200]}")


def _p95(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.95 * (len(xs) - 1)))] if xs \
        else None


def measure(light: bool = False) -> dict:
    import numpy as np

    from dnn_tpu.control.replicaset import ReplicaSet
    from dnn_tpu.control.router import start_router_in_background
    from dnn_tpu.workloads.arrivals import poisson_arrivals

    n_cold = 4 if light else 8
    warm_rate = 0.5 if light else 0.6
    warm_dur = 20.0 if light else 40.0
    row: dict = {"model": MODEL, "block_len": BLOCK_LEN,
                 "sys_blocks": SYS_BLOCKS, "max_new": MAX_NEW}
    with tempfile.TemporaryDirectory(prefix="kv_tier_") as tmp:
        rset = ReplicaSet.spawn_lm_fleet(
            tmp, model=MODEL, base_port=_BASE[0],
            metrics_base_port=_BASE[1], roles=["both"] * 2,
            slots=SLOTS, max_len=MAX_LEN, kv="paged",
            ready_deadline_s=READY_DEADLINE_S,
            extra_args=["--prefix_cache", "64",
                        "--block_len", str(BLOCK_LEN),
                        "--prompt_pad", str(PROMPT_PAD),
                        # pool sized for the TIER, not only the slots:
                        # the auto-sized 25-block pool forces constant
                        # store eviction under 2 tenants + live slots
                        # (a mis-deployment, and measurement churn)
                        "--paged_blocks", "64",
                        "--kv_lease_ttl_s", str(LEASE_TTL_S)])
        rset.start()
        router = rstop = None
        try:
            if not rset.wait_serving(2, READY_DEADLINE_S):
                raise RuntimeError("replicas never came up")
            # affinity deliberately broken: placement is the rotation,
            # never the holder; the directory only instructs PULLS
            router, rstop = start_router_in_background(
                rset, port=_ROUTER_PORT, policy="round_robin",
                kvtier="pull", kv_block_len=BLOCK_LEN,
                max_inflight_per_replica=SLOTS,
                default_deadline_s=120.0)
            raddr = f"127.0.0.1:{_ROUTER_PORT}"
            addrs = {name: h.address
                     for name, h in rset.replicas.items()}
            mports = {name: int(h.obs_url.rsplit(":", 1)[1])
                      for name, h in rset.replicas.items()}
            names = sorted(addrs)
            for a in addrs.values():
                _warm(a)
            _warm(raddr)

            def stream_routed(prompt, rid):
                t, _ = _stream_ttft(raddr, prompt, rid)
                return t

            # ---- forced-cold TTFT: unique prefixes, zero reuse ------
            cold_ttfts = []
            for i in range(n_cold):
                p = np.concatenate([
                    ((np.arange(1, SYS_BLOCKS * BLOCK_LEN + 1)
                      * (i + 11) * 17) % 991 + 1).astype(np.int32),
                    _tail(900 + i)])
                cold_ttfts.append(stream_routed(
                    p, f"gen:{MAX_NEW}:{7000 + i}"))

            # ---- seed: one cold turn per tenant through the router --
            origin = {}
            for t in range(2):
                placed = names[_rotation() % 2]
                stream_routed(
                    np.concatenate([_sys_prompt(t), _tail(t)]),
                    f"gen:{MAX_NEW}:{7100 + t}")
                origin[t] = placed
            row["origin"] = dict(origin)

            # ---- warm turns: every arrival goes to the tenant whose
            # blocks live on the OTHER replica (anti-affinity) --------
            arrivals = poisson_arrivals(warm_rate, warm_dur, seed=15,
                                        name="kvtier:chat")
            scr0 = {n: _scrape(mports[n]) for n in names}
            t0 = time.monotonic()
            # each tenant's FIRST anti-affinity turn carries the
            # synchronous block migration (lease + pull + adopt ride
            # its TTFT — the price of moving the blocks, paid once);
            # every later turn is the tier's steady state. Both
            # populations ride the row; the asserted p95 is the steady
            # state — the number millions of follow-up turns see.
            warm_ttfts, migration_ttfts = [], []
            seen_tenant: set = set()
            for i, at in enumerate(arrivals):
                now = time.monotonic() - t0
                if now < at:
                    time.sleep(at - now)
                placed = names[_rotation() % 2]
                tenant = next(t for t in (0, 1)
                              if origin[t] != placed)
                ttft = stream_routed(
                    np.concatenate([_sys_prompt(tenant),
                                    _tail(100 + i)]),
                    f"gen:{MAX_NEW}:{7200 + i}")
                if tenant in seen_tenant:
                    warm_ttfts.append(ttft)
                else:
                    seen_tenant.add(tenant)
                    migration_ttfts.append(ttft)
            scr1 = {n: _scrape(mports[n]) for n in names}

            def delta(key):
                return sum(scr1[n].get(key, 0.0)
                           - scr0[n].get(key, 0.0) for n in names)

            reused = delta("serving_prefix_blocks_reused_total")
            remote = delta("serving_kvtier_remote_block_hits_total")
            chunks = delta("serving_prefill_chunks_total")
            cold_equiv = sum(
                -(-(SYS_BLOCKS * BLOCK_LEN + _tail(100 + i).size)
                  // PROMPT_PAD)
                for i in range(len(arrivals)))
            migrated_bytes = delta("dnn_tpu_kvtier_migrated_bytes_total")
            migrated_blocks = delta(
                "dnn_tpu_kvtier_migrated_blocks_total")
            cross_ratio = remote / reused if reused else 0.0

            # ---- full-KV row-handoff baseline (the PR 12 wire) ------
            from dnn_tpu.comm.client import NodeClient

            cl = NodeClient(addrs[names[0]], transport="grpc",
                            breaker=False)
            try:
                row_handoff_bytes = int(cl.prefill_kv(
                    np.concatenate([_sys_prompt(0), _tail(0)]),
                    timeout=120.0).size)
            finally:
                cl.close()
            n_turns = len(warm_ttfts) + len(migration_ttfts)
            per_request_bytes = (migrated_bytes / n_turns
                                 if n_turns else 0.0)

            # ---- adopted-vs-local token parity (greedy + sampled) ---
            from dnn_tpu.comm.client import NodeClient as _NC

            par_prompt = np.concatenate([_sys_prompt(0), _tail(555)])
            donor_name = origin[0]
            other = next(n for n in names if n != donor_name)
            greedy_d = _gen(addrs[donor_name], par_prompt)
            samp_d = _gen(addrs[donor_name], par_prompt, seed=42,
                          temperature=0.9)
            cl = _NC(addrs[other], transport="grpc", breaker=False)
            try:
                pull_status = cl.kv_pull_from(addrs[donor_name],
                                              par_prompt)
            finally:
                cl.close()
            greedy_a = _gen(addrs[other], par_prompt)
            samp_a = _gen(addrs[other], par_prompt, seed=42,
                          temperature=0.9)
            parity = (greedy_d.tolist() == greedy_a.tolist()
                      and samp_d.tolist() == samp_a.tolist())
            row["parity_pull_status"] = str(pull_status)[:120]

            # ---- donor-death chaos leg ------------------------------
            # (a) an unconsumed lease on the donor expires: stage a
            # fresh prefix, lease it, never fetch — the TTL sweep must
            # record lease_expire + lease_reclaim in the DONOR's ring
            chaos_prompt = np.concatenate([
                ((np.arange(1, SYS_BLOCKS * BLOCK_LEN + 1) * 29)
                 % 983 + 1).astype(np.int32), _tail(777)])
            pre_kill = _gen(addrs[donor_name], chaos_prompt, seed=5,
                            temperature=0.8)
            cl = _NC(addrs[donor_name], transport="grpc",
                     breaker=False)
            try:
                lease_meta = cl.kv_lease(chaos_prompt)
            finally:
                cl.close()
            time.sleep(LEASE_TTL_S + 2.5)  # TTL + housekeeping tick
            donor_ring = _debugz(mports[donor_name])
            expired = [e for e in donor_ring
                       if e.get("kind") == "lease_expire"
                       and e.get("lease") == lease_meta["lease"]]
            reclaimed = [e for e in donor_ring
                         if e.get("kind") == "lease_reclaim"
                         and e.get("lease") == lease_meta["lease"]]
            # (b) SIGKILL the donor mid-migration: the adopter's pull
            # fails -> kvtier_fallback in ITS ring, the follow-up
            # generate re-prefills token-identically, zero leaks
            rset.replicas[donor_name].kill()
            cl = _NC(addrs[other], transport="grpc", breaker=False)
            try:
                dead_status = cl.kv_pull_from(addrs[donor_name],
                                              chaos_prompt,
                                              timeout=30.0)
            finally:
                cl.close()
            post_kill = _gen(addrs[other], chaos_prompt, seed=5,
                             temperature=0.8)
            other_ring = _debugz(mports[other])
            fallback_ev = [e for e in other_ring
                           if e.get("kind") == "kvtier_fallback"]
            other_m = _scrape(mports[other])
            used = other_m.get("serving_paged_blocks_used", -1.0)
            resident = other_m.get("dnn_tpu_kvtier_blocks", -2.0)
            # with no live requests, every used block must be store-
            # resident — anything else is a leak
            zero_leaks = used == resident
            chaos_ok = (bool(expired) and bool(reclaimed)
                        and "kvtier_fallback" in dead_status
                        and pre_kill.tolist() == post_kill.tolist()
                        and zero_leaks)
            # dump the artifacts the assertions just read
            dump = os.path.join(tempfile.gettempdir(),
                                f"kv_tier_rings_{os.getpid()}.json")
            with open(dump, "w") as f:
                json.dump({"donor": donor_ring, "adopter": other_ring},
                          f)

            warm_p95 = _p95(warm_ttfts)
            cold_p95 = _p95(cold_ttfts)
            ttft_ratio = (cold_p95 / warm_p95
                          if warm_p95 and cold_p95 else 0.0)
            ok_cross = cross_ratio >= CROSS_HIT_FLOOR
            ok_ttft = ttft_ratio >= TTFT_RATIO_FLOOR
            ok_bytes = (0 < per_request_bytes < row_handoff_bytes
                        if n_turns else False)
            row.update({
                "warm_turns": len(warm_ttfts),
                "migration_turns": len(migration_ttfts),
                "migration_ttft_p95_ms": round(
                    (_p95(migration_ttfts) or 0.0) * 1e3, 1),
                "cold_requests": n_cold,
                "ttft_cold_p95_ms": round(cold_p95 * 1e3, 1),
                "ttft_warm_p95_ms": round(warm_p95 * 1e3, 1),
                "ttft_cold_over_warm": round(ttft_ratio, 2),
                "blocks_reused": int(reused),
                "remote_block_hits": int(remote),
                "cross_replica_hit_ratio": round(cross_ratio, 4),
                "prefill_chunks_run_warm": int(chunks),
                "prefill_chunks_cold_equiv": int(cold_equiv),
                "prefill_chunks_avoided_frac": round(
                    1.0 - chunks / cold_equiv, 4) if cold_equiv else 0.0,
                "migrated_blocks": int(migrated_blocks),
                "migrated_bytes_total": int(migrated_bytes),
                "migrated_bytes_per_request": round(per_request_bytes),
                "row_handoff_baseline_bytes": row_handoff_bytes,
                "token_parity": bool(parity),
                "lease_expired_in_ring": bool(expired),
                "lease_reclaimed_in_ring": bool(reclaimed),
                "donor_death_fallback": "kvtier_fallback"
                                        in dead_status,
                "donor_death_parity":
                    pre_kill.tolist() == post_kill.tolist(),
                "zero_leaked_blocks": bool(zero_leaks),
                "rings_dump": dump,
                "ok_cross_hit": bool(ok_cross),
                "ok_ttft": bool(ok_ttft),
                "ok_bytes": bool(ok_bytes),
                "ok_parity": bool(parity),
                "ok_chaos": bool(chaos_ok),
                "ok": bool(ok_cross and ok_ttft and ok_bytes
                           and parity and chaos_ok),
                # replica children are pinned JAX_PLATFORMS=cpu (the
                # one-tunnel-client rule): the measured serving ran on
                # cpu whatever this parent process sees
                "platform": "cpu",
                "round_substrate": "cpu",
            })
        finally:
            if rstop is not None:
                rstop()
            rset.stop()
    return row


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--assert", dest="do_assert", action="store_true")
    ap.add_argument("--light", action="store_true",
                    help="shortened legs (smoke use; the acceptance "
                         "configuration is the full run)")
    ap.add_argument("--require-substrate", choices=["tpu", "cpu"],
                    default=os.environ.get("DNN_TPU_REQUIRE_SUBSTRATE")
                    or None)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    row = measure(light=args.light)
    if args.require_substrate:
        row["required_substrate"] = args.require_substrate
        if row["round_substrate"] != args.require_substrate:
            row["ok"] = False
            row["note"] = (f"required substrate "
                           f"'{args.require_substrate}' but the probe "
                           f"ran on '{row['round_substrate']}'")
    print(json.dumps(row), flush=True)
    if args.do_assert and not row["ok"]:
        print("ASSERT FAILED: "
              f"cross_hit={row.get('cross_replica_hit_ratio')} "
              f"(floor {CROSS_HIT_FLOOR}), "
              f"ttft_ratio={row.get('ttft_cold_over_warm')} "
              f"(floor {TTFT_RATIO_FLOOR}), "
              f"bytes={row.get('ok_bytes')}, "
              f"parity={row.get('ok_parity')}, "
              f"chaos={row.get('ok_chaos')}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
