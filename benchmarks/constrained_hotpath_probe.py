"""Constrained hot-path probe: the ISSUE 16 ratchet pair, asserted.

PR 16 moved the grammar DFA walk ON DEVICE (an int32 transition-table
pool next to the mask pool, the state advance folded into the decode
programs as donated per-slot carried state) and lifted the composition
rejections that pinned constrained decoding to convoy admission. This
probe measures exactly that delta and pins correctness while doing it:

  * **convoy** (the BEFORE leg, report-only): every request grammar-
    constrained ([0-9]+ over the byte vocab), admitted through inline
    prefill — the only path constraints had before this PR. This leg
    doubles as the ORACLE: its per-request token streams come from the
    same seeds as the hot leg's, so divergence means the device walk
    and the host walk disagree.

  * **hot** (ASSERTED): the same constrained population on the ISSUE 12
    machinery — interleaved chunked prefill + double-buffered overlap —
    which the on-device walk just unlocked for constrained traffic.
    Asserted: tokens/sec >= SPEEDUP_FLOOR x the convoy leg,
    host-serialization fraction <= step_timeline_probe's
    HOST_FRACTION_CEIL (0.40 — the same ceiling the unconstrained hot
    path answers to: constraints may no longer buy a softer ratchet),
    and EXACT token parity with the convoy leg.

Every emitted token is ALSO replayed through the host-side DFA
(TokenConstraint.table/allowed) — a pure-host oracle independent of
both serving legs: each sampled token must be legal at the walked
state, whatever the device said.

Standalone:  python benchmarks/constrained_hotpath_probe.py [--assert]
Suite row:   benchmarks/run_all.py config `constrained_hotpath`
             (cpu-runnable); ledger ratchets `constrained_speedup_floor`
             + `constrained_host_fraction` read it.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: asserted floor on hot-leg tokens/sec over the convoy leg's, both
#: legs fully grammar-constrained. The convoy leg pays an inline
#: prefill stall per admit; measured ~1.5-2.1x on this host — 1.15
#: catches a regression to convoy-class admission with margin while
#: tolerating scheduler noise.
SPEEDUP_FLOOR = 1.15

SLOTS = 4
REQUESTS = 16     # timed round: admitted continuously into the 4 slots
NEW_TOKENS = 24   # short decodes keep the admission pressure on
PROMPT = 8


def _build(hot: bool):
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    # the step_timeline_probe shape (s10/s11 standard: dense bucketed
    # f32) + the constraint machinery on BOTH legs; the hot leg adds
    # ONLY the ISSUE 12 knobs, so the delta between the legs is the
    # admission path and nothing else.
    cfg = gpt.GPTConfig(block_size=256, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    kw = {}
    if hot:
        kw = {"prefill_chunk_tokens": 16, "overlap": True}
    return ContinuousBatcher(cfg, prepared, slots=SLOTS,
                             max_len=cfg.block_size, prompt_pad=16,
                             decode_buckets=True, temperature=1.0,
                             allow_constraints=True, constraint_rows=8,
                             **kw)


def _constraint(vocab_size: int):
    from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab

    return TokenConstraint.from_regex(r"[0-9]+", byte_vocab(vocab_size))


def _host_walk_ok(c, tokens) -> bool:
    """Pure-host DFA oracle: replay `tokens` from the start state —
    every token must be allowed where it was sampled."""
    s = c.start
    for t in tokens:
        if not bool(c.allowed[s, t]):
            return False
        s = int(c.table[s, t])
    return True


def _leg(hot: bool, n_requests: int, new_tokens: int) -> tuple:
    """One measured constrained leg -> (row dict, per-request tokens)."""
    import numpy as np

    from dnn_tpu.obs.timeline import PHASES, StepClock

    srv = _build(hot)
    cons = _constraint(srv.cfg.vocab_size)
    clock = StepClock(capacity=8192).install()
    srv.step_clock = clock

    def round_(n_req=n_requests, collect=False):
        rids = []
        for i in range(n_req):
            while srv.free_slots() == 0:
                srv.step()
            rids.append(srv.submit(np.arange(1, PROMPT + 1), new_tokens,
                                   seed=i, constraint=cons))
        srv.drain()
        toks = [list(srv.results[r]) for r in rids] if collect else None
        srv.results.clear()
        srv.finish_reasons.clear()
        return toks

    # steady state: two warm rounds (bucket-ladder growth, then the
    # admission programs at the grown rungs), as in step_timeline_probe
    round_(SLOTS)
    round_(SLOTS)
    base = clock.steps_total
    t0 = time.perf_counter()
    toks = round_(collect=True)
    wall = time.perf_counter() - t0
    n_steps = clock.steps_total - base
    recs = clock.records()[-n_steps:]
    sums = {p: 0.0 for p in PHASES}
    for r in recs:
        for p, v in r["phases"].items():
            sums[p] = sums.get(p, 0.0) + v
    host_s = sum(sums[p] for p in ("admit", "host", "commit", "obs"))
    tokens = sum(len(t) for t in toks)
    leg = {
        "wall_s": round(wall, 4),
        "steps": n_steps,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 1),
        # same denominator discipline as step_timeline_probe: the
        # EXTERNAL wall, so an attribution hole cannot deflate it
        "host_serialization_fraction": round(host_s / wall, 4),
        "host_walk_oracle_ok": all(_host_walk_ok(cons, t) for t in toks),
    }
    return leg, toks


def measure(light: bool = False) -> dict:
    from dnn_tpu import obs

    from benchmarks.step_timeline_probe import HOST_FRACTION_CEIL

    was = obs.enabled()
    obs.set_enabled(True)
    try:
        n_req = 8 if light else REQUESTS
        new_tokens = 12 if light else NEW_TOKENS
        convoy, convoy_toks = _leg(hot=False, n_requests=n_req,
                                   new_tokens=new_tokens)
        hot, hot_toks = _leg(hot=True, n_requests=n_req,
                             new_tokens=new_tokens)
        row = {
            "slots": SLOTS, "requests": n_req, "new_tokens": new_tokens,
            "leg": "all slots grammar-constrained ([0-9]+), seeded "
                   "sampled (t=1.0): interleaved prefill (chunk=16) + "
                   "overlap vs the convoy-admission control",
            "convoy": convoy,
            "hot": hot,
            "vs_convoy_tps": round(
                hot["tokens_per_sec"] / convoy["tokens_per_sec"], 3),
            "host_fraction": hot["host_serialization_fraction"],
            # parity oracle: same seeds, same grammar — the device walk
            # must reproduce the convoy streams token for token
            "parity_ok": bool(hot_toks == convoy_toks),
            "oracle_ok": bool(convoy["host_walk_oracle_ok"]
                              and hot["host_walk_oracle_ok"]),
            "speedup_floor": SPEEDUP_FLOOR,
            "host_fraction_ceil": HOST_FRACTION_CEIL,
        }
        row["ok_speedup"] = bool(row["vs_convoy_tps"] >= SPEEDUP_FLOOR)
        row["ok_host_fraction"] = bool(
            row["host_fraction"] <= HOST_FRACTION_CEIL)
        row["ok"] = (row["parity_ok"] and row["oracle_ok"]
                     and row["ok_speedup"] and row["ok_host_fraction"])
        return row
    finally:
        obs.set_enabled(was)


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure(light="--light" in args)
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: parity={row['parity_ok']} oracle={row['oracle_ok']}"
              f" vs_convoy_tps={row['vs_convoy_tps']} "
              f"(floor {SPEEDUP_FLOOR}), host_fraction="
              f"{row['host_fraction']} (ceil {row['host_fraction_ceil']})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
