"""CIFAR CNN MFU experiments — close (or explain) the gap to the roofline cap.

The measured forward sits well below the model's own roofline cap
(benchmarks/RESULTS.md row 1; BASELINE.md's arithmetic-intensity argument
puts the cap around 22% MFU at B=256 — the CNN streams too many
activation bytes per FLOP for the MXU to stay busy). This probe times
controlled variants to find which structural lever moves the number:

  1. batch scaling (256..4096): amortize fixed overheads, give XLA bigger
     GEMM tiles per conv, and raise arithmetic intensity (the weight
     stream amortizes over more images — the roofline cap itself grows
     with batch);
  2. input-channel padding 3->8 on conv1 (zero-padded kernel rows are
     mathematically inert): whether the degenerate cin=3 contraction is
     what starves the first conv;
  3. conv-segment-only timing, to locate the time between the conv pair
     and the fc pair.

Each exact variant asserts numerical parity with the baseline forward
before its number is accepted. The chip sits behind a tunnel whose sync
jitter reaches tens of ms, so rep counts here are large (the slope
method's two points must be separated by >> the jitter).

Usage: python benchmarks/cifar_mfu_probe.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import cifar
from dnn_tpu.utils.flops import cifar_forward_flops, mfu
from dnn_tpu.utils.timing import device_time


def _emit(**row):
    print(json.dumps(row), flush=True)


def _ips(fn, *args, batch):
    dt = device_time(fn, *args, n1=100, n2=400, trials=5)
    return batch / dt


def main():
    params = cifar.init(jax.random.PRNGKey(0))
    base_fn = jax.jit(cifar.make_apply(compute_dtype=jnp.bfloat16))
    flops1 = cifar_forward_flops(1)

    # -- 1. batch scaling ---------------------------------------------------
    for batch in (256, 1024, 2048, 4096):
        x = cifar.example_input(batch_size=batch)
        ips = _ips(base_fn, params, x, batch=batch)
        _emit(variant=f"baseline_b{batch}", images_per_sec=round(ips, 1),
              mfu=round(mfu(flops1, ips) or 0, 4))

    batch = 1024
    x = cifar.example_input(batch_size=batch)
    ref = np.asarray(base_fn(params, x))

    # -- 2. conv1 input channels padded 3 -> 8 ------------------------------
    # zero-pad the image's channel axis and conv1's kernel input axis; the
    # extra contraction terms are 0*w = 0, so outputs are bit-identical.
    pad_params = dict(params)
    pad_params["conv1"] = {
        "kernel": jnp.pad(params["conv1"]["kernel"],
                          ((0, 0), (0, 0), (0, 5), (0, 0))),
        "bias": params["conv1"]["bias"],
    }

    @jax.jit
    def padded_fn(p, xx):
        xx = jnp.pad(xx, ((0, 0), (0, 0), (0, 0), (0, 5)))
        return cifar.make_apply(compute_dtype=jnp.bfloat16)(p, xx)

    np.testing.assert_allclose(np.asarray(padded_fn(pad_params, x)), ref,
                               atol=2e-2, rtol=2e-2)
    ips = _ips(padded_fn, pad_params, x, batch=batch)
    _emit(variant=f"cin_pad8_b{batch}", images_per_sec=round(ips, 1),
          mfu=round(mfu(flops1, ips) or 0, 4))

    # -- 3. segment split: convs only vs fcs only ---------------------------
    @jax.jit
    def convs_fn(p, xx):
        xx = xx.astype(jnp.bfloat16)
        h = cifar._seg_conv1(p, xx, compute_dtype=jnp.bfloat16)
        return cifar._seg_conv2(p, h, compute_dtype=jnp.bfloat16)

    flat = np.asarray(convs_fn(params, x))

    @jax.jit
    def fcs_fn(p, hh):
        h2 = cifar._seg_fc1(p, hh, compute_dtype=jnp.bfloat16)
        return cifar._seg_fc2(p, h2, compute_dtype=jnp.bfloat16)

    hh = jnp.asarray(flat)
    ips_c = _ips(convs_fn, params, x, batch=batch)
    ips_f = _ips(fcs_fn, params, hh, batch=batch)
    _emit(variant=f"convs_only_b{batch}", images_per_sec=round(ips_c, 1),
          share_of_forward_pct=round(100 * (batch / ips_c)
                                     / (batch / ips_c + batch / ips_f), 1))
    _emit(variant=f"fcs_only_b{batch}", images_per_sec=round(ips_f, 1),
          share_of_forward_pct=round(100 * (batch / ips_f)
                                     / (batch / ips_c + batch / ips_f), 1))


if __name__ == "__main__":
    main()
