"""CIFAR CNN MFU experiments — close (or explain) the gap to the roofline cap.

The measured forward sits well below the model's own roofline cap
(benchmarks/RESULTS.md row 1; BASELINE.md's arithmetic-intensity argument
puts the cap around 22% MFU at B=256 — the CNN streams too many
activation bytes per FLOP for the MXU to stay busy). This probe times
controlled variants to find which structural lever moves the number:

  1. batch scaling (256..4096): amortize fixed overheads, give XLA bigger
     GEMM tiles per conv, and raise arithmetic intensity (the weight
     stream amortizes over more images — the roofline cap itself grows
     with batch);
  2. an UNPADDED conv1 control (the model now zero-pads input channels
     3->8 on TPU by default — the lever this probe discovered; the
     control keeps the degenerate cin=3 contraction measurable);
  3. conv-segment-only timing, to locate the time between the conv pair
     and the fc pair.

Each exact variant asserts numerical parity with the baseline forward
before its number is accepted. The chip sits behind a tunnel whose sync
jitter reaches tens of ms, so rep counts here are large (the slope
method's two points must be separated by >> the jitter).

Usage: python benchmarks/cifar_mfu_probe.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import cifar
from dnn_tpu.utils.flops import cifar_forward_flops, mfu
from dnn_tpu.utils.timing import device_time


def _emit(**row):
    print(json.dumps(row), flush=True)


def _ips(fn, *args, batch):
    dt = device_time(fn, *args, n1=100, n2=400, trials=5)
    return batch / dt


def main():
    params = cifar.init(jax.random.PRNGKey(0))
    base_fn = jax.jit(cifar.make_apply(compute_dtype=jnp.bfloat16))
    flops1 = cifar_forward_flops(1)

    # -- 1. batch scaling ---------------------------------------------------
    for batch in (256, 1024, 2048, 4096):
        x = cifar.example_input(batch_size=batch)
        ips = _ips(base_fn, params, x, batch=batch)
        _emit(variant=f"baseline_b{batch}", images_per_sec=round(ips, 1),
              mfu=round(mfu(flops1, ips) or 0, 4))

    batch = 1024
    x = cifar.example_input(batch_size=batch)
    ref = np.asarray(base_fn(params, x))

    # -- 2. UNPADDED control --------------------------------------------
    # cifar._seg_conv1 now pads cin 3->8 on TPU by default (the lever this
    # probe originally discovered: 19.7% -> 39.1% MFU at B=1024). The
    # baseline above therefore already runs padded; this control runs the
    # ORIGINAL unpadded conv1 so the lever stays measurable — expect the
    # control to be ~2x SLOWER than the baseline on a v5e.
    from dnn_tpu.ops.nn import conv2d, max_pool2d, relu

    @jax.jit
    def nopad_fn(p, xx):
        xx = xx.astype(jnp.bfloat16)
        h = max_pool2d(relu(conv2d(p["conv1"], xx,
                                   compute_dtype=jnp.bfloat16)))
        h = cifar._seg_conv2(p, h, compute_dtype=jnp.bfloat16)
        h = cifar._seg_fc1(p, h, compute_dtype=jnp.bfloat16)
        return cifar._seg_fc2(p, h, compute_dtype=jnp.bfloat16)

    np.testing.assert_allclose(np.asarray(nopad_fn(params, x)), ref,
                               atol=2e-2, rtol=2e-2)
    ips = _ips(nopad_fn, params, x, batch=batch)
    _emit(variant=f"cin_nopad_control_b{batch}",
          images_per_sec=round(ips, 1),
          mfu=round(mfu(flops1, ips) or 0, 4))

    # -- 3. segment split: convs only vs fcs only ---------------------------
    @jax.jit
    def convs_fn(p, xx):
        xx = xx.astype(jnp.bfloat16)
        h = cifar._seg_conv1(p, xx, compute_dtype=jnp.bfloat16)
        return cifar._seg_conv2(p, h, compute_dtype=jnp.bfloat16)

    flat = np.asarray(convs_fn(params, x))

    @jax.jit
    def fcs_fn(p, hh):
        h2 = cifar._seg_fc1(p, hh, compute_dtype=jnp.bfloat16)
        return cifar._seg_fc2(p, h2, compute_dtype=jnp.bfloat16)

    hh = jnp.asarray(flat)
    ips_c = _ips(convs_fn, params, x, batch=batch)
    ips_f = _ips(fcs_fn, params, hh, batch=batch)
    _emit(variant=f"convs_only_b{batch}", images_per_sec=round(ips_c, 1),
          share_of_forward_pct=round(100 * (batch / ips_c)
                                     / (batch / ips_c + batch / ips_f), 1))
    _emit(variant=f"fcs_only_b{batch}", images_per_sec=round(ips_f, 1),
          share_of_forward_pct=round(100 * (batch / ips_f)
                                     / (batch / ips_c + batch / ips_f), 1))


if __name__ == "__main__":
    main()
