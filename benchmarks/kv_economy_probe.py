"""KV-economy probe: the miss-ratio curve's self-validation (ISSUE 18).

kvlens (dnn_tpu/obs/kvlens.py) claims its sampled reuse-distance curve
PREDICTS the block-hit ratio the radix KV tier would measure at pool
sizes nobody has run. A prediction instrument that is never checked
against ground truth is a dashboard decoration, so this probe closes
the loop on a real in-process batcher:

  1. Replay the PR 13 multi-turn-chat arrival schedule
     (workloads.arrivals.poisson_arrivals, seed=15, name
     "kvtier:chat" — the same deterministic order kv_tier_probe
     drives) over N_TENANTS tenants with Zipf-skewed tenant choice
     (arrivals.uniform, inverse-CDF — zero wall-clock randomness).
     Each tenant owns BLOCKS_PER_TENANT blocks of shared prefix; the
     working set is WORKING_SET_X times the configured pool, so the
     store evicts continuously at capacity A.
  2. At pool capacity A (CAP_A blocks) record what the lens's curve
     PREDICTS for capacity B = CAP_A // 2 — the 0.5x multiplier, a
     pool size this process has never run.
  3. Rebuild the batcher at capacity B, replay the IDENTICAL trace,
     and read the lens's exact per-block measured hit ratio (counted
     from the real store's lookup results, not from the sample).
  4. Assert |predicted − measured| <= MRC_ERROR_CEIL (0.10 absolute
     hit-ratio — benchmarks/ledger.py imports the constant for the
     `mrc_prediction_error` ratchet), and that the pressured run's
     thrash detector billed a non-zero evict→refetch tax (the forensic
     leg: re-prefill chunk-seconds with a live EMA price).

Workload-shape note (learned the hard way): a CYCLIC working set is
LRU's adversarial case — pure-LRU stack distance predicts 0 hits at
1x while the real leaf-LRU store (with parking) measures ~0.19 — so
the probe uses the skewed tenant-reuse shape real chat traffic has
(Zipf s=1.1 over tenants). The curve's contract is "predicts the
store's behaviour on serving-shaped traffic", not "models every
adversarial reference string"; STUDIES §22 records both numbers.

The prediction run and the measurement run share every seed, so the
whole probe is bit-deterministic on a host: once green, green.

Standalone:  python benchmarks/kv_economy_probe.py [--assert]
Suite row:   benchmarks/run_all.py config `kv_economy`
             (cpu-runnable).
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: asserted ceiling on |predicted − measured| block-hit ratio at the
#: untested pool size (absolute). Measured ~0.01-0.05 on this host;
#: 0.10 is the issue's contracted tolerance — a curve that drifts a
#: full decile from ground truth is mis-sizing pools. ledger.py reads
#: this constant for the `mrc_prediction_error` ratchet.
MRC_ERROR_CEIL = 0.10

CAP_A = 32            # pool capacity A (blocks) — the observed run
CAP_B = CAP_A // 2    # prediction target: the curve's 0.5x point
N_TENANTS = 96        # x1 block each = 96 distinct blocks...
BLOCKS_PER_TENANT = 1  # single-block prefixes: on 1-block chains the
# trie's leaf-LRU IS flat LRU, the reuse-distance model's policy —
# with deeper chains leaf-first eviction protects popular inner
# blocks and the real store BEATS the LRU curve (STUDIES §22 records
# the 2-block gap: the curve is then a conservative lower bound)
WORKING_SET_X = (N_TENANTS * BLOCKS_PER_TENANT) / CAP_A  # ...= 3.0x A
ZIPF_S = 1.1          # tenant-popularity skew (chat-shaped reuse)
CHAT_RATE_HZ = 60.0   # arrival schedule: ~300 turns over 5 s of the
CHAT_DUR_S = 5.0      # PR 13 chat process (replayed back-to-back —
# the probe needs the deterministic ORDER and COUNT, not the pacing)
BLOCK_LEN = 16
SEED = 15             # the kv_tier_probe chat seed


def _tenant_sequence(n: int):
    """Zipf(s)-skewed tenant id per arrival via inverse CDF over
    arrivals.uniform — deterministic, seed-pinned, no numpy RNG."""
    from dnn_tpu.workloads.arrivals import uniform

    w = [1.0 / (k + 1) ** ZIPF_S for k in range(N_TENANTS)]
    tot = sum(w)
    cdf, acc = [], 0.0
    for x in w:
        acc += x
        cdf.append(acc / tot)
    out = []
    for i in range(n):
        u = uniform(SEED, "kv_economy:tenant", i)
        t = 0
        while t < N_TENANTS - 1 and u > cdf[t]:
            t += 1
        out.append(t)
    return out


def _prompt(tenant: int):
    """BLOCKS_PER_TENANT blocks of tenant-owned tokens. 37 is coprime
    to 510, so no two tenants share even their first block."""
    import numpy as np

    n = BLOCKS_PER_TENANT * BLOCK_LEN
    return (np.arange(n) + 37 * tenant) % 510 + 1


def _replay(prefix_cache: int, tenants):
    """Build a paged batcher with `prefix_cache` store blocks, run the
    whole tenant sequence through submit→drain→claim (each turn's
    prefill really inserts / evicts in the radix store), and return
    the attached lens."""
    import jax

    from dnn_tpu import obs
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    was = obs.enabled()
    obs.set_enabled(True)  # the lens attaches at construction
    try:
        # explicit paged_blocks: prefix_cache + live-request headroom
        # (slots x max_len/block_len + the reserved null block), so
        # the STORE CAP is the binding constraint — the auto-sized
        # pool (17 blocks here) would bound residency below either
        # capacity under test and make A and B measure identically
        pool = prefix_cache + 4 * (cfg.block_size // BLOCK_LEN) + 1
        srv = ContinuousBatcher(cfg, prepared, slots=4,
                                max_len=cfg.block_size, prompt_pad=16,
                                kv="paged", block_len=BLOCK_LEN,
                                paged_blocks=pool,
                                prefix_cache=prefix_cache)
        lens = srv._kvlens
        assert lens is not None, "kvlens did not attach"
        for t in tenants:
            rid = srv.submit(_prompt(t), 1)
            srv.drain()
            srv.claim(rid)
        return lens
    finally:
        obs.set_enabled(was)


def measure() -> dict:
    from dnn_tpu.workloads.arrivals import poisson_arrivals

    arrivals = poisson_arrivals(CHAT_RATE_HZ, CHAT_DUR_S, seed=SEED,
                                name="kvtier:chat")
    tenants = _tenant_sequence(len(arrivals))

    # ---- run at capacity A: record the curve's 0.5x prediction -----
    lens_a = _replay(CAP_A, tenants)
    predicted_b = lens_a.predicted_hit_ratio(0.5)
    curve_a = lens_a.curve()
    # self-consistency receipt (reported, not the asserted leg): the
    # 1x point predicts the run it was sampled FROM
    self_err = abs(lens_a.predicted_hit_ratio(1.0)
                   - lens_a.measured_hit_ratio())

    # ---- re-run at capacity B: ground truth for the prediction -----
    lens_b = _replay(CAP_B, tenants)
    measured_b = lens_b.measured_hit_ratio()
    thrash_b = lens_b.thrash()

    err = abs(predicted_b - measured_b)
    return {
        "mrc_prediction_error": round(err, 4),
        "predicted_hit_ratio_at_B": round(predicted_b, 4),
        "measured_hit_ratio_at_B": round(measured_b, 4),
        "cap_A_blocks": CAP_A, "cap_B_blocks": CAP_B,
        "working_set_blocks": N_TENANTS * BLOCKS_PER_TENANT,
        "working_set_x": round(WORKING_SET_X, 2),
        "turns": len(tenants),
        "curve_at_A": {c["mult"]: c["predicted_hit_ratio"]
                       for c in curve_a},
        "measured_hit_ratio_at_A": round(lens_a.measured_hit_ratio(), 4),
        "self_consistency_err_at_A": round(self_err, 4),
        "sampled_at_A": lens_a.sampled,
        "sample_rate": lens_a.rate,
        # the forensic leg: the pressured pool's evict→refetch bill
        "thrash_refetch_blocks_at_B": thrash_b["refetch_blocks"],
        "thrash_chunk_seconds_at_B": round(thrash_b["chunk_seconds"], 4),
        "evictions_by_cause_at_B": dict(lens_b.evictions_by_cause),
        "ok": bool(err <= MRC_ERROR_CEIL
                   and thrash_b["refetch_blocks"] > 0),
    }


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure()
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print(f"FAIL: mrc_prediction_error "
              f"{row['mrc_prediction_error']} > {MRC_ERROR_CEIL} "
              f"(predicted {row['predicted_hit_ratio_at_B']} vs "
              f"measured {row['measured_hit_ratio_at_B']} at "
              f"{CAP_B} blocks) or zero thrash refetches",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
