"""Train-goodput probe: the asserted trainlens baseline (ISSUE 19).

The instrument-first pattern again (PR 10's step_timeline, PR 18's
kv_economy): trainlens ships BEFORE the training-at-scale PR it will
judge, so its numbers must already be trustworthy — this probe pins
them against ground truth a benchmark can hold:

  * **coverage** (ASSERTED): a real `train.fit` run on the pinned
    gpt-mini shape, phase-attributed by a TrainClock; the per-step
    phase accounting must cover >= COVERAGE_FLOOR of the externally
    measured fit() wall (no unattributed dark time) — the same 95%
    contract step_timeline holds on the serving side.

  * **mfu floor** (ASSERTED): step-time MFU priced by
    utils/flops.gpt_train_step_flops against an explicitly PINNED
    roofline (PINNED_PEAK_FLOPS — CPU has no table entry, and an
    asserted floor against an env-dependent denominator would be
    noise). The floor is deliberately conservative (1e-3 at a 1e12
    roofline tolerates ~190 ms/step on a ~2e8-FLOP step): it catches
    a broken pipeline (rate reading 0, flops mispriced by orders of
    magnitude), not host speed.

  * **stall attribution** (ASSERTED): the chaos `train_fault` sleep
    vector — a known injected input-pipeline stall (count x delay_s,
    landed inside fit's data window by the seam) must come back out
    as `data_stall_fraction` within STALL_TOLERANCE of the
    ground-truth sleep/wall ratio.

  * **sentinel latency** (ASSERTED): the chaos nan vector on a FLOAT
    toy model (token batches are int — NaN cannot ride them, which is
    itself the poison_batch contract) — the GradSentinel must fire
    `loss_nan` within SENTINEL_MAX_STEPS of the poisoned step, and
    the event must be present in the DUMPED flight ring (the /debugz
    jsonl a post-mortem actually reads).

  * **overhead** (ASSERTED): trainlens-live obs tax on the training
    step, ABBA-paired per iteration (the obs_overhead_probe
    estimator: gate ON,OFF,OFF,ON..., median per-pair difference over
    the median OFF wall) — clock + sentinel + flight, all inside the
    measured iteration, must stay under OVERHEAD_BUDGET.

Standalone:  python benchmarks/train_goodput_probe.py [--assert]
Suite row:   benchmarks/run_all.py config `train_goodput`
             (cpu-runnable). Ledger ratchets: train_mfu_floor,
             train_phase_coverage, trainlens_overhead_budget.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: asserted floor: attributed phase seconds / external fit() wall.
#: Measured ~99% on this host (fit's only uncovered time is loop entry
#: + the inter-iteration residue); 95% = the step_timeline contract.
COVERAGE_FLOOR = 0.95

#: asserted MFU floor at the PINNED roofline below. The gpt-mini step
#: costs ~1.3e9 FLOPs (3x forward, B=8, T=32, 4L/128d), so the floor
#: trips only when a step takes > ~1.3 s — a broken rate/pricing
#: pipeline, not a slow host. Measured ~0.04-0.06 here.
MFU_FLOOR = 1e-3

#: the explicit MFU denominator (utils/flops has no CPU table entry on
#: purpose — an asserted floor needs a pinned denominator, not an
#: env-dependent one)
PINNED_PEAK_FLOPS = 1e12

#: |measured data_stall_fraction − injected sleep/wall| ceiling
STALL_TOLERANCE = 0.10

#: loss_nan must fire within this many steps of the poisoned step
SENTINEL_MAX_STEPS = 2

#: trainlens-live obs tax budget (the ISSUE 3 contract, extended to
#: the training loop)
OVERHEAD_BUDGET = 0.02

BATCH = 8
SEQ = 32          # forward length; token batches carry SEQ+1 tokens
FIT_STEPS = 48
STALL_STEPS = 16
STALL_SLEEPS = 8
STALL_DELAY_S = 0.05
NAN_AT = 5        # poisoned iteration (0-indexed chaos counter)
OVERHEAD_PAIRS = 250


def _abba_on(i: int) -> bool:
    """obs_overhead_probe's gate schedule: ON,OFF,OFF,ON,ON,OFF,... —
    every adjacent pair holds one ON and one OFF in alternating order,
    so paired differencing cancels drift in both directions."""
    return i % 4 in (0, 3)


def _paired_overhead(seq):
    """[(on, wall_s), ...] ABBA-ordered -> (overhead_frac, med_on,
    med_off): median per-pair (on − off) over the median off wall."""
    on_t = sorted(dt for on, dt in seq if on)
    off_t = sorted(dt for on, dt in seq if not on)
    diffs = []
    for k in range(0, len(seq) - 1, 2):
        (a_on, a), (_b_on, b) = seq[k], seq[k + 1]
        diffs.append((a - b) if a_on else (b - a))
    diffs.sort()
    med_diff = diffs[len(diffs) // 2]
    med_off = off_t[len(off_t) // 2]
    return med_diff / med_off, on_t[len(on_t) // 2], med_off


def _gpt_mini():
    """The pinned probe shape + its jitted (state, batch) step, wrapped
    to fit()'s signature with the grad_stats leg live."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu.models import gpt
    from dnn_tpu.train import cross_entropy, make_train_step

    # 4L/128d: a ~1.3e9-FLOP (~25 ms on this host) step. Deliberately
    # NOT smaller: the sentinel's one readback/step costs a fixed
    # ~100 us (first host read of the fresh loss + stats buffers), so
    # a toy few-ms step would spend the <2% budget on buffer-read
    # constants rather than measuring the instrumentation.
    cfg = gpt.GPTConfig(block_size=64, vocab_size=256, n_layer=4,
                        n_head=4, n_embd=128)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    apply_fn = gpt.make_apply_stacked(cfg)

    def loss_fn(p, tokens):
        return cross_entropy(apply_fn(p, tokens[:, :-1]), tokens[:, 1:])

    opt = optax.adamw(1e-4)
    raw = make_train_step(loss_fn, opt, grad_stats=True)

    def step_fn(state, batch):
        p, s = state
        p, s, loss, stats = raw(p, s, batch)
        return (p, s), loss, stats

    tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ + 1),
                                0, cfg.vocab_size, dtype=jnp.int32)
    state = (prepared, opt.init(prepared))
    return cfg, step_fn, state, tokens


def _batches(tokens):
    while True:
        yield tokens


def _fit_leg() -> dict:
    """Coverage + MFU on a real fit() run (warmed: the compile lands
    before the clock starts)."""
    import jax

    from dnn_tpu.obs.trainlens import TrainClock
    from dnn_tpu.train import fit
    from dnn_tpu.utils.flops import gpt_train_step_flops

    cfg, step_fn, state, tokens = _gpt_mini()
    state = jax.block_until_ready(step_fn(state, tokens)[0])  # warm
    fps = gpt_train_step_flops(cfg, BATCH, SEQ)
    clock = TrainClock(capacity=FIT_STEPS + 8, flops_per_step=fps,
                       tokens_per_step=BATCH * SEQ,
                       peak_flops=PINNED_PEAK_FLOPS).install()
    t0 = time.perf_counter()
    fit(step_fn, state, _batches(tokens), num_steps=FIT_STEPS,
        clock=clock)
    wall = time.perf_counter() - t0
    recs = clock.records()
    attributed = sum(r["wall"] for r in recs)
    s = clock.summary()
    # hand MFU from the records themselves (rate over first-begin ->
    # last-end, the same span the ring rate converges to): the clock's
    # published number must agree with arithmetic a reviewer can redo
    span = (recs[-1]["t0"] + recs[-1]["wall"]) - recs[0]["t0"]
    hand_mfu = fps * (len(recs) / span) / PINNED_PEAK_FLOPS
    return {
        "steps": len(recs),
        "wall_s": round(wall, 4),
        "coverage": round(attributed / wall, 4),
        "mfu": s["mfu"],
        "hand_mfu": round(hand_mfu, 6),
        "flops_per_step": fps,
        "tokens_per_sec": s["tokens_per_sec"],
        "data_stall_baseline": s["data_stall_fraction"],
        "step_ms": round(attributed / len(recs) * 1e3, 3),
    }


def _stall_leg() -> dict:
    """Injected-sleep attribution: STALL_SLEEPS x STALL_DELAY_S of
    chaos sleep must come back as data_stall_fraction within
    STALL_TOLERANCE of ground truth."""
    import jax

    from dnn_tpu.chaos import inject as chaos
    from dnn_tpu.obs.trainlens import TrainClock
    from dnn_tpu.train import fit

    _cfg, step_fn, state, tokens = _gpt_mini()
    state = jax.block_until_ready(step_fn(state, tokens)[0])
    clock = TrainClock(capacity=STALL_STEPS + 8).install()
    chaos.install({"seed": 0, "faults": [
        {"kind": "train_fault", "target": "sleep", "at_n": 0,
         "count": STALL_SLEEPS, "delay_s": STALL_DELAY_S}]})
    try:
        fit(step_fn, state, _batches(tokens), num_steps=STALL_STEPS,
            clock=clock)
    finally:
        chaos.uninstall()
    s = clock.summary()
    expected = STALL_SLEEPS * STALL_DELAY_S / s["window_wall_s"]
    return {
        "injected_sleep_s": STALL_SLEEPS * STALL_DELAY_S,
        "window_wall_s": s["window_wall_s"],
        "data_stall_fraction": s["data_stall_fraction"],
        "expected_stall_fraction": round(expected, 4),
        "stall_error": round(abs(s["data_stall_fraction"] - expected),
                             4),
    }


def _sentinel_leg(tmpdir: str) -> dict:
    """Injected-NaN detection on a FLOAT toy model: the chaos nan
    vector poisons iteration NAN_AT's batch, the sentinel must fire
    loss_nan within SENTINEL_MAX_STEPS, and the event must be present
    in the DUMPED flight ring."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu.chaos import inject as chaos
    from dnn_tpu.obs import flight
    from dnn_tpu.obs.trainlens import GradSentinel
    from dnn_tpu.train import fit, make_train_step

    k = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(k, (16,)), "b": jnp.zeros(())}
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = x @ jax.random.normal(jax.random.PRNGKey(2), (16,))

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.sgd(1e-2)
    raw = make_train_step(loss_fn, opt, grad_stats=True)

    def step_fn(state, batch):
        p, s = state
        p, s, loss, stats = raw(p, s, batch)
        return (p, s), loss, stats

    sentinel = GradSentinel(warmup=2, bundle_dir=os.path.join(
        tmpdir, "incident"))
    chaos.install({"seed": 0, "faults": [
        {"kind": "train_fault", "target": "nan", "at_n": NAN_AT,
         "count": 1}]})
    try:
        fit(step_fn, (params, opt.init(params)),
            _batches({"x": x, "y": y}), num_steps=NAN_AT + 4,
            clock=None, sentinel=sentinel)
    finally:
        chaos.uninstall()
    evs = flight.recorder().events(kind="loss_nan")
    fired_step = evs[-1]["step"] if evs else None
    # the dumped ring — what an operator actually reads post-mortem
    dump = os.path.join(tmpdir, "ring.jsonl")
    flight.recorder().dump(dump)
    with open(dump) as f:
        dumped_kinds = [json.loads(ln)["kind"] for ln in f if ln.strip()]
    # chaos fires at 0-indexed iteration NAN_AT == fit step NAN_AT+1
    latency = None if fired_step is None else fired_step - (NAN_AT + 1)
    return {
        "poisoned_step": NAN_AT + 1,
        "loss_nan_step": fired_step,
        "sentinel_latency_steps": latency,
        "loss_nan_in_dumped_ring": "loss_nan" in dumped_kinds,
        "bundle_written": os.path.isdir(
            os.path.join(tmpdir, "incident")),
    }


def _overhead_leg() -> dict:
    """trainlens-live obs tax, ABBA-paired: each sample is one full
    fit-shaped iteration (begin/marks/end + sentinel.observe + the
    periodic registry flush) with the gate ON vs OFF."""
    import jax

    from dnn_tpu import obs
    from dnn_tpu.obs.trainlens import GradSentinel, TrainClock

    _cfg, step_fn, state, tokens = _gpt_mini()
    state = jax.block_until_ready(step_fn(state, tokens)[0])
    clock = TrainClock(capacity=256).install()
    sentinel = GradSentinel(warmup=2)
    it = _batches(tokens)
    was = obs.enabled()
    seq = []
    step = 0
    try:
        for i in range(2 * OVERHEAD_PAIRS):
            on = _abba_on(i)
            obs.set_enabled(on)
            t0 = time.perf_counter()
            rec = clock.begin()
            batch = next(it)
            if rec is not None:
                clock.mark(rec, "data")
            state, loss, stats = step_fn(state, batch)
            if rec is not None:
                clock.mark(rec, "dispatch")
            loss, stats = jax.block_until_ready((loss, stats))
            if rec is not None:
                clock.mark(rec, "wait")
                clock.mark(rec, "ckpt")
                clock.mark(rec, "eval")
            step += 1
            sentinel.observe(step, loss, stats)
            if rec is not None:
                clock.end(rec)
            seq.append((on, time.perf_counter() - t0))
    finally:
        obs.set_enabled(was)
    overhead, med_on, med_off = _paired_overhead(seq)
    return {
        "overhead_frac": round(overhead, 5),
        "step_ms_on": round(med_on * 1e3, 4),
        "step_ms_off": round(med_off * 1e3, 4),
        "pairs": OVERHEAD_PAIRS,
    }


def measure() -> dict:
    import shutil
    import tempfile

    from dnn_tpu import obs

    was = obs.enabled()
    obs.set_enabled(True)
    tmpdir = tempfile.mkdtemp(prefix="train-goodput-")
    try:
        fitl = _fit_leg()
        stall = _stall_leg()
        sent = _sentinel_leg(tmpdir)
        over = _overhead_leg()
    finally:
        obs.set_enabled(was)
        shutil.rmtree(tmpdir, ignore_errors=True)
    row = dict(fitl)
    row.update(stall)
    row.update(sent)
    row.update(over)
    row["overhead_pct"] = round(over["overhead_frac"] * 100, 2)
    row["coverage_floor"] = COVERAGE_FLOOR
    row["mfu_floor"] = MFU_FLOOR
    row["pinned_peak_flops"] = PINNED_PEAK_FLOPS
    row["ok_coverage"] = bool(fitl["coverage"] >= COVERAGE_FLOOR)
    row["ok_mfu"] = bool(fitl["mfu"] is not None
                         and fitl["mfu"] >= MFU_FLOOR)
    row["ok_stall"] = bool(stall["stall_error"] <= STALL_TOLERANCE)
    row["ok_sentinel"] = bool(
        sent["sentinel_latency_steps"] is not None
        and 0 <= sent["sentinel_latency_steps"] <= SENTINEL_MAX_STEPS
        and sent["loss_nan_in_dumped_ring"])
    row["ok_overhead"] = bool(
        over["overhead_frac"] < OVERHEAD_BUDGET)
    row["ok"] = (row["ok_coverage"] and row["ok_mfu"] and row["ok_stall"]
                 and row["ok_sentinel"] and row["ok_overhead"])
    return row


def main(argv=None) -> int:
    args = set(argv if argv is not None else sys.argv[1:])
    row = measure()
    print(json.dumps(row), flush=True)
    if "--assert" in args and not row["ok"]:
        print("FAIL: " + ", ".join(
            k for k in ("ok_coverage", "ok_mfu", "ok_stall",
                        "ok_sentinel", "ok_overhead") if not row[k]),
            file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
