"""workload_<name>: the open-loop scenario suite as asserted bench rows.

One row per scenario in dnn_tpu/workloads/scenarios.py, each SLO
asserted IN-RUN: the row's `ok` is the verdict engine's judgment of
the recorded traffic against the scenario's own declared objectives
(obs/slo.py). The breach scenario inverts the assertion — it is green
only when it BREACHES and its incident bundle reconstructs, checked by
READING THE BUNDLE BACK off disk (manifest verdict, chaos events in
the dumped timeline, CLI render) — never from in-memory state.

`python -m benchmarks.workload_probe --scenario chat [--light]
[--assert]` prints one JSON row; `--all` runs every scenario. The
run_all `workload_<name>` rows ride `measure()`; `run_all.py
--scenarios chat,json_mode` filters a round to the suite.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _p95_ms(rep, name: str):
    for o in rep.objectives:
        if o["name"].startswith(name) and o["measured"] is not None:
            return round(o["measured"] * 1e3, 2)
    return None


def _verify_bundle(path: str) -> dict:
    """Read an incident bundle BACK off disk and judge it — the
    'reconstructable from the flight recorder' assertion. Checks:
    manifest says breach, the dumped timeline carries the injected
    faults that caused it, and the CLI's renderer produces the
    event-by-event view."""
    from dnn_tpu.obs.slo import load_incident, render_incident

    out = {"bundle": path, "reconstructed": False}
    try:
        bundle = load_incident(path)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        out["error"] = f"unreadable bundle: {e}"
        return out
    rep = bundle["manifest"]["report"]
    events = bundle["flight"]
    injected = [e for e in events if e.get("kind") == "chaos_inject"]
    rendered = render_incident(bundle)
    out.update({
        "manifest_verdict_breach": not rep["ok"],
        "flight_events": len(events),
        "chaos_events_in_bundle": len(injected),
        "render_lines": len(rendered.splitlines()),
        "reconstructed": bool(not rep["ok"] and events and injected
                              and "SLO BREACH" in rendered),
    })
    return out


def measure(name: str, *, light: bool = False, seed: int = 0) -> dict:
    """One scenario end to end -> one bench row (plain dict). `ok` is
    the in-run SLO assertion (inverted + bundle-verified for
    expect_breach scenarios)."""
    import jax

    from dnn_tpu.workloads import get_scenario, run_scenario

    sc = get_scenario(name, light=light)
    incident_dir = None
    if sc.expect_breach:
        incident_dir = os.path.join(
            tempfile.mkdtemp(prefix=f"workload_{name}_"), "bundle")
    t0 = time.perf_counter()
    res = run_scenario(sc, seed=seed, incident_dir=incident_dir)
    rep = res["report"]
    row = {
        "scenario": name, "light": bool(light), "seed": seed,
        "requests": rep.requests, "completed": rep.completed,
        "rejected": rep.rejected, "lost": rep.lost,
        "availability": round(rep.completed / rep.requests, 4)
        if rep.requests else 0.0,
        "goodput_tokens_per_sec": rep.goodput_tps,
        "ttft_p95_ms": _p95_ms(rep, "ttft"),
        "itl_p95_ms": _p95_ms(rep, "itl"),
        "slo": sc.slo.to_dict(),
        "slo_verdict": "ok" if rep.ok else "breach",
        "burn_rates": rep.burn_rates,
        "wall_s": res["wall_s"],
        "probe_wall_s": round(time.perf_counter() - t0, 1),
        "platform": jax.default_backend(),
    }
    row["round_substrate"] = row["platform"]
    row.update(res["extras"])
    if sc.expect_breach:
        row["expect_breach"] = True
        if rep.ok:
            row.update({"ok": False,
                        "note": "scenario was expected to breach but "
                                "the verdict came back ok — the chaos "
                                "injection did not bite"})
        else:
            v = _verify_bundle(res["bundle"] or "")
            row.update(v)
            row["ok"] = bool(v["reconstructed"])
    else:
        row["ok"] = bool(rep.ok)
    return row


def main(argv=None) -> int:
    import argparse

    from dnn_tpu.workloads.scenarios import SCENARIOS

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", default=None,
                    help="one scenario name "
                         f"({', '.join(sorted(SCENARIOS))})")
    ap.add_argument("--all", action="store_true",
                    help="run every registered scenario")
    ap.add_argument("--light", action="store_true",
                    help="shortened durations (smoke use; the "
                         "acceptance configuration is the full run)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--assert", dest="do_assert", action="store_true",
                    help="exit nonzero when any row's in-run SLO "
                         "assertion fails")
    args = ap.parse_args(argv)
    if not args.all and not args.scenario:
        ap.error("need --scenario NAME or --all")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    names = sorted(SCENARIOS) if args.all else [args.scenario]
    rc = 0
    for name in names:
        row = measure(name, light=args.light, seed=args.seed)
        print(json.dumps(row), flush=True)
        if args.do_assert and not row["ok"]:
            print(f"ASSERT FAILED: workload_{name} "
                  f"(verdict={row.get('slo_verdict')}, "
                  f"ok={row['ok']})", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
