"""One-off exploration: forward-throughput variants for the headline bench.

Times GPT-2 small (B=8, S=512, bf16) forward variants on the real chip to
find headroom beyond the current ~52% MFU:

  scan        — shipped path (make_apply_stacked, lax.scan over blocks)
  unroll{N}   — same but lax.scan unroll=N (cross-layer scheduling freedom)
  flash       — Pallas flash-attention kernel at S=512
  bf16head    — lm_head emits bf16 logits (halves the 823 MB f32 logit write)

Not part of the benchmark suite; results inform which variants graduate
into bench.py / the model factories.
"""

import functools

import jax
import jax.numpy as jnp

from dnn_tpu.models import gpt
from dnn_tpu.ops.nn import layer_norm, linear
from dnn_tpu.utils.flops import gpt_forward_flops, mfu
from dnn_tpu.utils.timing import device_time

BATCH, SEQ = 8, 512
BF16 = jnp.bfloat16


def main():
    cfg = gpt.PRESETS["gpt2"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, SEQ), 0, cfg.vocab_size, dtype=jnp.int32
    )

    def scan_unroll(unroll):
        def apply(prep, idx):
            x = gpt.embed(prep, idx, cfg=cfg).astype(BF16)

            def body(carry, layer_params):
                return gpt.block_apply(
                    layer_params, carry, cfg=cfg, compute_dtype=BF16
                ), None

            x, _ = jax.lax.scan(body, x, prep["blocks"], unroll=unroll)
            return gpt.head(prep, x.astype(jnp.float32), cfg=cfg, compute_dtype=BF16)

        return apply

    def bf16_head(prep, idx):
        x = gpt.embed(prep, idx, cfg=cfg).astype(BF16)

        def body(carry, layer_params):
            return gpt.block_apply(layer_params, carry, cfg=cfg, compute_dtype=BF16), None

        x, _ = jax.lax.scan(body, x, prep["blocks"])
        x = layer_norm(prep["ln_f"], x.astype(jnp.float32), eps=cfg.ln_eps)
        out = linear(prep["lm_head"], x, compute_dtype=BF16, accum_dtype=jnp.float32)
        return out.astype(BF16)

    # bf16-resident weights: inference holds no f32 master, so the per-layer
    # param read halves (496 MB f32 -> 248 MB bf16 per forward for gpt2)
    prepared_bf16 = jax.tree.map(
        lambda a: a.astype(BF16) if a.dtype == jnp.float32 else a, prepared
    )

    variants = {
        "scan": (jax.jit(gpt.make_apply_stacked(cfg, compute_dtype=BF16)), prepared),
        "unroll3": (jax.jit(scan_unroll(3)), prepared),
        "unroll12": (jax.jit(scan_unroll(12)), prepared),
        "flash": (jax.jit(gpt.make_apply_stacked(cfg, compute_dtype=BF16, use_flash=True)), prepared),
        "bf16head": (jax.jit(bf16_head), prepared),
        "bf16params": (
            jax.jit(gpt.make_apply_stacked(cfg, compute_dtype=BF16,
                                           logits_dtype=BF16)),
            prepared_bf16,
        ),
    }

    fpt = gpt_forward_flops(cfg, BATCH, SEQ) / (BATCH * SEQ)
    for name, (fn, prep) in variants.items():
        try:
            dt = device_time(fn, prep, ids)
        except Exception as e:  # a variant failing to compile is a finding, not a crash
            print(f"{name:10s} FAILED: {type(e).__name__}: {str(e)[:200]}")
            continue
        tps = BATCH * SEQ / dt
        m = mfu(fpt, tps)
        print(f"{name:10s} {tps:12.0f} tok/s   mfu={m if m is None else round(m, 4)}")


if __name__ == "__main__":
    main()
