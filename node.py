#!/usr/bin/env python
"""Drop-in entrypoint shim: `python node.py --node_id X --config Y
[--input_image Z]` — the reference framework's invocation (readme.md:82-95)
— forwards to the dnn_tpu CLI."""

import sys

from dnn_tpu.node import main

if __name__ == "__main__":
    sys.exit(main())
