"""Native (C++) runtime components, built on demand.

The reference is 100% Python (SURVEY §2 — no native layer exists to port),
but a full framework wants its host-side hot paths native. This package
compiles `codec.cpp` with the system g++ the first time it's imported
(cached as a .so next to the source, keyed by source mtime) and binds it
via ctypes — no pybind11 required. Every entry point has a pure-Python
fallback producing bit-identical results, so the framework degrades
gracefully on hosts without a toolchain.

API:
    crc32c(data: bytes|memoryview|ndarray, seed=0) -> int
    bf16_to_f32(ndarray[bfloat16|uint16]) -> ndarray[float32]
    f32_to_bf16(ndarray[float32]) -> ndarray[bfloat16]
    native_available() -> bool
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

log = logging.getLogger("dnn_tpu.native")

_SRC = os.path.join(os.path.dirname(__file__), "codec.cpp")
_LOADER_SRC = os.path.join(os.path.dirname(__file__), "loader.cpp")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOADER_LIB: Optional[ctypes.CDLL] = None
_LOADER_TRIED = False


def _build_src(src: str, stem: str, extra_flags=()) -> Optional[str]:
    """Compile (or locate the cached) .so for `src`; None means 'use the
    Python fallback'. ANY environment problem — missing source in a wheel
    install, read-only site-packages, missing g++ — must degrade, not
    raise."""
    tmp = None
    try:
        # key the cache on source mtime so edits rebuild automatically
        src_dir = os.path.dirname(src)
        tag = int(os.stat(src).st_mtime)
        so = os.path.join(src_dir, f"_{stem}_{tag}.so")
        if os.path.exists(so):
            return so
        # stale caches from earlier source versions
        for name in os.listdir(src_dir):
            if name.startswith(f"_{stem}_") and name.endswith(".so"):
                try:
                    os.unlink(os.path.join(src_dir, name))
                except OSError:
                    pass
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=src_dir)
        os.close(fd)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               *extra_flags, src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except (subprocess.SubprocessError, OSError) as e:
        log.info("native %s build unavailable (%s); using Python fallback",
                 stem, e)
        try:
            if tmp and os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass
        return None


def _build() -> Optional[str]:
    return _build_src(_SRC, "codec")


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = _build()
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log.info("native codec load failed (%s); using Python fallback", e)
        return None
    lib.dnn_crc32c.restype = ctypes.c_uint32
    lib.dnn_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.dnn_bf16_to_f32.restype = None
    lib.dnn_bf16_to_f32.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.dnn_f32_to_bf16.restype = None
    lib.dnn_f32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _lib() is not None


def loader_lib() -> Optional[ctypes.CDLL]:
    """The async-loader library (loader.cpp), or None -> Python fallback.
    Built separately from the codec (needs -pthread)."""
    global _LOADER_LIB, _LOADER_TRIED
    if _LOADER_TRIED:
        return _LOADER_LIB
    _LOADER_TRIED = True
    so = _build_src(_LOADER_SRC, "loader", extra_flags=("-pthread",))
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log.info("native loader load failed (%s); using Python fallback", e)
        return None
    lib.dnn_loader_create.restype = ctypes.c_void_p
    lib.dnn_loader_create.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.dnn_loader_next.restype = ctypes.c_int
    lib.dnn_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.dnn_loader_destroy.restype = None
    lib.dnn_loader_destroy.argtypes = [ctypes.c_void_p]
    _LOADER_LIB = lib
    return _LOADER_LIB


def loader_available() -> bool:
    return loader_lib() is not None


# ----------------------------------------------------------------------
# crc32c
# ----------------------------------------------------------------------

_PY_TABLE: Optional[list] = None


def _py_table():
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (poly ^ (c >> 1)) if (c & 1) else (c >> 1)
            table.append(c)
        _PY_TABLE = table
    return _PY_TABLE


def _as_buffer(data) -> memoryview:
    """-> a C-contiguous uint8 memoryview over `data` WITHOUT copying
    when the input is already contiguous (the comm hot path checksums
    MB-scale activation views — a bytes() materialization here would be
    a hidden full payload copy per direction, defeating the zero-copy
    wire codec). Only non-contiguous inputs materialize."""
    if isinstance(data, np.ndarray):
        a = data if data.flags.c_contiguous else np.ascontiguousarray(data)
        # uint8 reinterpret-view: also covers dtypes the buffer
        # protocol rejects (ml_dtypes bfloat16)
        return memoryview(a.reshape(-1).view(np.uint8))
    view = memoryview(data)
    if not view.c_contiguous:
        view = memoryview(bytes(view))
    return view.cast("B") if view.ndim else view.cast("B", (1,))


def crc32c(data, seed: int = 0) -> int:
    """CRC32C (Castagnoli) checksum. Native slice-by-8 when the compiled
    codec is available; table-driven Python otherwise (bit-identical)."""
    buf = _as_buffer(data)
    lib = _lib()
    if lib is not None:
        # pointer pass-through (ctypes won't convert a memoryview to
        # c_void_p itself; frombuffer is a zero-copy view)
        ptr = np.frombuffer(buf, np.uint8).ctypes.data if len(buf) else 0
        return int(lib.dnn_crc32c(ptr, len(buf), ctypes.c_uint32(seed)))
    table = _py_table()
    crc = (~seed) & 0xFFFFFFFF
    for b in buf:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return (~crc) & 0xFFFFFFFF


# ----------------------------------------------------------------------
# bf16 conversion
# ----------------------------------------------------------------------

def bf16_to_f32(arr: np.ndarray) -> np.ndarray:
    """bfloat16 (or its uint16 bit-pattern) -> float32, exact."""
    src = np.ascontiguousarray(arr)
    if src.dtype.name == "bfloat16":
        src = src.view(np.uint16)
    elif src.dtype != np.uint16:
        raise TypeError(f"expected bfloat16/uint16, got {arr.dtype}")
    out = np.empty(src.shape, np.float32)
    lib = _lib()
    if lib is not None and src.size:
        lib.dnn_bf16_to_f32(
            src.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            src.size,
        )
    else:
        out[...] = (src.astype(np.uint32) << 16).view(np.float32)
    return out


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    """float32 -> bfloat16 with round-to-nearest-even (XLA semantics)."""
    import ml_dtypes

    src = np.ascontiguousarray(arr, dtype=np.float32)
    lib = _lib()
    if lib is None or not src.size:
        return src.astype(ml_dtypes.bfloat16)
    out = np.empty(src.shape, np.uint16)
    lib.dnn_f32_to_bf16(
        src.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        src.size,
    )
    return out.view(ml_dtypes.bfloat16)
