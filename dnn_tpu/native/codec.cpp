// dnn_tpu native codec: payload integrity + dtype conversion kernels.
//
// The reference ships zero native code (SURVEY §2: "100% Python") and its
// wire format carries raw bytes with no integrity check
// (/root/reference/node_service.proto:26-30, node.py:45-48). This library
// supplies the native half of the rebuild's transport hardening: CRC32C
// (Castagnoli) at memory bandwidth via slice-by-8, plus bf16<->f32 block
// converters (round-to-nearest-even, the MXU's native rounding) used when
// staging checkpoint/activation buffers.
//
// Built on demand by dnn_tpu/native/__init__.py with the system g++; the
// Python side falls back to a table-driven implementation when no compiler
// is present, producing bit-identical results.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

uint32_t g_tables[8][256];

// Static-init at dlopen time: no lazy-init data race when the first
// dnn_crc32c calls arrive concurrently from several server threads.
struct TableInit {
    TableInit() {
        const uint32_t poly = 0x82f63b78u;  // CRC32C (Castagnoli), reflected
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k)
                c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
            g_tables[0][i] = c;
        }
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = g_tables[0][i];
            for (int t = 1; t < 8; ++t) {
                c = g_tables[0][c & 0xff] ^ (c >> 8);
                g_tables[t][i] = c;
            }
        }
    }
};
const TableInit g_table_init;

}  // namespace

extern "C" {

// CRC32C over `n` bytes, continuing from `seed` (pass 0 to start).
uint32_t dnn_crc32c(const uint8_t* data, size_t n, uint32_t seed) {
    uint32_t crc = ~seed;
    // align to 8 bytes
    while (n && (reinterpret_cast<uintptr_t>(data) & 7u)) {
        crc = g_tables[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
        --n;
    }
    while (n >= 8) {
        uint64_t w;
        std::memcpy(&w, data, 8);
        w ^= crc;  // little-endian host assumed (TPU hosts are x86/ARM LE)
        crc = g_tables[7][w & 0xff] ^
              g_tables[6][(w >> 8) & 0xff] ^
              g_tables[5][(w >> 16) & 0xff] ^
              g_tables[4][(w >> 24) & 0xff] ^
              g_tables[3][(w >> 32) & 0xff] ^
              g_tables[2][(w >> 40) & 0xff] ^
              g_tables[1][(w >> 48) & 0xff] ^
              g_tables[0][(w >> 56) & 0xff];
        data += 8;
        n -= 8;
    }
    while (n--) crc = g_tables[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    return ~crc;
}

// bf16 (as uint16) -> f32: exact (bf16 is a truncated f32).
void dnn_bf16_to_f32(const uint16_t* src, float* dst, size_t n) {
    for (size_t i = 0; i < n; ++i) {
        uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
        std::memcpy(&dst[i], &bits, 4);
    }
}

// f32 -> bf16 with round-to-nearest-even (matches XLA/ml_dtypes). NaNs are
// quieted to preserve NaN-ness through truncation.
void dnn_f32_to_bf16(const float* src, uint16_t* dst, size_t n) {
    for (size_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, &src[i], 4);
        if ((bits & 0x7fffffffu) > 0x7f800000u) {  // NaN
            dst[i] = static_cast<uint16_t>((bits >> 16) | 0x0040u);
            continue;
        }
        uint32_t lsb = (bits >> 16) & 1u;
        bits += 0x7fffu + lsb;
        dst[i] = static_cast<uint16_t>(bits >> 16);
    }
}

}  // extern "C"
