// Native async data loader: CIFAR-10 binary batches decoded + normalized on
// background threads into a bounded ring of ready batches.
//
// The reference has no input pipeline at all (its only input is one PIL
// image per request — /root/reference/node.py:142-154); the Python loader
// (dnn_tpu/data/cifar_binary.py) supplies the training path, and this
// component moves its hot loop (uint8 record -> CHW->HWC transpose ->
// float32 normalize) plus the file IO off the training thread, so host-side
// preprocessing overlaps TPU steps instead of serializing with them.
//
// Contracts mirrored from the Python loader, verified by
// tests/test_native_loader.py:
//   * record layout: [1 label byte | 3072 image bytes, RGB planes, 32x32]
//   * normalize EXACTLY as ((v / 255.0f) - 0.5f) / 0.5f (same op order as
//     cifar_binary.decode, so shuffle=off batches are bit-identical);
//   * shuffle=off yields the dataset in file order, epoch after epoch;
//   * shuffle=on uses splitmix64-seeded Fisher-Yates, deterministic per
//     (seed, epoch) — a different permutation sequence than numpy's
//     Generator (documented; coverage-per-epoch is the tested invariant).
//
// Plain C ABI for ctypes; no pybind11 (not in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

constexpr int kRecordBytes = 1 + 3 * 32 * 32;
constexpr int kImageFloats = 32 * 32 * 3;

struct Batch {
    std::vector<float> imgs;     // (B, 32, 32, 3) NHWC
    std::vector<int32_t> labels; // (B,)
};

uint64_t splitmix64(uint64_t& s) {
    s += 0x9E3779B97F4A7C15ULL;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

struct Loader {
    // Borrowed pointer into the caller's record buffer (the Python side
    // keeps its backing memmap alive until dnn_loader_destroy returns —
    // zero-copy: the dataset is NOT duplicated into C++ memory).
    const uint8_t* records = nullptr;  // n * kRecordBytes
    size_t n = 0;
    int batch = 0;
    uint64_t seed = 0;
    bool shuffle = true;
    size_t depth = 0;

    std::thread worker;
    std::mutex mu;
    std::condition_variable cv_push, cv_pop;
    std::queue<Batch> ready;
    std::atomic<bool> stop{false};

    void decode(const size_t* idx, Batch& out) const {
        out.imgs.resize(static_cast<size_t>(batch) * kImageFloats);
        out.labels.resize(batch);
        for (int b = 0; b < batch; ++b) {
            const uint8_t* rec = records + idx[b] * kRecordBytes;
            out.labels[b] = rec[0];
            const uint8_t* px = rec + 1;  // 3 planes of 32*32, R then G then B
            float* dst = out.imgs.data() + static_cast<size_t>(b) * kImageFloats;
            for (int hw = 0; hw < 32 * 32; ++hw) {
                for (int c = 0; c < 3; ++c) {
                    float v = static_cast<float>(px[c * 32 * 32 + hw]);
                    dst[hw * 3 + c] = ((v / 255.0f) - 0.5f) / 0.5f;
                }
            }
        }
    }

    void run() {
        std::vector<size_t> order(n);
        for (uint64_t epoch = 0; !stop.load(); ++epoch) {
            for (size_t i = 0; i < n; ++i) order[i] = i;
            if (shuffle) {
                uint64_t s = seed + 0x1000003U * epoch + 1;
                for (size_t i = n; i > 1; --i) {
                    size_t j = splitmix64(s) % i;
                    std::swap(order[i - 1], order[j]);
                }
            }
            size_t usable = n - (n % static_cast<size_t>(batch));
            for (size_t lo = 0; lo < usable && !stop.load(); lo += batch) {
                Batch out;
                decode(order.data() + lo, out);
                std::unique_lock<std::mutex> lk(mu);
                cv_push.wait(lk, [&] { return ready.size() < depth || stop.load(); });
                if (stop.load()) return;
                ready.push(std::move(out));
                cv_pop.notify_one();
            }
        }
    }
};

}  // namespace

extern "C" {

// Returns a handle, or 0 on any error (caller falls back to Python).
// `blob` is the concatenated record bytes (Python does the file IO — it
// memory-maps the files; the native side BORROWS the pointer, so the
// caller must keep the buffer alive until dnn_loader_destroy returns).
void* dnn_loader_create(const uint8_t* blob, uint64_t n_records, int batch,
                        uint64_t seed, int shuffle, uint64_t queue_depth) {
    if (!blob || n_records == 0 || batch <= 0 ||
        static_cast<uint64_t>(batch) > n_records || queue_depth == 0) {
        return nullptr;
    }
    auto* L = new (std::nothrow) Loader();
    if (!L) return nullptr;
    L->records = blob;
    L->n = n_records;
    L->batch = batch;
    L->seed = seed;
    L->shuffle = shuffle != 0;
    L->depth = queue_depth;
    try {
        L->worker = std::thread([L] { L->run(); });
    } catch (...) {
        delete L;
        return nullptr;
    }
    return L;
}

// Blocks until a batch is ready; copies into caller-owned buffers
// (imgs: batch*3072 floats, labels: batch int32). Returns 0 on success.
int dnn_loader_next(void* handle, float* imgs, int32_t* labels) {
    auto* L = static_cast<Loader*>(handle);
    if (!L || !imgs || !labels) return 1;
    Batch out;
    {
        std::unique_lock<std::mutex> lk(L->mu);
        L->cv_pop.wait(lk, [&] { return !L->ready.empty() || L->stop.load(); });
        if (L->ready.empty()) return 2;  // stopped
        out = std::move(L->ready.front());
        L->ready.pop();
        L->cv_push.notify_one();
    }
    std::memcpy(imgs, out.imgs.data(), out.imgs.size() * sizeof(float));
    std::memcpy(labels, out.labels.data(), out.labels.size() * sizeof(int32_t));
    return 0;
}

void dnn_loader_destroy(void* handle) {
    auto* L = static_cast<Loader*>(handle);
    if (!L) return;
    L->stop.store(true);
    {
        std::lock_guard<std::mutex> lk(L->mu);
        L->cv_push.notify_all();
        L->cv_pop.notify_all();
    }
    if (L->worker.joinable()) L->worker.join();
    delete L;
}

}  // extern "C"
