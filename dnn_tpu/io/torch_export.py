"""Export framework params as torch-layout `.pth` checkpoints.

The inverse of dnn_tpu/io/checkpoint.py's import path, closing the interop
loop with the reference: its nodes can only consume a torch-saved full-model
state dict (/root/reference/node.py:294-317, torch.load at :296), and the
mirror's own weights blob was stripped (.MISSING_LARGE_BLOBS:
cifar10_model.pth). A model trained HERE can therefore be handed BACK to an
unmodified reference node — re-supplying the missing blob with weights a
reference process accepts byte-for-byte.

`save_pth` writes the torch zipfile serialization format (torch >= 1.6)
with a hand-emitted pickle program — no torch import at save time, so a
TPU host without torch can still produce checkpoints torch users load.
The stream contains exactly the graph `torch.load` expects:

    {key: _rebuild_tensor_v2(pers_id(('storage', <T>Storage, key, 'cpu',
     numel)), offset, size, stride, requires_grad, OrderedDict())}

with each storage's raw little-endian bytes at `archive/data/<key>`.
Verified against both `torch.load` and this package's own torch-free
reader (tests/test_torch_export.py).
"""

from __future__ import annotations

import struct
import zipfile
from typing import Dict

import numpy as np

# numpy dtype -> torch storage class name (the GLOBAL the pickle references)
_STORAGE_NAMES = {
    np.dtype(np.float32): "FloatStorage",
    np.dtype(np.float64): "DoubleStorage",
    np.dtype(np.float16): "HalfStorage",
    np.dtype(np.int64): "LongStorage",
    np.dtype(np.int32): "IntStorage",
    np.dtype(np.int16): "ShortStorage",
    np.dtype(np.int8): "CharStorage",
    np.dtype(np.uint8): "ByteStorage",
    np.dtype(np.bool_): "BoolStorage",
}

# pickle protocol-2 opcodes (emitted by hand so no fake torch modules are
# ever registered and no torch import is needed for GLOBAL verification)
_PROTO = b"\x80\x02"
_MARK = b"("
_EMPTY_DICT = b"}"
_EMPTY_TUPLE = b")"
_SETITEMS = b"u"
_TUPLE = b"t"
_REDUCE = b"R"
_BINPERSID = b"Q"
_NEWFALSE = b"\x89"
_STOP = b"."


def _unicode(s: str) -> bytes:
    raw = s.encode("utf-8")
    return b"X" + struct.pack("<I", len(raw)) + raw  # BINUNICODE


def _int(n: int) -> bytes:
    if 0 <= n < 256:
        return b"K" + bytes([n])  # BININT1
    return b"J" + struct.pack("<i", n)  # BININT


def _global(module: str, name: str) -> bytes:
    return b"c" + module.encode() + b"\n" + name.encode() + b"\n"


def _tensor_pickle(key: str, arr: np.ndarray) -> bytes:
    """One _rebuild_tensor_v2(...) value for the state-dict pickle."""
    storage_name = _STORAGE_NAMES.get(arr.dtype)
    if storage_name is None and arr.dtype.name == "bfloat16":
        storage_name = "BFloat16Storage"
    if storage_name is None:
        raise ValueError(f"cannot export dtype {arr.dtype} to torch storage")

    # contiguous row-major strides in elements
    strides, acc = [], 1
    for dim in reversed(arr.shape):
        strides.append(acc)
        acc *= dim
    strides.reverse()

    out = [_global("torch._utils", "_rebuild_tensor_v2"), _MARK]
    # persistent id ('storage', Storage, key, 'cpu', numel) -> BINPERSID
    out += [_MARK, _unicode("storage"), _global("torch", storage_name),
            _unicode(key), _unicode("cpu"), _int(arr.size), _TUPLE, _BINPERSID]
    out.append(_int(0))  # storage_offset
    out += [_MARK, *[_int(d) for d in arr.shape], _TUPLE]       # size
    out += [_MARK, *[_int(s) for s in strides], _TUPLE]         # stride
    out.append(_NEWFALSE)                                       # requires_grad
    out += [_global("collections", "OrderedDict"), _EMPTY_TUPLE, _REDUCE]
    out += [_TUPLE, _REDUCE]
    return b"".join(out)


def save_pth(path: str, flat_state_dict: Dict[str, np.ndarray]):
    """Write {name: array} as a torch-zipfile checkpoint at `path`. Arrays
    are stored little-endian contiguous (the torch storage layout)."""
    entries = {}
    pkl = [_PROTO, _EMPTY_DICT, _MARK]
    for i, (name, arr) in enumerate(flat_state_dict.items()):
        arr = np.ascontiguousarray(np.asarray(arr))
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        key = str(i)
        entries[key] = arr.tobytes()
        pkl += [_unicode(name), _tensor_pickle(key, arr)]
    pkl += [_SETITEMS, _STOP]

    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("archive/data.pkl", b"".join(pkl))
        for key, raw in entries.items():
            zf.writestr(f"archive/data/{key}", raw)
        zf.writestr("archive/version", "3\n")
        zf.writestr("archive/byteorder", "little")  # no newline: torch
        # compares the record bytes verbatim against b"little"


# ----------------------------------------------------------------------
# TPU layout -> torch layout converters (inverses of io/checkpoint.py)
# ----------------------------------------------------------------------

def _np(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x))


def cifar_state_dict_from_params(params) -> Dict[str, np.ndarray]:
    """Framework CIFAR params (NHWC/HWIO, dnn_tpu/models/cifar.py) -> the
    reference CNN's torch state dict (conv1/conv2/fc1/fc2 .weight/.bias,
    NCHW/OIHW — /root/reference/cifar_model_parts.py:9-13). Exact inverse
    of cifar_params_from_torch_state_dict; fc1 transfers with only the
    (in, out) -> (out, in) transpose because the model flattens in the
    reference's (C, H, W) order at the boundary (cifar.py _seg_conv2)."""
    return {
        "conv1.weight": _np(params["conv1"]["kernel"]).transpose(3, 2, 0, 1),
        "conv1.bias": _np(params["conv1"]["bias"]),
        "conv2.weight": _np(params["conv2"]["kernel"]).transpose(3, 2, 0, 1),
        "conv2.bias": _np(params["conv2"]["bias"]),
        "fc1.weight": _np(params["fc1"]["kernel"]).T,
        "fc1.bias": _np(params["fc1"]["bias"]),
        "fc2.weight": _np(params["fc2"]["kernel"]).T,
        "fc2.bias": _np(params["fc2"]["bias"]),
    }


def gpt_state_dict_from_params(params, *, layout: str = "conv1d") -> Dict[str, np.ndarray]:
    """Framework GPT params -> an HF-GPT-2-style state dict.

    `layout="conv1d"` stores projection weights (in, out) as HF's Conv1D
    does (loadable by transformers' GPT2LMHeadModel); `layout="linear"`
    stores (out, in) nanoGPT-style. Inverse of gpt_params_from_state_dict.
    """
    if layout not in ("conv1d", "linear"):
        raise ValueError(f"layout must be conv1d|linear, got {layout}")
    w = _np if layout == "conv1d" else (lambda x: _np(x).T)

    sd = {
        "wte.weight": _np(params["wte"]["embedding"]),
        "wpe.weight": _np(params["wpe"]["embedding"]),
        "ln_f.weight": _np(params["ln_f"]["scale"]),
        "ln_f.bias": _np(params["ln_f"]["bias"]),
    }
    n_layer = sum(1 for k in params if k.startswith("h_"))
    for i in range(n_layer):
        bp = params[f"h_{i}"]
        p = f"h.{i}."
        sd[p + "ln_1.weight"] = _np(bp["ln_1"]["scale"])
        sd[p + "ln_1.bias"] = _np(bp["ln_1"]["bias"])
        sd[p + "attn.c_attn.weight"] = w(bp["attn"]["qkv"]["kernel"])
        sd[p + "attn.c_attn.bias"] = _np(bp["attn"]["qkv"]["bias"])
        sd[p + "attn.c_proj.weight"] = w(bp["attn"]["proj"]["kernel"])
        sd[p + "attn.c_proj.bias"] = _np(bp["attn"]["proj"]["bias"])
        sd[p + "ln_2.weight"] = _np(bp["ln_2"]["scale"])
        sd[p + "ln_2.bias"] = _np(bp["ln_2"]["bias"])
        sd[p + "mlp.c_fc.weight"] = w(bp["mlp"]["fc"]["kernel"])
        sd[p + "mlp.c_fc.bias"] = _np(bp["mlp"]["fc"]["bias"])
        sd[p + "mlp.c_proj.weight"] = w(bp["mlp"]["proj"]["kernel"])
        sd[p + "mlp.c_proj.bias"] = _np(bp["mlp"]["proj"]["bias"])
    # lm_head is stored (out, in) by both HF and nanoGPT (nn.Linear)
    sd["lm_head.weight"] = _np(params["lm_head"]["kernel"]).T
    return sd


def _export_lin(sd: Dict[str, np.ndarray], p: str, leaf):
    """One linear leaf -> torch (out, in) weight + optional bias — the
    shared export form for every HF-style state dict below."""
    sd[p + ".weight"] = _np(leaf["kernel"]).T
    if "bias" in leaf:
        sd[p + ".bias"] = _np(leaf["bias"])


def llama_state_dict_from_params(params) -> Dict[str, np.ndarray]:
    """Framework LLaMA-family params -> an HF `LlamaForCausalLM`-style
    state dict ("model."-prefixed), loadable by every family that shares
    the layout (LLaMA/TinyLlama/Mistral/Qwen2/Gemma/Gemma-2). Inverse of
    checkpoint.llama_params_from_state_dict:

      * projections transpose back to torch's (out, in); any q/k/v
        'bias' leaves (Qwen2) ride along;
      * a 'post_ln_1' leaf in the blocks (Gemma-2) switches the norm
        naming — post_attention_layernorm becomes the POST-attention
        norm and the pre-MLP norm exports as pre_feedforward_layernorm —
        detected from the pytree itself, no flag;
      * tied pytrees (no 'lm_head' leaf — Gemma, LLaMA-3.2 class) export
        NO lm_head.weight: HF reties from the embedding when the config
        says tie_word_embeddings.

    The full fine-tune-and-hand-back loop: convert an HF checkpoint in,
    train with this framework, export here, `torch.load` on the other
    side."""

    def _lin(p, leaf):
        _export_lin(sd, p, leaf)  # Qwen2-class q/k/v biases ride along

    n_layer = sum(1 for k in params if k.startswith("h_"))
    if (n_layer and "ln_2" not in params["h_0"]
            and "post_ln_1" not in params["h_0"]):
        # Phi layout (parallel block: ONE norm per layer, fc1/fc2,
        # dense) exports through its own branch — distinct from OLMo-2,
        # which also lacks ln_2 but carries the post-branch norms
        return phi_state_dict_from_params(params)
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _np(params["wte"]["embedding"]),
        "model.norm.weight": _np(params["ln_f"]["scale"]),
    }
    for i in range(n_layer):
        bp = params[f"h_{i}"]
        p = f"model.layers.{i}."
        if "ln_1" in bp:
            sd[p + "input_layernorm.weight"] = _np(bp["ln_1"]["scale"])
        _lin(p + "self_attn.q_proj", bp["attn"]["q"])
        _lin(p + "self_attn.k_proj", bp["attn"]["k"])
        _lin(p + "self_attn.v_proj", bp["attn"]["v"])
        _lin(p + "self_attn.o_proj", bp["attn"]["o"])
        if "q_norm" in bp["attn"]:  # Qwen3/OLMo-2 qk_norm
            sd[p + "self_attn.q_norm.weight"] = \
                _np(bp["attn"]["q_norm"]["scale"])
            sd[p + "self_attn.k_norm.weight"] = \
                _np(bp["attn"]["k_norm"]["scale"])
        _lin(p + "mlp.gate_proj", bp["mlp"]["gate"])
        _lin(p + "mlp.up_proj", bp["mlp"]["up"])
        _lin(p + "mlp.down_proj", bp["mlp"]["down"])
        if "post_ln_1" in bp and "ln_1" not in bp:
            # OLMo-2: post-norm-only block (two norms, no pre-norms)
            sd[p + "post_attention_layernorm.weight"] = \
                _np(bp["post_ln_1"]["scale"])
            sd[p + "post_feedforward_layernorm.weight"] = \
                _np(bp["post_ln_2"]["scale"])
        elif "post_ln_1" in bp:  # Gemma-2 block: 4 norms, shifted names
            sd[p + "post_attention_layernorm.weight"] = \
                _np(bp["post_ln_1"]["scale"])
            sd[p + "pre_feedforward_layernorm.weight"] = \
                _np(bp["ln_2"]["scale"])
            sd[p + "post_feedforward_layernorm.weight"] = \
                _np(bp["post_ln_2"]["scale"])
        else:
            sd[p + "post_attention_layernorm.weight"] = \
                _np(bp["ln_2"]["scale"])
    if "lm_head" in params:
        sd["lm_head.weight"] = _np(params["lm_head"]["kernel"]).T
    return sd


def phi_state_dict_from_params(params) -> Dict[str, np.ndarray]:
    """Framework Phi params (parallel block — models/llama.py
    parallel_block configs) -> an HF `PhiForCausalLM`-style state dict;
    inverse of checkpoint.phi_params_from_state_dict. Biased LayerNorms
    export weight+bias, the o projection exports as `self_attn.dense`,
    the plain MLP as `mlp.fc1/fc2`, and lm_head keeps its bias —
    the same fine-tune-and-hand-back loop the LLaMA exporter gives."""

    def _lin(p, leaf):
        _export_lin(sd, p, leaf)

    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _np(params["wte"]["embedding"]),
        "model.final_layernorm.weight": _np(params["ln_f"]["scale"]),
        "model.final_layernorm.bias": _np(params["ln_f"]["bias"]),
    }
    n_layer = sum(1 for k in params if k.startswith("h_"))
    for i in range(n_layer):
        bp = params[f"h_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _np(bp["ln_1"]["scale"])
        sd[p + "input_layernorm.bias"] = _np(bp["ln_1"]["bias"])
        _lin(p + "self_attn.q_proj", bp["attn"]["q"])
        _lin(p + "self_attn.k_proj", bp["attn"]["k"])
        _lin(p + "self_attn.v_proj", bp["attn"]["v"])
        _lin(p + "self_attn.dense", bp["attn"]["o"])
        _lin(p + "mlp.fc1", bp["mlp"]["up"])
        _lin(p + "mlp.fc2", bp["mlp"]["down"])
    if "lm_head" in params:
        _lin("lm_head", params["lm_head"])
    return sd
