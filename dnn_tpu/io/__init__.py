from dnn_tpu.io.checkpoint import (
    load_checkpoint,
    load_pth_state_dict,
    cifar_params_from_torch_state_dict,
    gpt_params_from_state_dict,
    save_npz,
)
from dnn_tpu.io.preprocess import load_image, dummy_image

__all__ = [
    "load_checkpoint",
    "load_pth_state_dict",
    "cifar_params_from_torch_state_dict",
    "gpt_params_from_state_dict",
    "save_npz",
    "load_image",
    "dummy_image",
]
