from dnn_tpu.io.checkpoint import (
    load_checkpoint,
    load_pth_state_dict,
    cifar_params_from_torch_state_dict,
    gpt_params_from_state_dict,
    save_npz,
)
from dnn_tpu.io.preprocess import load_image, dummy_image
from dnn_tpu.io.train_ckpt import (
    save_train_state,
    restore_train_state,
    latest_checkpoint,
    cleanup_old_checkpoints,
)

__all__ = [
    "save_train_state",
    "restore_train_state",
    "latest_checkpoint",
    "cleanup_old_checkpoints",
    "load_checkpoint",
    "load_pth_state_dict",
    "cifar_params_from_torch_state_dict",
    "gpt_params_from_state_dict",
    "save_npz",
    "load_image",
    "dummy_image",
]
