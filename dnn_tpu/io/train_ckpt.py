"""Training checkpoint save / resume.

The reference is load-only: every node reads one pre-trained `.pth` and
never writes anything back (/root/reference/node.py:294-317; SURVEY §5
"Checkpoint / resume: LOAD-ONLY ... No saving, no resume"). The rebuild
adds the other half: periodically persist the full train state (params +
optimizer state + step) and resume from the newest checkpoint.

Design (TPU-first, torch-free):
  * A checkpoint is one `.npz` per step (`step_00000100.npz`) plus a JSON
    manifest. Arbitrary pytrees are flattened with
    `jax.tree_util.tree_flatten_with_path`; each leaf is keyed by its
    keystr, so optax states (nested namedtuples) round-trip without custom
    code.
  * Restore is template-based: the caller passes a `like=` pytree with the
    target structure (the freshly-initialized train state), mirroring how
    the engine slices a full state dict per stage. This avoids pickling
    treedefs.
  * bfloat16 leaves are stored as a uint16 view with the true dtype
    recorded in the manifest (npz has no native bf16).
  * Sharded arrays are fine: `np.asarray` gathers the addressable shards
    (single-process), and restore re-places leaves with `device_put` onto
    each template leaf's sharding, so a dp/tp/pp-sharded train state resumes
    into the same mesh layout it was saved from.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d{8})\.npz$")
_MANIFEST_SUFFIX = ".manifest.json"


def _flatten(tree) -> Tuple[dict, Any]:
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    flat = {}
    for path, leaf in leaves:
        flat[jax.tree_util.keystr(path)] = leaf
    return flat, treedef


def _to_savable(x: np.ndarray):
    """Return (array-to-store, dtype-tag). bf16 -> uint16 view + tag."""
    arr = np.asarray(x)
    if arr.dtype.name == "bfloat16":
        return arr.view(np.uint16), "bfloat16"
    return arr, arr.dtype.name


def _from_savable(arr: np.ndarray, tag: str):
    if tag == "bfloat16":
        import ml_dtypes

        return arr.view(ml_dtypes.bfloat16)
    return arr


def checkpoint_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:08d}.npz")


def save_train_state(
    ckpt_dir: str, step: int, state, *, compress_bf16: bool = False
) -> str:
    """Persist `state` (any pytree: (params, opt_state), a dataclass of
    arrays, ...) as checkpoint `step` under `ckpt_dir`. Atomic: written to a
    temp file in the same directory, then renamed. Returns the path.

    `compress_bf16=True` stores float32 leaves as bfloat16 (half the bytes,
    round-to-nearest-even via the native codec); restore upcasts back to the
    template's dtype. Use for inference snapshots / space-constrained
    checkpoints — optimizer moments lose precision like everything else."""
    os.makedirs(ckpt_dir, exist_ok=True)
    flat, _ = _flatten(state)
    arrays, dtypes = {}, {}
    for i, (key, leaf) in enumerate(flat.items()):
        arr = np.asarray(leaf)
        if compress_bf16 and arr.dtype == np.float32:
            from dnn_tpu.native import f32_to_bf16

            arr = f32_to_bf16(arr)
        arr, tag = _to_savable(arr)
        # npz member names must be safe; manifest maps index -> keystr.
        arrays[f"leaf_{i}"] = arr
        dtypes[f"leaf_{i}"] = {"key": key, "dtype": tag}

    # Crash-safe ordering. A checkpoint is "complete" only when BOTH the
    # npz and its manifest exist (latest_checkpoint checks the pair), so:
    #   1. stage both files as temps;
    #   2. if overwriting an existing step, retract the OLD manifest — the
    #      stale npz becomes invisible debris, and a crash from here on can
    #      never pair a new manifest with the old npz;
    #   3. rename the npz into place, THEN the manifest. A kill between
    #      the renames leaves npz-without-manifest == ignorable debris.
    # Every crash point therefore yields either the complete new pair, or
    # no visible step-N checkpoint (resume falls back to the previous one)
    # — never a checkpoint that resume selects but cannot trust.
    path = checkpoint_path(ckpt_dir, step)
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".npz.tmp")
    mfd, mtmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".manifest.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        with os.fdopen(mfd, "w") as f:
            json.dump({"step": step, "leaves": dtypes, "format": 1}, f)
        if os.path.exists(path + _MANIFEST_SUFFIX):
            os.unlink(path + _MANIFEST_SUFFIX)
        os.replace(tmp, path)
        os.replace(mtmp, path + _MANIFEST_SUFFIX)
    except BaseException:
        for t in (tmp, mtmp):
            if os.path.exists(t):
                os.unlink(t)
        raise
    return path


def restore_train_state(ckpt_dir_or_path: str, like, step: Optional[int] = None):
    """Load a checkpoint into the structure of `like` (a template pytree
    with the desired treedef, e.g. a freshly-initialized train state).
    Returns (state, step). Leaves are re-placed onto each template leaf's
    sharding (committed device placement), so sharded states resume in
    place."""
    if os.path.isdir(ckpt_dir_or_path):
        if step is not None:
            path = checkpoint_path(ckpt_dir_or_path, step)
        else:
            found = latest_checkpoint(ckpt_dir_or_path)
            if found is None:
                raise FileNotFoundError(
                    f"no checkpoints under {ckpt_dir_or_path}"
                )
            path, step = found
    else:
        path = ckpt_dir_or_path

    with open(path + _MANIFEST_SUFFIX) as f:
        manifest = json.load(f)
    if step is None:
        step = manifest["step"]

    by_key = {}
    with np.load(path) as zf:
        for member, meta in manifest["leaves"].items():
            by_key[meta["key"]] = _from_savable(zf[member], meta["dtype"])

    leaves, treedef = jax.tree_util.tree_flatten_with_path(like)
    out = []
    for path_keys, tmpl in leaves:
        key = jax.tree_util.keystr(path_keys)
        if key not in by_key:
            raise KeyError(f"checkpoint {path} is missing leaf {key}")
        arr = by_key[key]
        tmpl_arr = np.asarray(tmpl) if not hasattr(tmpl, "shape") else tmpl
        if tuple(arr.shape) != tuple(tmpl_arr.shape):
            raise ValueError(
                f"shape mismatch for {key}: checkpoint {arr.shape} vs "
                f"template {tmpl_arr.shape}"
            )
        if arr.dtype != tmpl_arr.dtype:
            # dtype adaptation (e.g. a compress_bf16 checkpoint restored
            # into an f32 state); bf16 -> f32 upcasts through the native
            # codec, everything else through numpy
            if arr.dtype.name == "bfloat16" and tmpl_arr.dtype == np.float32:
                from dnn_tpu.native import bf16_to_f32

                arr = bf16_to_f32(arr)
            else:
                arr = arr.astype(tmpl_arr.dtype)
        if isinstance(tmpl, jax.Array):
            out.append(jax.device_put(arr, tmpl.sharding))
        else:
            out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out), step


def latest_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """Newest complete (path, step) under ckpt_dir, or None. An npz without
    its manifest (crash debris) is skipped."""
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m:
            path = os.path.join(ckpt_dir, name)
            if not os.path.exists(path + _MANIFEST_SUFFIX):
                continue
            s = int(m.group(1))
            if best is None or s > best[1]:
                best = (path, s)
    return best


class AsyncCheckpointer:
    """Overlap checkpoint IO with training.

    `save()` snapshots the state to HOST memory synchronously (the
    device-to-host copies — cheap next to the npz serialization + disk
    write) and hands the copies to one background writer thread running
    the same atomic `save_train_state`. The training loop keeps stepping
    while the write happens; the snapshot copy also makes saving safe
    under buffer donation (the step may invalidate the device buffers the
    moment it runs — the host copy is already taken).

    One writer, bounded in-flight count: at most `max_pending` snapshots
    exist between enqueue and commit — the (max_pending+1)-th `save()`
    BLOCKS before even taking its host copy (backpressure: checkpoints
    are ordered, and a train loop outrunning the disk should feel it
    rather than accumulate multi-GB host copies). A failed write
    re-raises on the NEXT `save()`/`wait()` call, so errors surface in
    the loop that caused them. Call `wait()` before reading
    `latest_checkpoint` (or exiting) — a checkpoint is visible only
    after its writer-side atomic rename.
    """

    def __init__(self, max_pending: int = 1):
        import queue
        import threading

        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self._q: "queue.Queue" = queue.Queue()
        # bounds snapshots alive (queued + being written), not queue slots
        # — a maxsize'd queue alone under-counts the one the worker holds
        self._slots = threading.Semaphore(max_pending)
        self._err: Optional[BaseException] = None
        self._err_lock = threading.Lock()
        self._closed = False

        def run():
            while True:
                item = self._q.get()
                try:
                    if item is None:
                        return
                    ckpt_dir, step, host_state, compress = item
                    try:
                        save_train_state(ckpt_dir, step, host_state,
                                         compress_bf16=compress)
                    except BaseException as e:  # noqa: BLE001 — held for caller
                        with self._err_lock:
                            if self._err is None:
                                self._err = e
                    finally:
                        self._slots.release()
                finally:
                    self._q.task_done()

        self._worker = threading.Thread(target=run, daemon=True,
                                        name="ckpt-writer")
        self._worker.start()

    def _raise_pending(self):
        with self._err_lock:
            err, self._err = self._err, None
        if err is not None:
            raise RuntimeError("async checkpoint write failed") from err

    def save(self, ckpt_dir: str, step: int, state, *,
             compress_bf16: bool = False) -> None:
        """Snapshot `state` to host and enqueue the write. Blocks only for
        the device-to-host copies (and for queue space when the previous
        write is still in flight)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        self._slots.acquire()  # backpressure BEFORE the host copy
        try:
            # np.array (not asarray): numpy leaves and zero-copy
            # CPU-backed jax.Arrays must be REAL copies, or an in-place /
            # donated update could mutate the snapshot mid-write
            host_state = jax.tree_util.tree_map(
                lambda x: np.array(x, copy=True), state)
            self._q.put((ckpt_dir, step, host_state, compress_bf16))
        except BaseException:
            self._slots.release()
            raise

    def wait(self) -> None:
        """Block until every enqueued write has committed (atomic rename
        done); re-raise the first failure if any write died."""
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Drain outstanding writes and stop the worker. Idempotent."""
        if self._closed:
            return
        self._q.join()
        self._closed = True
        self._q.put(None)
        self._worker.join(timeout=60)
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def cleanup_old_checkpoints(ckpt_dir: str, keep: int = 3) -> int:
    """Delete all but the newest `keep` COMPLETE checkpoints (npz+manifest
    pairs — the same completeness rule latest_checkpoint applies), plus any
    crash debris: an npz without its manifest or a manifest without its npz.
    Returns #files-removed."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    if not os.path.isdir(ckpt_dir):
        return 0
    complete, debris = [], []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m:
            path = os.path.join(ckpt_dir, name)
            if os.path.exists(path + _MANIFEST_SUFFIX):
                complete.append((int(m.group(1)), path))
            else:
                debris.append(path)
        elif name.endswith(_MANIFEST_SUFFIX):
            npz = os.path.join(ckpt_dir, name[: -len(_MANIFEST_SUFFIX)])
            if _STEP_RE.match(os.path.basename(npz)) and not os.path.exists(npz):
                debris.append(os.path.join(ckpt_dir, name))
    complete.sort(reverse=True)
    removed = 0
    for _, path in complete[keep:]:
        os.unlink(path)
        os.unlink(path + _MANIFEST_SUFFIX)
        removed += 2
    for path in debris:
        os.unlink(path)
        removed += 1
    return removed
