"""Tokenizers for text-in/text-out serving.

The reference framework has no text layer at all — its GPT path takes and
returns raw token ids (/root/reference/partitions/gpt_model_parts.py), and
its only text RPC (`SendMessage`) is dead code with no caller
(node.py:111-113, SURVEY §3.4). The rebuild gives that RPC a job: the LM
daemon can serve PROMPT TEXT -> GENERATED TEXT when built with a
tokenizer (dnn_tpu/runtime/lm_server.py).

Two implementations behind one two-method protocol
(`encode(str) -> list[int]`, `decode(ids) -> str`):

  * `ByteTokenizer` — dependency-free UTF-8 bytes as ids (+ optional id
    offset to keep specials free). Any model with vocab_size >= 256
    serves text out of the box; it is also the test vehicle (exact
    round-trip by construction, no vocab files needed).
  * `load_hf_tokenizer(path)` — a thin adapter over a LOCAL HuggingFace
    tokenizer directory (AutoTokenizer.from_pretrained on a path; this
    environment has no network, and a hub name would try to download).
    Use for real GPT-2/LLaMA vocabularies.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["ByteTokenizer", "load_hf_tokenizer"]


class ByteTokenizer:
    """UTF-8 bytes as token ids, shifted by `offset`.

    Round-trips any text exactly (decode(encode(s)) == s). Ids outside
    [offset, offset+256) decode to the replacement character rather than
    raising — generated ids come from a model that does not know byte
    boundaries, and a text endpoint must not 500 on them."""

    def __init__(self, vocab_size: int, *, offset: int = 0):
        if vocab_size < offset + 256:
            raise ValueError(
                f"byte tokenizer needs vocab_size >= offset+256, got "
                f"{vocab_size} (offset {offset})")
        self.vocab_size = vocab_size
        self.offset = offset

    def encode(self, text: str) -> List[int]:
        return [b + self.offset for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        raw = bytearray()
        for i in ids:
            j = int(i) - self.offset
            if 0 <= j < 256:
                raw.append(j)
            else:
                raw += b"\xef\xbf\xbd"  # U+FFFD, as documented — never a
                # fabricated 0x00/0xFF byte
        return bytes(raw).decode("utf-8", errors="replace")

    def vocab_bytes(self) -> List[bytes]:
        """Token id -> the bytes that token emits — the vocab map
        constrained decoding compiles its token table over
        (runtime/constrain.TokenConstraint). Ids outside the byte range
        map to b"", which the constraint engine bans outright."""
        return [bytes([i - self.offset])
                if self.offset <= i < self.offset + 256 else b""
                for i in range(self.vocab_size)]


def load_hf_tokenizer(path: str):
    """Adapter over a local HF tokenizer directory: returns an object with
    the same encode/decode protocol (no special tokens added on encode;
    specials skipped on decode — the daemon serves raw continuations)."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    class _HF:
        vocab_size = tok.vocab_size

        @staticmethod
        def encode(text: str) -> List[int]:
            return tok.encode(text, add_special_tokens=False)

        @staticmethod
        def decode(ids: Sequence[int]) -> str:
            return tok.decode(list(ids), skip_special_tokens=True)

    return _HF()
