"""Tokenizers for text-in/text-out serving.

The reference framework has no text layer at all — its GPT path takes and
returns raw token ids (/root/reference/partitions/gpt_model_parts.py), and
its only text RPC (`SendMessage`) is dead code with no caller
(node.py:111-113, SURVEY §3.4). The rebuild gives that RPC a job: the LM
daemon can serve PROMPT TEXT -> GENERATED TEXT when built with a
tokenizer (dnn_tpu/runtime/lm_server.py).

Two implementations behind one two-method protocol
(`encode(str) -> list[int]`, `decode(ids) -> str`):

  * `ByteTokenizer` — dependency-free UTF-8 bytes as ids (+ optional id
    offset to keep specials free). Any model with vocab_size >= 256
    serves text out of the box; it is also the test vehicle (exact
    round-trip by construction, no vocab files needed).
  * `load_hf_tokenizer(path)` — a thin adapter over a LOCAL HuggingFace
    tokenizer directory (AutoTokenizer.from_pretrained on a path; this
    environment has no network, and a hub name would try to download).
    Use for real GPT-2/LLaMA vocabularies.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = ["ByteTokenizer", "StreamingDetokenizer",
           "stream_detokenizer", "hf_vocab_bytes", "load_hf_tokenizer"]


class ByteTokenizer:
    """UTF-8 bytes as token ids, shifted by `offset`.

    Round-trips any text exactly (decode(encode(s)) == s). Ids outside
    [offset, offset+256) decode to the replacement character rather than
    raising — generated ids come from a model that does not know byte
    boundaries, and a text endpoint must not 500 on them."""

    def __init__(self, vocab_size: int, *, offset: int = 0):
        if vocab_size < offset + 256:
            raise ValueError(
                f"byte tokenizer needs vocab_size >= offset+256, got "
                f"{vocab_size} (offset {offset})")
        self.vocab_size = vocab_size
        self.offset = offset

    def encode(self, text: str) -> List[int]:
        return [b + self.offset for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        raw = bytearray()
        for i in ids:
            j = int(i) - self.offset
            if 0 <= j < 256:
                raw.append(j)
            else:
                raw += b"\xef\xbf\xbd"  # U+FFFD, as documented — never a
                # fabricated 0x00/0xFF byte
        return bytes(raw).decode("utf-8", errors="replace")

    def vocab_bytes(self, vocab_size: Optional[int] = None) -> List[bytes]:
        """Token id -> the bytes that token emits — the vocab map
        constrained decoding compiles its token table over
        (runtime/constrain.TokenConstraint). Ids outside the byte range
        map to b"", which the constraint engine bans outright. Pass the
        MODEL's `vocab_size` when it differs (padded embedding table)."""
        size = vocab_size or self.vocab_size
        return [bytes([i - self.offset])
                if self.offset <= i < self.offset + 256 else b""
                for i in range(size)]


def _utf8_complete_prefix(b) -> int:
    """Length of the longest prefix of `b` that ends on a UTF-8 sequence
    boundary — the split point at which chunked decoding equals whole
    -buffer decoding. Only a trailing INCOMPLETE sequence (a lead byte
    still waiting for continuation bytes) is held back; orphan
    continuation bytes and invalid leads can never become valid later,
    so they flow through (decoded to U+FFFD, exactly as a one-shot
    decode would)."""
    n = len(b)
    i, k = n - 1, 0
    while i >= 0 and k < 3 and (b[i] & 0xC0) == 0x80:
        i -= 1
        k += 1
    if i < 0:
        return n  # nothing but continuations — invalid either way
    lead = b[i]
    if lead >= 0xF0:
        need = 4
    elif lead >= 0xE0:
        need = 3
    elif lead >= 0xC0:
        need = 2
    else:
        need = 1  # ASCII or invalid lead — complete at this byte
    return i if i + need > n else n


class _ByteStreamingDetokenizer:
    """Byte-exact incremental detokenizer for ByteTokenizer streams:
    O(1) per token, emits only complete UTF-8 sequences (a multi-byte
    character split across tokens never surfaces as partial garbage).
    Invariant: ``"".join(push(t) for t) + flush() == tok.decode(ids)``
    byte-for-byte — pinned in tests/test_tokenizer.py."""

    def __init__(self, tok: "ByteTokenizer"):
        self._tok = tok
        self._buf = bytearray()

    def push(self, token_id: int) -> str:
        j = int(token_id) - self._tok.offset
        if 0 <= j < 256:
            self._buf.append(j)
        else:
            self._buf += b"\xef\xbf\xbd"  # U+FFFD, as decode() does
        cut = _utf8_complete_prefix(self._buf)
        if cut == 0:
            return ""
        chunk = bytes(self._buf[:cut]).decode("utf-8", errors="replace")
        del self._buf[:cut]
        return chunk

    def flush(self) -> str:
        chunk = bytes(self._buf).decode("utf-8", errors="replace")
        self._buf.clear()
        return chunk


class StreamingDetokenizer:
    """Tokenizer-agnostic incremental detokenizer: works over anything
    with ``decode(ids) -> str`` (the HF adapter included, whose BPE
    pieces may be partial UTF-8 sequences).

    Strategy (the HF TextStreamer construction): keep all ids, decode
    the full stream, emit the text that GREW since the last emission —
    holding back whenever the decode ends in U+FFFD, because a later
    token may complete the partial character (a genuine replacement
    character is released by the next clean decode, or by flush()).
    Cost is O(n) decode per token (O(n^2) per stream) — bounded by
    max_new_tokens; use ByteTokenizer's byte-exact streamer (via
    `stream_detokenizer`) for the O(n) path.

    Invariant: ``"".join(chunks) + flush() == decode(all_ids)`` for any
    PREFIX-MONOTONE decode (decode(ids + [t]) extends decode(ids)) —
    true of byte-concatenation decoders (byte-level BPE, ByteTokenizer,
    this module's HF adapter). A non-monotone decode (e.g. HF
    clean_up_tokenization_spaces collapsing "word " + "." -> "word.")
    cannot stream exactly — emitted text can never be retracted; this
    class detects the prefix violation, stops emitting, and lets
    flush() emit everything past the longest common prefix (no
    duplicated characters, possibly a small divergence at the
    boundary)."""

    def __init__(self, tok):
        self._tok = tok
        self._ids: List[int] = []
        self._done = ""  # text already yielded

    def push(self, token_id: int) -> str:
        self._ids.append(int(token_id))
        text = self._tok.decode(self._ids)
        if text.endswith("�"):
            return ""  # possibly a split multi-byte piece — wait
        if not text.startswith(self._done):
            return ""  # non-monotone decode — hold for flush()
        chunk = text[len(self._done):]
        self._done = text
        return chunk

    def flush(self) -> str:
        text = self._tok.decode(self._ids)
        if text.startswith(self._done):
            chunk = text[len(self._done):]
        else:
            n = 0  # longest common prefix with what already went out
            for a, b in zip(text, self._done):
                if a != b:
                    break
                n += 1
            chunk = text[n:]
        self._done = text
        return chunk


def stream_detokenizer(tok):
    """The right incremental detokenizer for `tok`: byte-exact O(1)/token
    for ByteTokenizer, decode-diff for everything else."""
    if isinstance(tok, ByteTokenizer):
        return _ByteStreamingDetokenizer(tok)
    return StreamingDetokenizer(tok)


def _byte_level_alphabet():
    """The GPT-2 byte-level BPE printable-alias table: byte value ->
    the unicode char that stands for it inside vocab token STRINGS
    (the public bytes_to_unicode construction — printable bytes map to
    themselves, the rest to 256+n aliases)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAC + 1)) + list(range(0xAE, 0xFF + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def hf_vocab_bytes(tok, vocab_size: Optional[int] = None) -> List[bytes]:
    """Best-effort token-id -> EMITTED-BYTES map for a HuggingFace
    tokenizer — the vocab map constrained decoding needs
    (runtime/constrain.TokenConstraint) for real BPE/SentencePiece
    models, where one token is several bytes.

    Handles the two dominant conventions, DETECTED ONCE PER VOCAB (a
    per-token guess would mis-decode SentencePiece pieces that happen to
    consist of alias-alphabet chars — 'é' must become its UTF-8 bytes,
    not the Latin-1 byte the alias table maps it to):
      * SentencePiece (LLaMA family) — any '▁'-marked or '<0xNN>' piece
        in the vocab: '▁' prefixes a space, '<0xNN>' pieces are raw
        bytes, everything else is UTF-8 text;
      * otherwise byte-level BPE (GPT-2/RoBERTa family): vocab strings
        use the bytes_to_unicode alias alphabet, inverted char-by-char.
    Special tokens and anything unmappable map to b"" (banned by the
    constraint engine — a grammar can never need them; EOS is handled
    separately by mask_row). Pass the MODEL's `vocab_size` when its
    embedding table is padded past the tokenizer vocab — the padding ids
    map to b"".

    Known best-effort divergence (SentencePiece): '▁' is mapped to a
    space UNCONDITIONALLY, but SP detokenization strips the leading
    space of the FIRST piece — so a '▁'-prefixed token at position 0
    contributes b" x..." here while the decoded text starts with "x...".
    A grammar anchored at string start therefore cannot be satisfied by
    '▁'-prefixed first tokens even when the decoded text would match;
    write such grammars to tolerate one leading space (e.g. prefix with
    ' ?'), or serve byte-level vocabs where the map is exact."""
    vocab = tok.get_vocab()  # {token_string: id}
    size = vocab_size or max(vocab.values()) + 1
    out = [b""] * size
    specials = set(getattr(tok, "all_special_tokens", []) or [])

    def _is_byte_piece(s):
        return s.startswith("<0x") and s.endswith(">") and len(s) == 6

    sentencepiece = any("▁" in s or _is_byte_piece(s) for s in vocab)
    alias = None if sentencepiece else _byte_level_alphabet()
    for s, tid in vocab.items():
        if tid >= size or s in specials:
            continue
        if sentencepiece:
            if _is_byte_piece(s):
                try:
                    out[tid] = bytes([int(s[3:5], 16)])
                except ValueError:
                    pass
                continue
            out[tid] = s.replace("▁", " ").encode("utf-8")
        elif all(ch in alias for ch in s):
            out[tid] = bytes(alias[ch] for ch in s)
        # non-alias strings in a byte-level vocab (added specials) stay b""
    return out


def load_hf_tokenizer(path: str):
    """Adapter over a local HF tokenizer directory: returns an object with
    the same encode/decode protocol (no special tokens added on encode;
    specials skipped on decode — the daemon serves raw continuations),
    plus `vocab_bytes()` so constrained decoding / the daemon's JSON mode
    work over the real vocab."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    class _HF:
        vocab_size = tok.vocab_size

        @staticmethod
        def encode(text: str) -> List[int]:
            return tok.encode(text, add_special_tokens=False)

        @staticmethod
        def decode(ids: Sequence[int]) -> str:
            return tok.decode(list(ids), skip_special_tokens=True)

        @staticmethod
        def vocab_bytes(vocab_size: Optional[int] = None) -> List[bytes]:
            return hf_vocab_bytes(tok, vocab_size)

    return _HF()
