"""Tensor wire codec.

The reference serializes activations as raw little-endian numpy bytes plus
a (shape, dtype-string) header carried in its protobuf `Tensor` message
(node_service.proto:26-30; encode node.py:64-68, decode node.py:45-48) —
with no endianness handling and no integrity check. This codec keeps the
same wire triple (bytes, shape, dtype) for compatibility, normalizes to
little-endian explicitly, supports bf16 (which numpy only has via
ml_dtypes), and validates payload length against shape*itemsize instead of
letting `reshape` throw.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


class PayloadCorruptError(ValueError):
    """Checksum mismatch on a tensor payload — transient wire corruption,
    distinct from deterministic decode failures (bad dtype/shape), so the
    transport layer knows a resend is worthwhile."""


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def encode_tensor(arr) -> Tuple[bytes, Tuple[int, ...], str]:
    """array -> (payload, shape, dtype_name), little-endian payload."""
    a = np.asarray(arr)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    shape = tuple(a.shape)  # before ascontiguousarray, which promotes 0-d to 1-d
    a = np.ascontiguousarray(a)
    return a.tobytes(), shape, a.dtype.name


def decode_tensor(payload: bytes, shape: Sequence[int], dtype: str) -> np.ndarray:
    """(payload, shape, dtype_name) -> array, with length validation."""
    dt = _np_dtype(dtype)
    shape = tuple(int(s) for s in shape)
    expect = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
    if len(payload) != expect:
        raise ValueError(
            f"tensor payload is {len(payload)} bytes but shape {shape} "
            f"dtype {dtype} needs {expect}"
        )
    return np.frombuffer(payload, dtype=dt).reshape(shape).copy()
