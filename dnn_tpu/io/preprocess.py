"""Client-side input preprocessing.

Rebuilds the reference's node-0 image path (/root/reference/node.py:142-154):
PIL open -> RGB -> Resize(32, 32) -> ToTensor (scale to [0,1]) ->
Normalize(mean=0.5, std=0.5 per channel) -> add batch dim; on any failure,
fall back to a dummy random input (node.py:149-154). Differences: output is
NHWC (TPU layout) and torchvision is not required — the transform is PIL +
numpy.
"""

from __future__ import annotations

import numpy as np

CIFAR_SIZE = (32, 32)
_MEAN = 0.5
_STD = 0.5


def load_image(path: str, size=CIFAR_SIZE) -> np.ndarray:
    """Image file -> normalized (1, H, W, 3) float32 array.

    Matches torchvision Resize((32,32)) (bilinear) + ToTensor + Normalize
    ((0.5,)*3, (0.5,)*3) from node.py:142-148, in NHWC.
    """
    from PIL import Image

    img = Image.open(path).convert("RGB").resize(size[::-1], Image.BILINEAR)
    arr = np.asarray(img, dtype=np.float32) / 255.0
    arr = (arr - _MEAN) / _STD
    return arr[None, ...]  # (1, H, W, 3)


def dummy_image(size=CIFAR_SIZE, seed: int = 0) -> np.ndarray:
    """The reference's torch.randn(1, 3, 32, 32) fallback (node.py:149-154),
    in NHWC."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal((1, *size, 3), dtype=np.float32)


def load_image_or_dummy(path, size=CIFAR_SIZE):
    """Load `path`, falling back to dummy data on *any* failure — exactly the
    reference's error handling (node.py:149-154). Returns (array, used_dummy)."""
    if not path:
        return dummy_image(size), True
    try:
        return load_image(path, size), False
    except Exception:
        return dummy_image(size), True
