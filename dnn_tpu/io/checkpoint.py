"""Checkpoint loading and torch->JAX weight conversion.

The reference loads one shared full-model `.pth` state dict on every node
and keeps each node's slice via `load_state_dict(strict=False)`
(/root/reference/node.py:294-317, path from config.json:15). The rebuild
must do the same *without assuming torch exists on a TPU host* (SURVEY.md
§5 "Checkpoint / resume"): `load_pth_state_dict` parses the torch zipfile
serialization format directly (zip of a pickle program + raw storage blobs)
with a restricted unpickler, and falls back to `torch.load` only if torch
is importable and the file is in a legacy format.

Also accepts `.npz` and `.safetensors` full-model checkpoints, and converts
between torch layouts (NCHW conv / (out,in) linear / HF Conv1D) and this
framework's TPU layouts (HWIO conv / (in,out) linear).
"""

from __future__ import annotations

import io
import os
import pickle
import zipfile
from typing import Dict, Optional

import numpy as np

# ----------------------------------------------------------------------
# torch-free .pth (zip serialization) reader
# ----------------------------------------------------------------------

_STORAGE_DTYPES = {
    "FloatStorage": np.float32,
    "DoubleStorage": np.float64,
    "HalfStorage": np.float16,
    "LongStorage": np.int64,
    "IntStorage": np.int32,
    "ShortStorage": np.int16,
    "CharStorage": np.int8,
    "ByteStorage": np.uint8,
    "BoolStorage": np.bool_,
}


def _bfloat16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


class _StorageRef:
    __slots__ = ("dtype", "key", "numel")

    def __init__(self, dtype, key, numel):
        self.dtype, self.key, self.numel = dtype, key, numel


class _StorageType:
    """Sentinel returned by find_class for torch.<T>Storage references."""

    __slots__ = ("dtype",)

    def __init__(self, dtype):
        self.dtype = dtype


def _rebuild_tensor(storage: np.ndarray, storage_offset, size, stride, *args, **kwargs):
    size, stride = tuple(size), tuple(stride)
    if not size:
        # 0-d tensor (e.g. a saved step counter): keep it an ndarray so it
        # survives _flatten_state_dict, matching torch.load's behavior.
        return np.array(storage[storage_offset])
    itemsize = storage.dtype.itemsize
    byte_strides = tuple(s * itemsize for s in stride)
    view = np.lib.stride_tricks.as_strided(
        storage[storage_offset:], shape=size, strides=byte_strides
    )
    return np.ascontiguousarray(view)


def _rebuild_parameter(data, requires_grad=True, backward_hooks=None):
    return data


class _TorchUnpickler(pickle.Unpickler):
    """Restricted unpickler for torch state dicts: only tensor-rebuild
    machinery and plain containers are allowed; anything else (i.e.
    arbitrary code objects in a malicious checkpoint) raises."""

    def __init__(self, file, read_storage):
        super().__init__(file)
        self._read_storage = read_storage

    def find_class(self, module, name):
        if module == "torch._utils" and name in ("_rebuild_tensor_v2", "_rebuild_tensor"):
            return _rebuild_tensor
        if module == "torch._utils" and name == "_rebuild_parameter":
            return _rebuild_parameter
        if module == "torch" and name in _STORAGE_DTYPES:
            return _StorageType(np.dtype(_STORAGE_DTYPES[name]))
        if module == "torch" and name == "BFloat16Storage":
            return _StorageType(_bfloat16_dtype())
        if module == "torch.storage" and name == "TypedStorage":
            return _StorageType(None)
        if module == "collections" and name == "OrderedDict":
            from collections import OrderedDict

            return OrderedDict
        if module == "builtins" and name in ("dict", "list", "tuple", "set", "int", "float", "str"):
            import builtins

            return getattr(builtins, name)
        raise pickle.UnpicklingError(
            f"Refusing to unpickle {module}.{name} from checkpoint (not tensor data)"
        )

    def persistent_load(self, pid):
        # torch zip format: ('storage', StorageType, key, location, numel)
        if not (isinstance(pid, tuple) and len(pid) == 5 and pid[0] == "storage"):
            raise pickle.UnpicklingError(f"Unsupported persistent id: {pid!r}")
        storage_type, key, _location, numel = pid[1], pid[2], pid[3], pid[4]
        dtype = storage_type.dtype if isinstance(storage_type, _StorageType) else None
        if dtype is None:
            dtype = np.dtype(np.float32)
        return self._read_storage(key, dtype, numel)


def load_pth_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Parse a torch-saved checkpoint into {name: numpy array} without
    importing torch. Handles the zipfile format (torch >= 1.6 default);
    legacy formats fall back to torch.load if torch is available."""
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic[:2] != b"PK":
        return _load_pth_legacy(path)

    with zipfile.ZipFile(path) as zf:
        names = zf.namelist()
        pkl_name = next((n for n in names if n.endswith("data.pkl")), None)
        if pkl_name is None:
            raise ValueError(
                f"{path} is a zip archive but not a torch checkpoint "
                "(no data.pkl member)"
            )
        prefix = pkl_name[: -len("data.pkl")]
        cache: Dict[str, np.ndarray] = {}

        def read_storage(key, dtype, numel):
            if key not in cache:
                raw = zf.read(f"{prefix}data/{key}")
                cache[key] = np.frombuffer(raw, dtype=dtype)
            return cache[key]

        with zf.open(pkl_name) as pf:
            obj = _TorchUnpickler(io.BytesIO(pf.read()), read_storage).load()

    return _flatten_state_dict(obj)


def _load_pth_legacy(path: str) -> Dict[str, np.ndarray]:
    try:
        import torch
    except ImportError:
        raise RuntimeError(
            f"{path} is a legacy (non-zip) torch checkpoint and torch is not "
            "installed; re-save it in zip format, .npz, or .safetensors"
        ) from None
    sd = torch.load(path, map_location="cpu", weights_only=True)
    return _flatten_state_dict(
        {k: v.to(torch.float32).numpy() if v.dtype == torch.bfloat16 else v.numpy()
         for k, v in sd.items()}
    )


def _flatten_state_dict(obj, prefix="") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict):
                out.update(_flatten_state_dict(v, key))
            elif isinstance(v, np.ndarray):
                out[key] = v
            # non-tensor metadata entries are dropped
    return out


# ----------------------------------------------------------------------
# generic container formats
# ----------------------------------------------------------------------

def load_npz(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def load_safetensors(path: str, keys=None) -> Dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    sd = load_file(path)
    if keys is not None:
        sd = {k: v for k, v in sd.items() if k in keys}
    return sd


def save_npz(path: str, flat_state_dict: Dict[str, np.ndarray]):
    np.savez(path, **flat_state_dict)


def load_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Dispatch on extension: .pth/.pt (torch), .npz, .safetensors."""
    ext = os.path.splitext(path)[1].lower()
    if ext in (".pth", ".pt", ".bin"):
        return load_pth_state_dict(path)
    if ext == ".npz":
        return load_npz(path)
    if ext == ".safetensors":
        return load_safetensors(path)
    raise ValueError(f"Unsupported checkpoint format: {path}")


# ----------------------------------------------------------------------
# torch layout -> TPU layout converters
# ----------------------------------------------------------------------

def _t_conv(w: np.ndarray) -> np.ndarray:
    """torch OIHW conv weight -> HWIO."""
    return np.ascontiguousarray(w.transpose(2, 3, 1, 0))


def _t_linear(w: np.ndarray) -> np.ndarray:
    """torch (out, in) linear weight -> (in, out)."""
    return np.ascontiguousarray(w.T)


def cifar_params_from_torch_state_dict(sd: Dict[str, np.ndarray]):
    """Convert the reference CNN's state dict (keys conv1/conv2/fc1/fc2
    .weight/.bias — cifar_model_parts.py:9-13) to this framework's NHWC
    param pytree. fc1 needs only the usual (out, in) transpose: the model's
    flatten boundary deliberately emits the reference's (C, H, W) feature
    order (dnn_tpu/models/cifar.py _seg_conv2), so the 4096-dim input
    layout already matches."""
    return {
        "conv1": {"kernel": np.asarray(_t_conv(sd["conv1.weight"])), "bias": sd["conv1.bias"]},
        "conv2": {"kernel": np.asarray(_t_conv(sd["conv2.weight"])), "bias": sd["conv2.bias"]},
        "fc1": {"kernel": _t_linear(sd["fc1.weight"]), "bias": sd["fc1.bias"]},
        "fc2": {"kernel": _t_linear(sd["fc2.weight"]), "bias": sd["fc2.bias"]},
    }


def _strip_prefix(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    if any(k.startswith("transformer.") for k in sd):
        stripped = {}
        for k, v in sd.items():
            stripped[k[len("transformer."):] if k.startswith("transformer.") else k] = v
        return stripped
    return sd


def _detect_gpt_layout(sd: Dict[str, np.ndarray]) -> str:
    """HF GPT-2 uses Conv1D weights stored (in, out); nanoGPT uses nn.Linear
    stored (out, in). Distinguish by the non-square c_attn shape."""
    for k, v in sd.items():
        if k.endswith("attn.c_attn.weight"):
            if v.shape[1] == 3 * v.shape[0]:
                return "conv1d"  # (C, 3C): already (in, out)
            if v.shape[0] == 3 * v.shape[1]:
                return "linear"  # (3C, C): torch Linear, transpose needed
    raise ValueError("Cannot detect GPT checkpoint layout (no c_attn.weight found)")


def gpt_params_from_state_dict(sd: Dict[str, np.ndarray], n_layer: Optional[int] = None):
    """Convert an HF-GPT-2 or nanoGPT state dict to this framework's GPT
    param pytree (dnn_tpu/models/gpt.py). Re-authors the weight-compat path
    the reference leaves implicit by importing nanoGPT's missing model.py
    (gpt_model_parts.py:4)."""
    sd = _strip_prefix(sd)
    layout = _detect_gpt_layout(sd)
    w = (lambda x: np.ascontiguousarray(x)) if layout == "conv1d" else _t_linear

    if n_layer is None:
        n_layer = 1 + max(
            int(k.split(".")[1]) for k in sd if k.startswith("h.") and k.split(".")[1].isdigit()
        )

    params = {
        "wte": {"embedding": sd["wte.weight"]},
        "wpe": {"embedding": sd["wpe.weight"]},
        "ln_f": {"scale": sd["ln_f.weight"], "bias": sd["ln_f.bias"]},
    }
    for i in range(n_layer):
        p = f"h.{i}."
        params[f"h_{i}"] = {
            "ln_1": {"scale": sd[p + "ln_1.weight"], "bias": sd[p + "ln_1.bias"]},
            "attn": {
                "qkv": {"kernel": w(sd[p + "attn.c_attn.weight"]), "bias": sd[p + "attn.c_attn.bias"]},
                "proj": {"kernel": w(sd[p + "attn.c_proj.weight"]), "bias": sd[p + "attn.c_proj.bias"]},
            },
            "ln_2": {"scale": sd[p + "ln_2.weight"], "bias": sd[p + "ln_2.bias"]},
            "mlp": {
                "fc": {"kernel": w(sd[p + "mlp.c_fc.weight"]), "bias": sd[p + "mlp.c_fc.bias"]},
                "proj": {"kernel": w(sd[p + "mlp.c_proj.weight"]), "bias": sd[p + "mlp.c_proj.bias"]},
            },
        }
    # lm_head: explicit if present, else tied to wte (GPT-2 ties weights).
    if "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _t_linear(sd["lm_head.weight"])}
    else:
        params["lm_head"] = {"kernel": np.ascontiguousarray(sd["wte.weight"].T)}
    return params


def llama_params_from_state_dict(sd: Dict[str, np.ndarray],
                                 n_layer: Optional[int] = None,
                                 post_norms: bool = False,
                                 tied_head: str = "materialize"):
    """Convert an HF LlamaForCausalLM state dict (model.embed_tokens /
    model.layers.N.self_attn.{q,k,v,o}_proj / mlp.{gate,up,down}_proj /
    input_layernorm / post_attention_layernorm / model.norm / lm_head) to
    this framework's LLaMA param pytree (dnn_tpu/models/llama.py). Every
    projection is a plain torch Linear, so each kernel takes the usual
    (out, in) -> (in, out) transpose; RMSNorm weights map to 'scale'.
    Qwen2-class checkpoints (same layout + q/k/v projection BIASES) pass
    through unchanged: any present `*_proj.bias` rides along as a 'bias'
    leaf, which ops.nn.linear applies wherever the kernel goes.

    Gemma checkpoints share the layout (GemmaForCausalLM); the two
    divergences are opt-in:
      * `post_norms=True` (Gemma-2): `post_attention_layernorm` is the
        POST-attention norm (-> post_ln_1) and the pre-MLP norm is
        `pre_feedforward_layernorm` (-> ln_2), with
        `post_feedforward_layernorm` -> post_ln_2. Under the default
        (LLaMA/Gemma-1), `post_attention_layernorm` IS the pre-MLP norm.
      * `tied_head="omit"`: tied-embedding checkpoints produce a pytree
        with NO lm_head leaf (llama.head projects through wte.T — true
        sharing, no V x C transpose copy); the default materializes the
        transpose for untied model code."""
    # HF prefixes everything but lm_head with "model."
    sd = {(k[len("model."):] if k.startswith("model.") else k): v
          for k, v in sd.items()}
    if n_layer is None:
        n_layer = 1 + max(
            int(k.split(".")[1]) for k in sd
            if k.startswith("layers.") and k.split(".")[1].isdigit()
        )

    params = {
        "wte": {"embedding": sd["embed_tokens.weight"]},
        "ln_f": {"scale": sd["norm.weight"]},
    }
    def _proj(key):
        out = {"kernel": _t_linear(sd[key + ".weight"])}
        if key + ".bias" in sd:  # Qwen2-class q/k/v biases
            out["bias"] = sd[key + ".bias"]
        return out

    for i in range(n_layer):
        p = f"layers.{i}."
        blk = {
            "attn": {
                "q": _proj(p + "self_attn.q_proj"),
                "k": _proj(p + "self_attn.k_proj"),
                "v": _proj(p + "self_attn.v_proj"),
                "o": _proj(p + "self_attn.o_proj"),
            },
            "mlp": {
                "gate": _proj(p + "mlp.gate_proj"),
                "up": _proj(p + "mlp.up_proj"),
                "down": _proj(p + "mlp.down_proj"),
            },
        }
        if p + "input_layernorm.weight" in sd:
            blk["ln_1"] = {"scale": sd[p + "input_layernorm.weight"]}
        if p + "self_attn.q_norm.weight" in sd:  # Qwen3/OLMo-2 qk_norm
            blk["attn"]["q_norm"] = {
                "scale": sd[p + "self_attn.q_norm.weight"]}
            blk["attn"]["k_norm"] = {
                "scale": sd[p + "self_attn.k_norm.weight"]}
        if post_norms and "ln_1" not in blk:
            # OLMo-2: post-norm-only block — only the two post-branch
            # norms exist (no ln_1/ln_2 at all)
            blk["post_ln_1"] = {
                "scale": sd[p + "post_attention_layernorm.weight"]}
            blk["post_ln_2"] = {
                "scale": sd[p + "post_feedforward_layernorm.weight"]}
        elif post_norms:  # Gemma-2 block: 4 norms, names shift meaning
            blk["post_ln_1"] = {
                "scale": sd[p + "post_attention_layernorm.weight"]}
            blk["ln_2"] = {
                "scale": sd[p + "pre_feedforward_layernorm.weight"]}
            blk["post_ln_2"] = {
                "scale": sd[p + "post_feedforward_layernorm.weight"]}
        else:
            blk["ln_2"] = {
                "scale": sd[p + "post_attention_layernorm.weight"]}
        params[f"h_{i}"] = blk
    # lm_head: explicit if present, else tied to the embedding
    # (LLaMA-3.2/Gemma-class models tie; TinyLlama-1.1B ships
    # tie_word_embeddings=false with an explicit lm_head.weight, as do
    # the 7B-class models)
    if tied_head == "omit":
        # tied pytree: llama.head projects through wte.embedding.T. Tied
        # HF models still EXPORT an lm_head.weight alias of the embedding
        # in state_dict() — verify it really is the same tensor rather
        # than silently dropping a genuinely different head.
        if "lm_head.weight" in sd and not np.array_equal(
                np.asarray(sd["lm_head.weight"]),
                np.asarray(sd["embed_tokens.weight"])):
            raise ValueError(
                "tied_head='omit' but the checkpoint's lm_head.weight "
                "differs from embed_tokens.weight — this model is not "
                "tied; convert with tied_head='materialize'")
    elif "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _t_linear(sd["lm_head.weight"])}
    else:
        params["lm_head"] = {
            "kernel": np.ascontiguousarray(sd["embed_tokens.weight"].T)}
    return params


def phi_params_from_state_dict(sd: Dict[str, np.ndarray],
                               n_layer: Optional[int] = None):
    """Convert an HF PhiForCausalLM state dict to this framework's
    LLaMA-family pytree (models/llama.py parallel_block configs):
    biased LayerNorms map scale+bias, `self_attn.dense` is the o
    projection, `mlp.fc1/fc2` are the plain MLP's up/down, and every
    projection (lm_head included) carries a bias. The parallel block
    has ONE norm per layer (input_layernorm -> ln_1; no ln_2 leaf)."""
    sd = {(k[len("model."):] if k.startswith("model.") else k): v
          for k, v in sd.items()}
    if n_layer is None:
        n_layer = 1 + max(
            int(k.split(".")[1]) for k in sd
            if k.startswith("layers.") and k.split(".")[1].isdigit()
        )

    def _proj(key):
        out = {"kernel": _t_linear(sd[key + ".weight"])}
        if key + ".bias" in sd:
            out["bias"] = sd[key + ".bias"]
        return out

    params = {
        "wte": {"embedding": sd["embed_tokens.weight"]},
        "ln_f": {"scale": sd["final_layernorm.weight"],
                 "bias": sd["final_layernorm.bias"]},
        "lm_head": _proj("lm_head"),
    }
    for i in range(n_layer):
        p = f"layers.{i}."
        params[f"h_{i}"] = {
            "ln_1": {"scale": sd[p + "input_layernorm.weight"],
                     "bias": sd[p + "input_layernorm.bias"]},
            "attn": {
                "q": _proj(p + "self_attn.q_proj"),
                "k": _proj(p + "self_attn.k_proj"),
                "v": _proj(p + "self_attn.v_proj"),
                "o": _proj(p + "self_attn.dense"),
            },
            "mlp": {
                "up": _proj(p + "mlp.fc1"),
                "down": _proj(p + "mlp.fc2"),
            },
        }
    return params


# ----------------------------------------------------------------------
# native (framework-own) flat format
# ----------------------------------------------------------------------

_SEP = "/"


def params_to_flat(params, prefix="") -> Dict[str, np.ndarray]:
    """Nested param pytree -> flat {"a/b/c": array} dict, the framework's
    own checkpoint layout (saved via save_npz / safetensors). This is the
    save-side capability the reference lacks entirely (load-only — SURVEY
    §5 'Checkpoint / resume')."""
    out = {}
    if isinstance(params, dict):
        for k, v in params.items():
            key = f"{prefix}{_SEP}{k}" if prefix else str(k)
            out.update(params_to_flat(v, key))
    else:
        out[prefix] = np.asarray(params)
    return out


def flat_to_params(flat: Dict[str, np.ndarray]):
    """Inverse of params_to_flat."""
    tree: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def is_native_flat(sd: Dict[str, np.ndarray]) -> bool:
    return bool(sd) and all(_SEP in k or "." not in k for k in sd)


# ----------------------------------------------------------------------
# per-stage slicing
# ----------------------------------------------------------------------

def slice_params_for_stage(full_params, stage_spec):
    """Stage-local view of the shared checkpoint — the rebuild of every node
    loading the full .pth and keeping its slice via strict=False
    (node.py:294-317), except nothing foreign is ever materialized on-device."""
    return stage_spec.slice_params(full_params)
