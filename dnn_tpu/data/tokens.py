"""Memory-mapped token dataset for causal-LM training.

The GPT training counterpart of the CIFAR loader: a flat binary of token
ids (uint16 for GPT-2's 50257-token vocab, uint32 accepted for larger
vocabularies — the nanoGPT train.bin convention). Batches are random
(B, T+1) windows — `train.next_token_loss` shifts them into inputs and
targets. The reference has no training inputs of any kind (SURVEY §5).
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

_DTYPES = {2: np.uint16, 4: np.uint32}


class TokenDataset:
    """Random-window sampler over a memory-mapped token file."""

    def __init__(self, path: str, *, dtype=None):
        size = os.path.getsize(path)
        if dtype is None:
            dtype = np.uint16
        dtype = np.dtype(dtype)
        if dtype.type not in (np.uint16, np.uint32):
            raise ValueError(f"token dtype must be uint16/uint32, got {dtype}")
        if size % dtype.itemsize != 0:
            raise ValueError(f"{path}: size {size} not divisible by {dtype.itemsize}")
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        if len(self.tokens) < 2:
            raise ValueError(f"{path}: need at least 2 tokens")

    def __len__(self) -> int:
        return len(self.tokens)

    def sample(self, rng: np.random.Generator, batch_size: int, seq_len: int) -> np.ndarray:
        """(B, seq_len + 1) int32 windows at random offsets."""
        if seq_len + 1 > len(self.tokens):
            raise ValueError(
                f"seq_len {seq_len} + 1 exceeds dataset length {len(self.tokens)}"
            )
        starts = rng.integers(0, len(self.tokens) - seq_len, batch_size)
        return np.stack(
            [self.tokens[s:s + seq_len + 1] for s in starts]
        ).astype(np.int32)

    def batches(self, batch_size: int, seq_len: int, *, seed: int = 0) -> Iterator[np.ndarray]:
        """Infinite iterator of (B, seq_len + 1) batches (deterministic per
        seed — resume-friendly with train.fit's advance_batches)."""
        rng = np.random.default_rng(seed)
        while True:
            yield self.sample(rng, batch_size, seq_len)


def write_tokens(path: str, tokens: np.ndarray, *, dtype=np.uint16):
    """Flat token-id binary writer (fixture/export counterpart)."""
    arr = np.asarray(tokens)
    info = np.iinfo(dtype)
    if arr.min() < 0 or arr.max() > info.max:
        raise ValueError(f"token ids out of range for {np.dtype(dtype)}")
    with open(path, "wb") as f:
        f.write(arr.astype(dtype).tobytes())
