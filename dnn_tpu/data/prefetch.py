"""Host→device prefetch for input pipelines.

The reference moves data host→device synchronously inside the hot path
(`torch.from_numpy(...).to(DEVICE)`, /root/reference/node.py:45-48): every
step pays the full transfer latency. On TPU the idiomatic fix is to keep
the *next* batch's host→HBM copy in flight while the current step
computes — `jax.device_put` is async (it returns immediately with the
transfer enqueued), so a one-deep software pipeline is just "put batch
k+1 before yielding batch k".

`prefetch_to_device` wraps any host-batch iterator (CifarBinaryDataset /
TokenDataset `.batches()`) and yields on-device pytrees with `size`
transfers in flight. With a `sharding` it places batches directly in
their final layout (e.g. batch-sharded over a `data` mesh axis), so the
training step never re-lays-out its inputs.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Optional

import jax


def prefetch_to_device(
    iterator: Iterator[Any],
    size: int = 2,
    *,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator[Any]:
    """Yield batches from `iterator` as device arrays, keeping up to
    `size` async host→device transfers in flight.

    Each batch may be any pytree of numpy arrays. With `sharding`, every
    leaf is placed with that sharding (use a pytree-prefix via
    `jax.device_put`'s normal rules if leaves differ); without it, leaves
    go to the default device.
    """
    # validate eagerly (this is a plain function returning a generator, so
    # a bad `size` fails at the call site, not at the first next() deep
    # inside some training loop)
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")

    def _put(batch):
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    def _gen():
        queue: collections.deque = collections.deque()
        it = iter(iterator)
        try:
            while len(queue) < size:
                queue.append(_put(next(it)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(_put(next(it)))
            except StopIteration:
                pass
            yield out

    return _gen()
