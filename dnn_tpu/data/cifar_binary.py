"""CIFAR-10 binary-format dataset reader.

The reference framework's only input path is one PIL image per inference
request (/root/reference/node.py:142-154); it has no dataset/training
input pipeline at all (SURVEY §5). This module supplies the training-side
loader for the standard CIFAR-10 binary format (data_batch_*.bin: 10000
records of [1 label byte | 3072 image bytes, R then G then B planes,
32x32 row-major]).

Output batches match the client path's preprocessing exactly
(dnn_tpu/io/preprocess.py): float32 NHWC in [-1, 1] via /255 then
(x - 0.5) / 0.5 — so a model trained from this loader serves unchanged
behind the inference engine.
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence, Tuple

import numpy as np

RECORD_BYTES = 1 + 3 * 32 * 32
_MEAN = 0.5
_STD = 0.5


class CifarBinaryDataset:
    """Memory-mapped CIFAR-10 binary batches with seeded shuffling.

    `files` are one or more *.bin paths; records are concatenated. Images
    decode to (H, W, C) float32 normalized; labels to int32.
    """

    def __init__(self, files: Sequence[str]):
        if isinstance(files, (str, os.PathLike)):
            files = [files]
        if not files:
            raise ValueError("need at least one CIFAR binary file")
        self._mmaps = []
        for path in files:
            size = os.path.getsize(path)
            if size == 0 or size % RECORD_BYTES != 0:
                raise ValueError(
                    f"{path}: size {size} is not a multiple of the "
                    f"{RECORD_BYTES}-byte CIFAR record"
                )
            self._mmaps.append(
                np.memmap(path, dtype=np.uint8, mode="r").reshape(-1, RECORD_BYTES)
            )
        self._records = np.concatenate(self._mmaps) if len(self._mmaps) > 1 \
            else self._mmaps[0]

    def __len__(self) -> int:
        return self._records.shape[0]

    def decode(self, idx) -> Tuple[np.ndarray, np.ndarray]:
        """Records at `idx` (array-like) -> (images (N, 32, 32, 3) f32
        normalized, labels (N,) int32)."""
        recs = self._records[np.asarray(idx)]
        labels = recs[:, 0].astype(np.int32)
        # planes: (N, 3, 32, 32) CHW -> NHWC
        imgs = recs[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        imgs = imgs.astype(np.float32) / 255.0
        imgs = (imgs - _MEAN) / _STD
        return imgs, labels

    def batches(
        self, batch_size: int, *, shuffle: bool = True, seed: int = 0,
        epochs: int | None = None, drop_remainder: bool = True,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield (images, labels) batches. `epochs=None` repeats forever
        (each epoch reshuffled deterministically from `seed`)."""
        n = len(self)
        if batch_size > n:
            raise ValueError(f"batch_size {batch_size} > dataset size {n}")
        rng = np.random.default_rng(seed)
        epoch = 0
        while epochs is None or epoch < epochs:
            order = rng.permutation(n) if shuffle else np.arange(n)
            stop = n - (n % batch_size) if drop_remainder else n
            for lo in range(0, stop, batch_size):
                yield self.decode(order[lo:lo + batch_size])
            epoch += 1


def write_cifar_binary(path: str, images: np.ndarray, labels: np.ndarray):
    """Write (N, 32, 32, 3) uint8 images + (N,) labels in the CIFAR binary
    format — the test-fixture/export counterpart of the reader."""
    images = np.asarray(images, np.uint8)
    labels = np.asarray(labels, np.uint8)
    if images.ndim != 4 or images.shape[1:] != (32, 32, 3):
        raise ValueError(f"expected (N, 32, 32, 3) uint8, got {images.shape}")
    if labels.shape != (images.shape[0],):
        raise ValueError("one label per image required")
    chw = images.transpose(0, 3, 1, 2).reshape(images.shape[0], -1)
    recs = np.concatenate([labels[:, None], chw], axis=1)
    with open(path, "wb") as f:
        f.write(recs.tobytes())
