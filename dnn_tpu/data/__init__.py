from dnn_tpu.data.cifar_binary import CifarBinaryDataset
from dnn_tpu.data.tokens import TokenDataset
from dnn_tpu.data.prefetch import prefetch_to_device
from dnn_tpu.data.async_loader import AsyncCifarLoader

__all__ = ["CifarBinaryDataset", "TokenDataset", "prefetch_to_device",
           "AsyncCifarLoader"]
