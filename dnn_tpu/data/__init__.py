from dnn_tpu.data.cifar_binary import CifarBinaryDataset
from dnn_tpu.data.tokens import TokenDataset
from dnn_tpu.data.prefetch import prefetch_to_device

__all__ = ["CifarBinaryDataset", "TokenDataset", "prefetch_to_device"]
