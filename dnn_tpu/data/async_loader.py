"""Async CIFAR batch loader backed by the native (C++) decoder.

`CifarBinaryDataset.batches` decodes records on the calling thread, so
host preprocessing serializes with TPU steps. `AsyncCifarLoader` moves
decode + normalize onto a C++ background thread (dnn_tpu/native/
loader.cpp) feeding a bounded ring of ready batches — the training loop's
`next()` is a memcpy. When the native library can't build (no g++), it
degrades to the Python loader with identical batch contents for
shuffle=False (bit-for-bit; the shuffled permutation sequence differs —
splitmix64 Fisher-Yates vs numpy Generator — with per-epoch full coverage
either way).
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Sequence, Tuple

import numpy as np

from dnn_tpu.data.cifar_binary import RECORD_BYTES, CifarBinaryDataset


class AsyncCifarLoader:
    """Iterator of (images (B,32,32,3) f32 normalized, labels (B,) i32),
    repeating epochs forever. Use as a context manager (or call close())
    to stop the background thread."""

    def __init__(self, files: Sequence[str], batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, queue_depth: int = 4):
        self.batch_size = int(batch_size)
        if int(queue_depth) < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self._ds = CifarBinaryDataset(files)
        if self.batch_size > len(self._ds):
            raise ValueError(
                f"batch_size {batch_size} > dataset size {len(self._ds)}"
            )
        self._handle = None
        self._fallback = None

        from dnn_tpu import native

        lib = native.loader_lib()
        if lib is not None:
            # ZERO-COPY: the C++ side borrows this buffer for the loader's
            # lifetime, so it must stay referenced until close() destroys
            # the handle (which joins the worker thread first)
            self._blob = np.ascontiguousarray(self._ds._records).reshape(-1)
            assert self._blob.nbytes == len(self._ds) * RECORD_BYTES
            handle = lib.dnn_loader_create(
                self._blob.ctypes.data_as(ctypes.c_void_p), len(self._ds),
                self.batch_size, int(seed), int(bool(shuffle)),
                int(queue_depth),
            )
            if handle:
                self._handle = ctypes.c_void_p(handle)
                self._lib = lib
        if self._handle is None:
            self._fallback = self._ds.batches(
                self.batch_size, shuffle=shuffle, seed=seed, epochs=None
            )

    @property
    def native(self) -> bool:
        return self._handle is not None

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._fallback is not None:
            return next(self._fallback)
        if self._handle is None:
            raise RuntimeError("loader is closed")
        imgs = np.empty((self.batch_size, 32, 32, 3), np.float32)
        labels = np.empty((self.batch_size,), np.int32)
        rc = self._lib.dnn_loader_next(
            self._handle,
            imgs.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            raise RuntimeError(f"native loader stopped (rc={rc})")
        return imgs, labels

    def close(self):
        if self._handle is not None:
            self._lib.dnn_loader_destroy(self._handle)  # joins the worker
            self._handle = None
            self._blob = None  # safe to release only after destroy
        self._fallback = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
