"""dnn_tpu — a TPU-native distributed neural-network framework.

Re-implements (from scratch, TPU-first) the capabilities of the reference
framework 123-code/Distributed-neural-networks: a model is split into
sequential stages placed on separate devices from a JSON topology config
(reference: config.json, node.py:222-277), activations flow stage-to-stage
through a pipeline (reference: gRPC SendTensor relay, node.py:35-105), a
single shared checkpoint is sliced per stage (node.py:294-317), and a
client path preprocesses an input and returns the final prediction
(node.py:137-200).

Where the reference hosts each stage as a PyTorch nn.Module in a separate
gRPC process and relays raw numpy bytes over TCP, this framework hosts
stages as jit-compiled JAX programs on TPU chips, maps the config's
`part_index` onto a `jax.sharding.Mesh` pipeline axis, and moves
activations with `jax.lax.ppermute` (XLA CollectivePermute) over ICI.
"""

from dnn_tpu.version import __version__
from dnn_tpu.registry import get_model, register_model, available_models
from dnn_tpu.config import TopologyConfig

__all__ = [
    "__version__",
    "get_model",
    "register_model",
    "available_models",
    "TopologyConfig",
]
