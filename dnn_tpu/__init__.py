"""dnn_tpu — a TPU-native distributed neural-network framework.

Re-implements (from scratch, TPU-first) the capabilities of the reference
framework 123-code/Distributed-neural-networks: a model is split into
sequential stages placed on separate devices from a JSON topology config
(reference: config.json, node.py:222-277), activations flow stage-to-stage
through a pipeline (reference: gRPC SendTensor relay, node.py:35-105), a
single shared checkpoint is sliced per stage (node.py:294-317), and a
client path preprocesses an input and returns the final prediction
(node.py:137-200).

Where the reference hosts each stage as a PyTorch nn.Module in a separate
gRPC process and relays raw numpy bytes over TCP, this framework hosts
stages as jit-compiled JAX programs on TPU chips, maps the config's
`part_index` onto a `jax.sharding.Mesh` pipeline axis, and moves
activations with `jax.lax.ppermute` (XLA CollectivePermute) over ICI.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # this codebase targets the modern `jax.shard_map(..., check_vma=)`
    # API; on older jax (<= 0.4.x) the function lives in
    # jax.experimental.shard_map with the kwarg named check_rep.
    # Install a translating alias ONCE at package import so every
    # runtime/parallel module runs unmodified on either version.
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs,
                          check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    _jax.shard_map = _compat_shard_map

if not hasattr(_jax.lax, "axis_size"):
    # same vintage gap: modern code calls lax.axis_size(name) for the
    # mapped-axis size; on older jax psum of the constant 1 folds to the
    # same Python int inside shard_map tracing.
    _jax.lax.axis_size = lambda axis_name: _jax.lax.psum(1, axis_name)

from dnn_tpu.version import __version__
from dnn_tpu.registry import get_model, register_model, available_models
from dnn_tpu.config import TopologyConfig

__all__ = [
    "__version__",
    "get_model",
    "register_model",
    "available_models",
    "TopologyConfig",
]
