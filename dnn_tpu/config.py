"""Topology / runtime configuration.

Parses the reference's JSON schema (/root/reference/config.json:1-18,
parsed at node.py:222-277) — `nodes[].{id,address,part_index}`,
`model_weights`, `num_parts`, `return_to_node_id` — and extends it with
TPU-native keys. Unlike the reference, which hard-exits unless
`num_parts == 2` (node.py:246-248), any num_parts supported by the model
family is accepted.

Extended keys (all optional, with reference-equivalent defaults):
  model:           model-zoo name (default "cifar_cnn", the reference's only
                   wired family — node.py:11,29-32)
  device_type:     "tpu" | "cpu" (BASELINE.json north-star `device_type=tpu`
                   dispatch)
  runtime:         "spmd" (shard_map+ppermute pipeline) | "relay"
                   (device-per-stage sequential relay, the reference's
                   semantics) | "auto"
  microbatches:    GPipe-style microbatching factor for the spmd runtime;
                   0 (the default) = auto — the engine picks the largest
                   divisor of the batch up to 2*num_parts, so out of the
                   box the pipeline actually overlaps stages instead of
                   degenerating to a serial relay with a (S-1)/(S) bubble
  dtype:           compute dtype ("float32" | "bfloat16")
  mesh:            {axis_name: size} overrides for multi-axis runs
  distributed:     {coordinator_address, num_processes, process_id?} — join
                   a multi-host jax.distributed job (DCN); see
                   dnn_tpu/parallel/multihost.py
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple


def _parse_distributed(d: Optional[dict]):
    if d is None:
        return None
    from dnn_tpu.parallel.multihost import DistributedConfig

    return DistributedConfig.from_dict(d)


@dataclasses.dataclass(frozen=True)
class NodeEntry:
    """One entry of config `nodes[]` (config.json:3-14). In the TPU runtime
    a "node" maps to a pipeline-stage coordinate on the mesh rather than a
    separate gRPC process; `address` is kept for the gRPC edge/serve mode."""

    id: str
    part_index: int
    address: Optional[str] = None

    @property
    def port(self) -> Optional[int]:
        # node.py:254-258 parses the port off "ip:port".
        if not self.address:
            return None
        try:
            return int(self.address.rsplit(":", 1)[-1])
        except ValueError:
            raise ValueError(
                f"Invalid address '{self.address}' for node '{self.id}'; expected IP:Port"
            ) from None


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    nodes: Tuple[NodeEntry, ...]
    num_parts: int
    model_weights: Optional[str] = None
    return_to_node_id: Optional[str] = None
    model: str = "cifar_cnn"
    device_type: str = "tpu"
    runtime: str = "auto"
    microbatches: int = 0  # 0 = auto (see engine._effective_microbatches)
    # spmd-runtime weight placement: "stage" (packed, each device holds only
    # its own stage's weights), "replicated" (all weights everywhere, no
    # pack/unpack work), or "auto" (stage iff the model is big enough for
    # per-device HBM savings to outweigh the unpack overhead — see
    # engine._resolve_param_placement)
    param_placement: str = "auto"
    dtype: str = "float32"
    mesh: Dict[str, int] = dataclasses.field(default_factory=dict)
    distributed: Optional["DistributedConfig"] = None  # multihost job spec
    # inter-stage hop transport for the gRPC edge deployment (--serve):
    # "auto" negotiates device -> shm -> grpc per hop at handshake
    # (comm/transport.py); "grpc" pins the reference wire path; explicit
    # "device"/"shm" fail loud when the hop cannot satisfy them
    transport: str = "auto"

    # ---- construction ----------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "TopologyConfig":
        raw_nodes: List[dict] = d.get("nodes", [])
        nodes = tuple(
            NodeEntry(id=n["id"], part_index=int(n["part_index"]), address=n.get("address"))
            for n in raw_nodes
        )
        num_parts = d.get("num_parts")
        if num_parts is None:
            num_parts = len(nodes) if nodes else 1
        cfg = cls(
            nodes=nodes,
            num_parts=int(num_parts),
            model_weights=d.get("model_weights"),
            return_to_node_id=d.get("return_to_node_id"),
            model=d.get("model", "cifar_cnn"),
            device_type=d.get("device_type", "tpu"),
            runtime=d.get("runtime", "auto"),
            microbatches=int(d.get("microbatches", 0)),
            param_placement=d.get("param_placement", "auto"),
            dtype=d.get("dtype", "float32"),
            mesh=dict(d.get("mesh", {})),
            distributed=_parse_distributed(d.get("distributed")),
            transport=d.get("transport", "auto"),
        )
        cfg.validate()
        return cfg

    @classmethod
    def from_json(cls, path: str) -> "TopologyConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def validate(self):
        if self.num_parts < 1:
            raise ValueError(f"num_parts must be >= 1, got {self.num_parts}")
        if self.nodes:
            part_indices = sorted(n.part_index for n in self.nodes)
            if part_indices != list(range(self.num_parts)):
                raise ValueError(
                    "nodes[].part_index must cover exactly 0..num_parts-1; got "
                    f"{part_indices} for num_parts={self.num_parts}"
                )
            ids = [n.id for n in self.nodes]
            if len(set(ids)) != len(ids):
                raise ValueError(f"duplicate node ids in config: {ids}")
        if self.return_to_node_id and self.nodes:
            if all(n.id != self.return_to_node_id for n in self.nodes):
                raise ValueError(
                    f"return_to_node_id '{self.return_to_node_id}' not among node ids"
                )
        if self.runtime not in ("auto", "spmd", "relay"):
            raise ValueError(f"runtime must be auto|spmd|relay, got '{self.runtime}'")
        if self.microbatches < 0:
            raise ValueError("microbatches must be >= 0 (0 = auto)")
        if self.param_placement not in ("auto", "stage", "replicated"):
            raise ValueError(
                "param_placement must be auto|stage|replicated, got "
                f"'{self.param_placement}'"
            )
        if self.transport not in ("auto", "grpc", "shm", "device"):
            raise ValueError(
                "transport must be auto|grpc|shm|device, got "
                f"'{self.transport}'"
            )

    # ---- lookups (reference: node.py:234-277) ----------------------------

    def node_by_id(self, node_id: str) -> NodeEntry:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(f"Node ID '{node_id}' not found in config")

    def node_by_part(self, part_index: int) -> NodeEntry:
        for n in self.nodes:
            if n.part_index == part_index:
                return n
        raise KeyError(f"No node with part_index {part_index} in config")

    def next_node(self, node: NodeEntry) -> Optional[NodeEntry]:
        """Next-hop resolution (node.py:262-271): the node owning
        part_index+1, or None for the last stage."""
        if node.part_index == self.num_parts - 1:
            return None
        return self.node_by_part(node.part_index + 1)

    def return_node(self) -> Optional[NodeEntry]:
        """The reference resolves `return_to_node_id` but never dials it
        (dead code, node.py:272-277 / SURVEY §3.3); here it names the stage
        coordinate that receives the final result ring-shifted back."""
        if not self.return_to_node_id:
            return None
        return self.node_by_id(self.return_to_node_id)
