"""PrefixDirectory: which replica holds which prefix.

The router's bounded, observation-fed map from block-aligned prefix
DIGESTS to the replica last seen holding (or being handed) those
blocks. Fed two ways:

  * admission observation — after routing a gen request (or a pull
    instruction) to replica R, every block-aligned prefix of its
    prompt is recorded as resident on R (the radix store inserts
    exactly those paths at admission, and retire-time insertion only
    extends them);
  * replica scrape — /statusz carries per-replica kvtier residency
    counts (obs/fleet.py), which the router uses for health, not keys:
    shipping the actual key set per poll would be unbounded.

`locate` walks a prompt's digests LONGEST-first, so the answer is the
replica with the deepest known coverage. Entries are a bounded LRU —
stale claims (evicted store entries, dead replicas) cost one wasted
pull instruction, never correctness: the kvpull path is advisory end
to end, and the adopter re-prefills on any miss.

Pure stdlib — unit-tests as goldens with no jax, no grpc.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Optional

import numpy as np

__all__ = ["PrefixDirectory", "PrefixLocation"]


@dataclasses.dataclass(frozen=True)
class PrefixLocation:
    replica: str
    n_blocks: int


def _digest(tokens: np.ndarray) -> bytes:
    return hashlib.blake2s(
        np.ascontiguousarray(tokens, np.int32).tobytes(),
        digest_size=16).digest()


class PrefixDirectory:
    """See module docstring. `cap` bounds entries (one per distinct
    block-aligned prefix seen fleet-wide); `max_blocks` bounds the
    per-prompt digest walk."""

    def __init__(self, block_len: int = 16, *, cap: int = 8192,
                 max_blocks: int = 64):
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        self.block_len = int(block_len)
        self.cap = int(cap)
        self.max_blocks = int(max_blocks)
        self._map: "OrderedDict[bytes, PrefixLocation]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._map)

    def _n_full(self, tokens: np.ndarray) -> int:
        return min(int(np.asarray(tokens).size) // self.block_len,
                   self.max_blocks)

    def observe(self, tokens, replica: str):
        """Record every block-aligned prefix of `tokens` as resident on
        `replica` (latest claim wins — the most recent admission/pull
        is the best guess for where the blocks live NOW)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bp = self.block_len
        for k in range(1, self._n_full(tokens) + 1):
            d = _digest(tokens[: k * bp])
            self._map[d] = PrefixLocation(str(replica), k)
            self._map.move_to_end(d)
        while len(self._map) > self.cap:
            self._map.popitem(last=False)

    def locate(self, tokens) -> Optional[PrefixLocation]:
        """The replica with the DEEPEST known coverage of `tokens`'s
        block-aligned prefixes, or None. A hit promotes to MRU."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bp = self.block_len
        for k in range(self._n_full(tokens), 0, -1):
            loc = self._map.get(_digest(tokens[: k * bp]))
            if loc is not None:
                self._map.move_to_end(_digest(tokens[: k * bp]))
                return PrefixLocation(loc.replica, k)
        return None

    def forget(self, replica: str) -> int:
        """Drop every claim naming `replica` (death/teardown); returns
        how many were dropped."""
        dead = [d for d, loc in self._map.items()
                if loc.replica == replica]
        for d in dead:
            del self._map[d]
        return len(dead)
