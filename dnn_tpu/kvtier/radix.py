"""Radix index: a trie over block_len-aligned token-id chunks.

The paged prefix cache's OrderedDict (PRs 6/13) keyed FULL prefixes at
prompt_pad granularity — an exact-match LRU, so two prompts sharing 90%
of their tokens but diverging mid-chunk shared nothing, and every
cached prefix length was its own entry re-pinning the same blocks. The
radix index stores each block-sized token chunk ONCE as a trie node:

  * one node per KV pool block — `node.block` is the physical block id
    holding the K/V for this node's block_len positions; the token path
    from the root to the node IS the prefix those positions encode;
  * longest-prefix-match walks full chunks (`match`), then reports how
    many tokens of the NEXT (possibly partial) chunk agree with an
    existing child — the copy-on-write boundary candidate: the serving
    layer copies that ONE block and resumes prefill mid-block instead
    of recomputing it;
  * eviction is leaf-LRU (`evict_lru_leaf`): only leaves are evictable
    (an interior node's block is attended through every descendant's
    prefix), in least-recently-matched order. Refcount protection is
    the ALLOCATOR's job — evicting a node drops only the store's
    reference; blocks shared by live decode slots survive until those
    retire (dnn_tpu/runtime/paged_kvcache.BlockAllocator).

Pure host Python, no jax: the index never touches device memory — it
maps token bytes to block IDS; the store (kvtier/store.py) owns the
allocator bookkeeping and the serving layer owns the device programs.
Single-producer contract: all MUTATIONS (insert/evict/match's LRU
touch) happen on the pool's one worker thread, exactly like the
batcher's own host state; scrape-time readers only load counters
(`n_nodes`), which is GIL-atomic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["RadixIndex", "RadixNode", "chunk_key"]


def chunk_key(tokens: np.ndarray) -> bytes:
    """The trie edge key for one block_len token chunk — raw int32
    bytes (the dense path's OrderedDict used the same spelling)."""
    return np.ascontiguousarray(tokens, dtype=np.int32).tobytes()


class RadixNode:
    """One resident block: `chunk` (the block_len token ids), `block`
    (the physical pool block id the store holds one reference on),
    `children` keyed by the next chunk's bytes, `logit_row` (the
    model's logits AFTER this node's last token, when the insert had
    them — what lets an exactly-block-aligned full-prompt hit sample
    its first token without running a single chunk), and `origin`
    ("local" = prefilled here, "adopted" = migrated in from a sibling
    replica — the cross-replica hit accounting the kv_tier probe
    asserts reads this)."""

    __slots__ = ("chunk", "block", "children", "parent", "logit_row",
                 "origin", "lru", "obskey")

    def __init__(self, chunk: np.ndarray, block: int,
                 parent: "Optional[RadixNode]", *, origin: str = "local"):
        self.chunk = np.ascontiguousarray(chunk, dtype=np.int32)
        self.block = int(block)
        self.children: Dict[bytes, RadixNode] = {}
        self.parent = parent
        self.logit_row = None
        self.origin = origin
        self.lru = 0
        # path digest stamped by obs/kvlens.py at insert time — evicted
        # nodes are detached (parent=None), so the forensics key must be
        # captured while the path is still walkable; None when the lens
        # was off at birth (forensics degrade, eviction counts hold)
        self.obskey = None

    @property
    def depth(self) -> int:
        n, d = self, 0
        while n.parent is not None:
            n, d = n.parent, d + 1
        return d

    def __repr__(self):  # pragma: no cover — debugging aid
        return (f"RadixNode(block={self.block}, depth={self.depth}, "
                f"origin={self.origin}, leaf={not self.children})")


class RadixIndex:
    """The trie. `capacity` bounds RESIDENT NODES (= resident blocks;
    the `prefix_cache=N` constructor knob); `insert` evicts LRU leaves
    to stay inside it, `match` never allocates."""

    def __init__(self, block_len: int, capacity: int):
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.block_len = int(block_len)
        self.capacity = int(capacity)
        # sentinel root: no chunk, no block — never evicted, never
        # counted
        self.root = RadixNode(np.zeros((0,), np.int32), -1, None)
        self._nodes: List[RadixNode] = []
        self._tick = 0
        self._park = 0  # decreasing: newly INSERTED nodes park at the
        # LRU end, newest-first — only a MATCH promotes. A burst of
        # novel prompts then cycles its own one-shot nodes through the
        # eviction slot instead of unraveling the hot shared-prefix
        # path (the dense LRU's scan-resistant insertion, kept)

    @property
    def n_nodes(self) -> int:
        return len(self._nodes)

    def _touch(self, node: RadixNode):
        self._tick += 1
        node.lru = self._tick

    # -- lookup --------------------------------------------------------

    def match(self, tokens: np.ndarray
              ) -> Tuple[List[RadixNode], int, Optional[RadixNode]]:
        """Longest-prefix match of `tokens` against the trie.

        Returns (matched_nodes, boundary_tokens, boundary_node):
        `matched_nodes` are the FULL-chunk matches in path order (their
        `.block` ids are the shared run); `boundary_node` is the child
        of the last match whose chunk agrees with the next, possibly
        partial, chunk of `tokens` on `boundary_tokens` > 0 leading
        tokens — the copy-on-write candidate. Matching touches the LRU
        clock on every node on the path (and the boundary)."""
        tokens = np.ascontiguousarray(tokens, dtype=np.int32)
        bp = self.block_len
        node = self.root
        matched: List[RadixNode] = []
        at = 0
        while at + bp <= tokens.size:
            child = node.children.get(chunk_key(tokens[at:at + bp]))
            if child is None:
                break
            matched.append(child)
            self._touch(child)
            node = child
            at += bp
        # boundary: the longest leading agreement between the REMAINING
        # tokens and any child chunk (ties broken by most tokens, then
        # most recently used — deterministic given the LRU history)
        tail = tokens[at:at + bp]
        best: Optional[RadixNode] = None
        best_n = 0
        if tail.size:
            for child in node.children.values():
                n = int(np.argmin(
                    np.concatenate([
                        child.chunk[:tail.size] == tail, [False]])))
                if n > best_n or (n == best_n and n > 0 and best is not
                                  None and child.lru > best.lru):
                    best, best_n = child, n
        if best is not None:
            self._touch(best)
        return matched, best_n, best

    # -- insert / evict ------------------------------------------------

    def insert(self, tokens: np.ndarray, blocks: List[int], *,
               logit_rows: Optional[dict] = None,
               origin: str = "local"
               ) -> Tuple[List[RadixNode], List[RadixNode]]:
        """Insert the full-chunk path for `tokens` (block-aligned; the
        ragged tail is ignored) mapped onto physical `blocks` (one per
        full chunk, path order). Existing nodes are reused — their
        blocks stay as-is and the corresponding entry of `blocks` is
        simply not referenced (the caller keeps ownership of it).

        `logit_rows` maps chunk INDEX (0-based along this path) -> the
        logits row after that chunk's last token; attached to the node
        (existing nodes only gain a row they lacked — a row is a pure
        function of the prefix, so overwriting is a no-op by value).

        `origin` is one provenance for every created node, or a
        per-chunk sequence (short sequences pad "local") — a re-insert
        of a path whose ADOPTED nodes were evicted under pressure must
        not launder them into local-origin blocks, or the
        cross-replica hit accounting decays with cache churn.

        Returns (created_nodes, evicted_nodes): the caller must take
        one allocator reference per created node's block and release
        one per evicted node's block (the store does both)."""
        tokens = np.ascontiguousarray(tokens, dtype=np.int32)
        bp = self.block_len
        n_full = tokens.size // bp
        if len(blocks) < n_full:
            raise ValueError(
                f"insert covers {n_full} full chunks but only "
                f"{len(blocks)} blocks were supplied")
        if isinstance(origin, str):
            def origin_at(_i):
                return origin
        else:
            origins = list(origin)

            def origin_at(i):
                return origins[i] if i < len(origins) else "local"
        created: List[RadixNode] = []
        evicted: List[RadixNode] = []
        node = self.root
        for i in range(n_full):
            chunk = tokens[i * bp:(i + 1) * bp]
            key = chunk_key(chunk)
            child = node.children.get(key)
            if child is None:
                while self.n_nodes >= self.capacity:
                    victim = self.evict_lru_leaf(protect=node)
                    if victim is None:
                        # nothing evictable (every leaf is on the path
                        # being built): stop extending — the prefix we
                        # DID insert is still valid
                        return created, evicted
                    evicted.append(victim)
                child = RadixNode(chunk, blocks[i], node,
                                  origin=origin_at(i))
                # scan-resistant: park below every matched node (the
                # newest park evicts first); promotion is match()'s job
                self._park -= 1
                child.lru = self._park
                node.children[key] = child
                self._nodes.append(child)
                created.append(child)
            if logit_rows and i in logit_rows \
                    and child.logit_row is None:
                child.logit_row = logit_rows[i]
            node = child
        return created, evicted

    def evict_lru_leaf(self, protect: Optional[RadixNode] = None
                       ) -> Optional[RadixNode]:
        """Detach and return the least-recently-matched LEAF (interior
        nodes are load-bearing for every descendant's prefix). `protect`
        (and its ancestors) are exempt — the path an in-progress insert
        is extending must not be evicted under it. Returns None when
        nothing is evictable. The caller releases the store's allocator
        reference on the returned node's block.

        Cost note: O(resident nodes) per eviction (one linear scan +
        a list remove). At the capacities this repo serves (tens to a
        few thousand blocks) the scan is microseconds on the worker
        thread; a make-room burst evicting hundreds of leaves in one
        admission is the pathological corner — if profiles ever show
        it, the fix is an ordered leaf index maintained on park/touch,
        not a bigger scan."""
        protected = set()
        n = protect
        while n is not None:
            protected.add(id(n))
            n = n.parent
        victim: Optional[RadixNode] = None
        for node in self._nodes:
            if node.children or id(node) in protected:
                continue
            if victim is None or node.lru < victim.lru:
                victim = node
        if victim is None:
            return None
        self._nodes.remove(victim)
        parent = victim.parent
        if parent is not None:
            parent.children.pop(chunk_key(victim.chunk), None)
        victim.parent = None
        return victim

    def walk(self):
        """Every resident node (unordered) — gauges and tests."""
        return list(self._nodes)
