"""PrefixStore: the radix index bound to the paged BlockAllocator.

Ownership protocol (the invariant every test in tests/test_kvtier.py
leans on): the store holds EXACTLY ONE allocator reference per resident
node's block — taken at insert, released at eviction. Live decode slots
hold their own references (ContinuousBatcher's admission refs shared
blocks before allocating tails), so evicting an entry whose blocks a
slot still shares frees nothing until the slot retires: eviction is
leaf-LRU *under refcount protection*, with the refcount living where it
always has (paged_kvcache.BlockAllocator).

The store is a HOST index: it never touches device memory. The serving
layer (runtime/serving.py) owns the device programs — block gather for
lookup-hit rows, the one-block copy behind the COW boundary, the
install that populates blocks after a prefill — and calls back into
`lookup` / `insert` / `evict_one` from the pool's single worker thread.
Scrape-time readers (`n_blocks`, the counters) only load ints.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from dnn_tpu.kvtier.radix import RadixIndex, RadixNode

__all__ = ["PrefixStore", "PrefixHit"]


@dataclasses.dataclass
class PrefixHit:
    """One admission lookup's answer.

    `shared` — physical block ids of the full-chunk matches, path
    order (the caller refs them before touching the allocator again);
    `origins` — each shared block's provenance ("local" | "adopted"),
    same order; `cow_src`/`cow_tokens`/`cow_origin` — the boundary
    block candidate: `cow_tokens` leading tokens of the next partial
    chunk agree with the cached block `cow_src`, so copying that ONE
    block lets prefill resume mid-block (0 = no boundary sharing);
    `logit_row` — the stored logits after the last shared token,
    present only when the prompt is exactly the shared run (the
    full-hit fast path: zero chunks run).

    Lookup itself counts NOTHING: the admission may truncate the run,
    hold the request back, or fail — the caller reports what it
    actually reused via `note_reuse` (the counters behind the
    cross-replica ratio the kv_tier probe floors must never exceed
    blocks genuinely served)."""

    shared: List[int]
    origins: List[str]
    cow_src: int = -1
    cow_tokens: int = 0
    cow_origin: str = "local"
    logit_row: Optional[object] = None

    @property
    def n_shared(self) -> int:
        return len(self.shared)

    def remote_used(self, n_shared_used: int, cow_used: bool) -> int:
        """Adopted-origin blocks among the FIRST `n_shared_used`
        shared blocks (+ the COW boundary when used)."""
        n = sum(1 for o in self.origins[:n_shared_used]
                if o == "adopted")
        if cow_used and self.cow_origin == "adopted":
            n += 1
        return n


class PrefixStore:
    """See module docstring. `capacity` = resident blocks (the
    `prefix_cache=N` knob)."""

    def __init__(self, allocator, block_len: int, capacity: int):
        self.allocator = allocator
        self.block_len = int(block_len)
        self.index = RadixIndex(block_len, capacity)
        # counters the serving gauges read (GIL-atomic int loads)
        self.block_hits = 0          # blocks reused across all lookups
        self.remote_block_hits = 0   # ... of adopted (migrated) origin
        self.evictions = 0
        # optional memory-economy observer (obs/kvlens.py), attached by
        # the serving layer when the obs gate is on. Every hook below is
        # one `is not None` test when absent — the <2% contract's cost
        # when observability is off.
        self.lens = None

    # -- scrape-side ---------------------------------------------------

    @property
    def n_blocks(self) -> int:
        """Resident blocks (= nodes): the kvtier residency gauge."""
        return self.index.n_nodes

    # -- worker-side ---------------------------------------------------

    def lookup(self, prompt: np.ndarray) -> PrefixHit:
        """Longest-prefix match for an arriving prompt (no counter
        side effects — `note_reuse` records what admission actually
        used)."""
        matched, cow_n, cow_node = self.index.match(prompt)
        if self.lens is not None:
            # admission demand only: coverage()/nodes_for() serve the
            # adopt/export paths, not arriving traffic, and would skew
            # the reuse-distance sample if fed here
            self.lens.on_access(prompt, n_resident=len(matched))
        logit_row = None
        bp = self.block_len
        p = int(np.asarray(prompt).size)
        if matched and p == len(matched) * bp:
            logit_row = matched[-1].logit_row
        has_cow = cow_n > 0 and cow_node is not None
        return PrefixHit(
            shared=[n.block for n in matched],
            origins=[n.origin for n in matched],
            cow_src=cow_node.block if has_cow else -1,
            cow_tokens=cow_n if has_cow else 0,
            cow_origin=cow_node.origin if has_cow else "local",
            logit_row=logit_row)

    def note_reuse(self, n_blocks: int, n_remote: int,
                   cow: bool = False):
        """Admission succeeded reusing `n_blocks` resident blocks, of
        which `n_remote` were adopted from a sibling — the counters
        the gauges and the kv_tier probe read. `cow` marks that the
        reuse included the boundary copy-on-write block (lifecycle
        forensics; the counters are unchanged by it)."""
        self.block_hits += int(n_blocks)
        self.remote_block_hits += int(n_remote)
        if self.lens is not None:
            self.lens.on_share(int(n_blocks), int(n_remote), cow=cow)

    def insert(self, tokens: np.ndarray, blocks: List[int], *,
               logit_rows: Optional[dict] = None,
               origin="local") -> int:
        """Insert the full-chunk path for `tokens` over physical
        `blocks` (one per full chunk). The store refs every NEWLY
        resident block and frees every evicted one — the caller's own
        references are untouched (a live slot keeps its blocks; a
        staging path frees its transient refs afterwards). Returns the
        number of nodes created."""
        created, evicted = self.index.insert(
            tokens, blocks, logit_rows=logit_rows, origin=origin)
        if created:
            self.allocator.ref([n.block for n in created])
            if self.lens is not None:
                self.lens.on_insert(tokens, created, origin=origin)
        if evicted:
            self._release(evicted, cause="capacity")
        return len(created)

    def evict_one(self, cause: str = "capacity") -> bool:
        """Evict the LRU leaf (admission's make-room loop). False when
        nothing is evictable. `cause` attributes the eviction for
        forensics: "capacity" (pressure) vs housekeeping causes."""
        victim = self.index.evict_lru_leaf()
        if victim is None:
            return False
        self._release([victim], cause=cause)
        return True

    def coverage(self, tokens: np.ndarray) -> int:
        """Full blocks of `tokens` already resident — the adopt path's
        dedup (pull only what is missing). LRU-touching like any
        match."""
        matched, _n, _node = self.index.match(tokens)
        return len(matched)

    def nodes_for(self, tokens: np.ndarray) -> List[RadixNode]:
        """The matched full-chunk nodes for `tokens` (export reads
        their blocks + logit rows)."""
        matched, _n, _node = self.index.match(tokens)
        return matched

    def _release(self, nodes: List[RadixNode], cause: str = "capacity"):
        self.allocator.free([n.block for n in nodes])
        self.evictions += len(nodes)
        if self.lens is not None:
            self.lens.on_evict(
                [getattr(n, "obskey", None) for n in nodes], cause=cause)

    def clear(self):
        """Release every resident block (teardown / tests)."""
        while self.evict_one(cause="clear"):
            pass
