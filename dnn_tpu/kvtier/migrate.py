"""Block migration: move a prefix's KV blocks between replicas.

Three layers, composing the PR 7 transport lessons with the PR 12
handoff idiom — but PER BLOCK, not pack-the-whole-row:

  * the WIRE CODEC (`pack_blocks` / `unpack_blocks`): one uint8 tensor
    = magic + length-prefixed JSON header + raw block leaves in C
    order. Quantized pools migrate AS-IS: int8 K/V ships at 1 byte per
    element and int4 ships NIBBLE-PACKED at half a byte (two values
    per byte — the 4–8x wire win the quantized-KV ladder bought now
    pays on the network too; note the row handoff of PR 12 REJECTS
    int4 outright — block migration supersedes it there). bfloat16
    ships viewed as uint16, exactly like handoff.py.

  * the LEASE state machine (`Lease` / `LeaseTable`, donor side): a
    staged export is a lease — `offered` (bytes staged, optionally
    published to a shm segment) -> `pulling` (the adopter started a
    grpc fetch) -> `adopted` (adopter acked ingest) ->  `released`
    (donor freed the staging). TTL expiry from offered/pulling lands
    in `expired`, whose ONLY exit is the sweep's `lease_reclaim` back
    to released — delete that edge and staged payloads leak forever,
    which is exactly what the protocol gate's PRO002 check reports
    (analysis/protocol.KVLEASE declares this table; both directions
    are model-checked in CI). A dying donor can never corrupt an
    adopter: the adopter ingests only fully-parsed, geometry-verified
    payloads into FRESH local blocks, and a lease that dies mid-pull
    simply expires — the adopter re-prefills, loud, via a
    `kvtier_fallback` flight event.

  * the RUNGS (`publish_shm` / `attach_shm` / `pull_blocks`): on the
    same host the payload crosses as one memcpy through a POSIX shared
    -memory segment whose first bytes carry the offer's nonce — the
    adopter PROVES it attached the right segment by echoing the nonce
    check, the PR 7 proof-carrying idiom; anything else (attach
    failure, nonce mismatch, cross-host) falls back to the grpc fetch
    rung, recorded as a `kvtier_shm_fallback` flight event. `auto`
    degradation, never silent failure.

Pure numpy + stdlib (+ ml_dtypes for bf16 payloads) — no device work
anywhere: the only jax-adjacent import is the flight recorder the rest
of the control plane already uses.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from dnn_tpu import obs

__all__ = ["pack_blocks", "unpack_blocks", "MigrateFormatError",
           "Lease", "LeaseTable", "publish_shm", "attach_shm",
           "pull_blocks", "DEFAULT_LEASE_TTL_S"]

_MAGIC = b"dnnkvt1\n"
_NONCE_BYTES = 16
DEFAULT_LEASE_TTL_S = 30.0

# dtypes shipped as themselves; registered views for the rest
_VIEW_AS = {"bfloat16": "uint16"}


class MigrateFormatError(ValueError):
    """A payload this module cannot pack or parse — corrupt bytes, an
    unsupported dtype, or a header/byte-length mismatch. A ValueError
    so server endpoints map it to INVALID_ARGUMENT."""


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import ml_dtypes  # jax dependency; only needed for bf16 payloads

    try:
        return np.dtype(getattr(ml_dtypes, name))
    except AttributeError:
        raise MigrateFormatError(
            f"kvtier payload names unknown dtype {name!r}") from None


def _pack_nibbles(arr: np.ndarray) -> bytes:
    """int8 VALUES in [-8, 7] -> two's-complement nibbles, two per
    byte (even index = low nibble). Odd element counts pad one zero
    nibble; the header's shape recovers the true count."""
    flat = np.ascontiguousarray(arr, np.int8).reshape(-1)
    if flat.size % 2:
        flat = np.concatenate([flat, np.zeros((1,), np.int8)])
    u = (flat.astype(np.int16) & 0xF).astype(np.uint8)
    return (u[0::2] | (u[1::2] << 4)).tobytes()


def _unpack_nibbles(raw: np.ndarray, n: int) -> np.ndarray:
    """Inverse of _pack_nibbles -> n int8 values in [-8, 7]."""
    lo = (raw & 0xF).astype(np.int8)
    hi = ((raw >> 4) & 0xF).astype(np.int8)
    out = np.empty((raw.size * 2,), np.int8)
    out[0::2], out[1::2] = lo, hi
    out = np.where(out > 7, out - 16, out).astype(np.int8)
    return out[:n]


def _leaf_dtype_name(fingerprint: dict, name: str, arr: np.ndarray
                     ) -> str:
    """The TRUE cache dtype of a leaf — int4 pools cross the host
    boundary as int8 values, so the fingerprint (not the host array)
    is the authority."""
    spec = (fingerprint or {}).get("leaves", {}).get(name)
    return spec[1] if spec else arr.dtype.name


def pack_blocks(payload: Dict) -> np.ndarray:
    """`ContinuousBatcher.kvtier_export`'s dict -> one 1-D uint8 wire
    tensor. Leaves ride raw C-order bytes; int4 leaves nibble-pack."""
    fp = payload.get("fingerprint") or {}
    tokens = np.ascontiguousarray(payload["tokens"], np.int32)
    chunks = [tokens.tobytes()]
    leaf_specs = {}
    for name in sorted(payload["leaves"]):
        arr = np.ascontiguousarray(payload["leaves"][name])
        true_dt = _leaf_dtype_name(fp, name, arr)
        if true_dt == "int4":
            wire = _pack_nibbles(arr)
            enc = "nibble"
        else:
            view = _VIEW_AS.get(true_dt)
            if view is not None:
                wire = arr.view(np.dtype(view)).tobytes()
            else:
                try:
                    np.dtype(true_dt)
                except TypeError:
                    raise MigrateFormatError(
                        f"cache dtype {true_dt!r} has no kvtier wire "
                        "form") from None
                wire = arr.tobytes()
            enc = "raw"
        chunks.append(wire)
        leaf_specs[name] = {"shape": list(arr.shape), "dtype": true_dt,
                            "enc": enc, "bytes": len(wire)}
    lr = payload.get("logit_rows") or {}
    lr_idx = sorted(int(i) for i in lr)
    lr_arr = (np.stack([np.asarray(lr[i], np.float32) for i in lr_idx])
              if lr_idx else np.zeros((0, 0), np.float32))
    chunks.append(np.ascontiguousarray(lr_arr).tobytes())
    header = json.dumps({
        "v": 1,
        "block_len": int(payload["block_len"]),
        "n_tokens": int(tokens.size),
        "fingerprint": fp,
        "leaves": leaf_specs,
        "logit_idx": lr_idx,
        "logit_shape": list(lr_arr.shape),
    }).encode()
    buf = b"".join([_MAGIC, len(header).to_bytes(4, "big"), header]
                   + chunks)
    return np.frombuffer(buf, np.uint8)


def unpack_blocks(buf) -> Dict:
    """Inverse of pack_blocks. Raises MigrateFormatError (a ValueError)
    on anything malformed — an adopter must answer INVALID_ARGUMENT,
    never ingest garbage blocks."""
    raw = np.asarray(buf, np.uint8).tobytes()
    if not raw.startswith(_MAGIC):
        raise MigrateFormatError(
            "not a kvtier block payload (bad magic) — was this tensor "
            "produced by pack_blocks?")
    at = len(_MAGIC)
    if len(raw) < at + 4:
        raise MigrateFormatError("kvtier payload truncated (no header)")
    hlen = int.from_bytes(raw[at:at + 4], "big")
    at += 4
    try:
        head = json.loads(raw[at:at + hlen].decode())
    except (ValueError, UnicodeDecodeError):
        raise MigrateFormatError(
            "kvtier header is not valid JSON") from None
    at += hlen
    body = memoryview(raw)
    n_tok = int(head["n_tokens"])
    if at + n_tok * 4 > len(body):
        raise MigrateFormatError("kvtier payload truncated (tokens)")
    tokens = np.frombuffer(body[at:at + n_tok * 4], np.int32)
    at += n_tok * 4
    leaves = {}
    for name in sorted(head.get("leaves", {})):
        spec = head["leaves"][name]
        n = int(spec["bytes"])
        if at + n > len(body):
            raise MigrateFormatError(
                f"kvtier payload truncated (leaf {name})")
        shape = tuple(spec["shape"])
        count = int(np.prod(shape)) if shape else 1
        wire = np.frombuffer(body[at:at + n], np.uint8)
        if spec.get("enc") == "nibble":
            arr = _unpack_nibbles(wire, count).reshape(shape)
        else:
            dt = _resolve_dtype(spec["dtype"])
            wire_dt = np.dtype(_VIEW_AS.get(spec["dtype"],
                                            spec["dtype"]))
            arr = np.frombuffer(body[at:at + n], wire_dt)
            if wire_dt != dt:
                arr = arr.view(dt)
            try:
                arr = arr.reshape(shape)
            except ValueError:
                raise MigrateFormatError(
                    f"kvtier leaf {name} bytes do not match shape "
                    f"{shape} dtype {spec['dtype']}") from None
        leaves[name] = arr
        at += n
    lr_shape = tuple(head.get("logit_shape") or (0, 0))
    lr_count = int(np.prod(lr_shape)) if lr_shape else 0
    lr_arr = np.frombuffer(body[at:at + lr_count * 4], np.float32)
    if lr_arr.size != lr_count:
        raise MigrateFormatError("kvtier payload truncated (logits)")
    lr_arr = lr_arr.reshape(lr_shape) if lr_count else lr_arr
    logit_rows = {int(i): lr_arr[j]
                  for j, i in enumerate(head.get("logit_idx", []))}
    return {"tokens": tokens, "block_len": int(head["block_len"]),
            "leaves": leaves, "logit_rows": logit_rows,
            "fingerprint": head.get("fingerprint") or {}}


# ----------------------------------------------------------------------
# shm rung: same-host zero-serialization block transfer
# ----------------------------------------------------------------------

#: segment names THIS process created (publish_shm): attach_shm must
#: not deregister those from the resource tracker — the creator's own
#: unlink still needs the registration (in-process attach = tests)
_OWN_SHM_NAMES: set = set()


def publish_shm(data: bytes) -> Optional[Tuple[str, str, object]]:
    """Stage `data` in a fresh POSIX shm segment: first _NONCE_BYTES
    hold a random nonce the adopter must verify (proof it attached THE
    offered segment, not a stale or hostile one — the PR 7 handshake
    idiom). Returns (name, nonce_hex, segment) or None when shm is
    unavailable on this platform."""
    try:
        from multiprocessing import shared_memory
    except ImportError:  # pragma: no cover — ancient platform
        return None
    nonce = secrets.token_bytes(_NONCE_BYTES)
    try:
        seg = shared_memory.SharedMemory(
            create=True, size=_NONCE_BYTES + len(data))
        seg.buf[:_NONCE_BYTES] = nonce
        seg.buf[_NONCE_BYTES:_NONCE_BYTES + len(data)] = data
    except OSError:  # pragma: no cover — /dev/shm full or missing
        return None
    _OWN_SHM_NAMES.add(seg.name)
    return seg.name, nonce.hex(), seg


def attach_shm(name: str, nonce_hex: str, nbytes: int) -> bytes:
    """Adopter-side memcpy out of the donor's segment. Verifies the
    nonce before reading a byte of payload; any failure raises (the
    caller falls back to the grpc fetch rung, loud)."""
    from multiprocessing import shared_memory

    seg = shared_memory.SharedMemory(name=name)
    if name not in _OWN_SHM_NAMES:
        # CPython registers ATTACHED segments with its resource
        # tracker as if it owned them; the DONOR owns and unlinks
        # this one, so deregister or the adopter's interpreter warns
        # about (and may try to clean) a segment that was never its
        # to free. Same-process attaches (tests) skip this — the
        # creator's unlink still needs its registration.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker internals vary by
            pass           # version; worst case is a shutdown warning
    try:
        if bytes(seg.buf[:_NONCE_BYTES]).hex() != nonce_hex:
            raise ValueError(
                f"shm segment {name} nonce mismatch — not the offered "
                "lease")
        return bytes(seg.buf[_NONCE_BYTES:_NONCE_BYTES + nbytes])
    finally:
        seg.close()


# ----------------------------------------------------------------------
# the lease state machine (donor side)
# ----------------------------------------------------------------------

class Lease:
    """One staged export. The lifecycle table is DECLARED in
    analysis/protocol.KVLEASE and model-checked both directions — edit
    the two together."""

    def __init__(self, lease_id: str, data: bytes, ttl_s: float):
        self.lease_id = lease_id
        self.data: Optional[bytes] = data
        self.nbytes = len(data)
        self.ttl_s = float(ttl_s)
        self.t_offer = time.monotonic()
        self.shm_name: Optional[str] = None
        self.shm_nonce: Optional[str] = None
        self._seg = None
        self.state = "offered"

    def _free(self):
        self.data = None
        if self._seg is not None:
            try:
                self._seg.close()
                self._seg.unlink()
            except OSError:  # pragma: no cover — already gone
                pass
            self._seg = None


class LeaseTable:
    """Donor-side staging: offers carry a TTL so an adopter that dies
    mid-pull can never pin staged payloads (or their shm segments)
    forever. Thread-safe — gRPC handler threads offer/fetch/ack, the
    worker's idle sweep expires."""

    def __init__(self, *, ttl_s: float = DEFAULT_LEASE_TTL_S,
                 max_leases: int = 16, use_shm: bool = True):
        self.ttl_s = float(ttl_s)
        self.max_leases = int(max_leases)
        self.use_shm = bool(use_shm)
        self._leases: "Dict[str, Lease]" = {}
        self._lock = threading.Lock()
        self._seq = 0

    def offer(self, data: bytes, *, ttl_s: Optional[float] = None
              ) -> dict:
        """Stage `data`; returns the offer meta the adopter needs:
        {lease, bytes, shm?, nonce?}. Publishes a shm segment when the
        platform has one — the adopter proves attachment via the
        nonce, or falls back to kvfetch."""
        with self._lock:
            self._seq += 1
            lease_id = f"L{os.getpid()}_{self._seq}"
            lease = Lease(lease_id, data, ttl_s or self.ttl_s)
            if self.use_shm:
                pub = publish_shm(data)
                if pub is not None:
                    lease.shm_name, lease.shm_nonce, lease._seg = pub
            self._leases[lease_id] = lease
            # bounded: expire the oldest past-capacity offer NOW (the
            # sweep would get it anyway; capacity must not wait for it)
            while len(self._leases) > self.max_leases:
                oldest = min(self._leases.values(),
                             key=lambda x: x.t_offer)
                self._expire(oldest)
        meta = {"lease": lease_id, "bytes": lease.nbytes}
        if lease.shm_name:
            meta["shm"] = lease.shm_name
            meta["nonce"] = lease.shm_nonce
        return meta

    def fetch(self, lease_id: str) -> bytes:
        """grpc rung: the adopter pulls the staged bytes. offered ->
        pulling. KeyError for unknown/expired leases (the adopter
        re-prefills, loud)."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.data is None:
                raise KeyError(lease_id)
            if lease.state == "offered":
                lease.state = "pulling"
                obs.flight.record("lease_pull", lease=lease_id,
                                  bytes=lease.nbytes)
            return lease.data

    def ack(self, lease_id: str) -> bool:
        """The adopter confirmed ingest: -> adopted, then the donor
        releases the staging immediately (-> released). False for
        unknown/expired leases (the ack raced the sweep — harmless,
        the adopter already holds the blocks)."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None or lease.state in ("expired", "released"):
                return False
            lease.state = "adopted"
            obs.flight.record("lease_adopt", lease=lease_id)
            lease.state = "released"
            lease._free()
            obs.flight.record("lease_release", lease=lease_id)
            return True

    def _expire(self, lease: Lease):
        # under _lock. expired is NOT terminal: its one exit is the
        # reclaim below — delete it and staged payloads (and their shm
        # segments) leak forever, the exact PRO002 shape the protocol
        # gate pins
        lease.state = "expired"
        obs.flight.record("lease_expire", lease=lease.lease_id,
                          bytes=lease.nbytes,
                          age_s=round(time.monotonic() - lease.t_offer,
                                      2),
                          cause="lease_reclaim")
        lease._free()
        lease.state = "released"
        obs.flight.record("lease_reclaim", lease=lease.lease_id,
                          cause="lease_reclaim")
        self._leases.pop(lease.lease_id, None)

    def sweep(self, now: Optional[float] = None) -> int:
        """Expire offers past their TTL; returns how many. Called from
        the serving worker's idle boundary (and before every offer)."""
        now = time.monotonic() if now is None else now
        n = 0
        with self._lock:
            for lease in list(self._leases.values()):
                if lease.state in ("offered", "pulling") \
                        and now - lease.t_offer > lease.ttl_s:
                    self._expire(lease)
                    n += 1
        return n

    @property
    def n_leases(self) -> int:
        return len(self._leases)

    def close(self):
        with self._lock:
            for lease in list(self._leases.values()):
                self._expire(lease)


# ----------------------------------------------------------------------
# adopter-side pull driver (negotiated rungs: shm -> grpc)
# ----------------------------------------------------------------------

def pull_blocks(client, tokens, *, timeout: float = 30.0) -> Dict:
    """Pull a prefix's blocks from a donor replica through `client`
    (a comm.client.NodeClient pointed at the donor): lease the export,
    move the bytes over the best provable rung (shm when the nonce
    checks out, else the grpc fetch), ack, unpack. Raises on any
    failure — the CALLER records `kvtier_fallback` and re-prefills;
    this function never fabricates blocks."""
    meta = client.kv_lease(tokens, timeout=timeout)
    lease_id = meta["lease"]
    data: Optional[bytes] = None
    if meta.get("shm"):
        try:
            data = attach_shm(meta["shm"], meta.get("nonce", ""),
                              int(meta["bytes"]))
        except Exception as e:  # noqa: BLE001 — cross-host / stale
            # segment / nonce mismatch: degrade to the grpc rung, loud
            obs.flight.record("kvtier_shm_fallback",
                              error=f"{type(e).__name__}: {e}"[:160])
    if data is None:
        data = client.kv_fetch(lease_id, timeout=timeout).tobytes()
    payload = unpack_blocks(np.frombuffer(data, np.uint8))
    payload["_wire_bytes"] = len(data)  # the on-the-wire price, for
    # the adopter's migrated-bytes gauges (nibble-packed int4 and int8
    # payloads price at their true half/one byte per element)
    try:
        client.kv_ack(lease_id, timeout=min(timeout, 5.0))
    except Exception:  # noqa: BLE001 — best-effort: the donor's TTL
        # sweep reclaims an unacked lease; the blocks are already ours
        pass
    return payload
