"""dnn_tpu.kvtier — the fleet-wide radix prefix/KV tier (ROADMAP item 2).

Four connected pieces:

  * `radix.py`    — a trie over block_len-aligned token-id chunks; pure
                    host data structure (no jax), one node per KV pool
                    block, leaf-LRU eviction under refcount protection;
  * `store.py`    — PrefixStore: binds the radix index to the paged
                    BlockAllocator (dnn_tpu/runtime/paged_kvcache.py),
                    owning one reference per resident block; the serving
                    pool (`ContinuousBatcher(kv="paged", prefix_cache=N)`)
                    consults it at admission — longest-prefix-match
                    returns a run of refcounted physical blocks,
                    divergence copy-on-writes only the boundary block;
  * `migrate.py`  — per-block migration between replicas: the packed
                    block wire format (int8/int4 quantized blocks
                    migrate as-is), the model-checked lease state
                    machine (offered/pulling/adopted/released/expired —
                    analysis/protocol.KVLEASE), and the shm/grpc rungs;
  * `directory.py`— the router's bounded which-replica-holds-which-prefix
                    map feeding prefix-aware placement
                    (dnn_tpu/control/router.py).

The serving integration lives in runtime/serving.py (admission +
stage/export/adopt) and runtime/lm_server.py (the kvstage/kvlease/
kvfetch/kvack/kvpull endpoints). `benchmarks/kv_tier_probe.py` is the
asserted contract.
"""

from dnn_tpu.kvtier.radix import RadixIndex, RadixNode  # noqa: F401
from dnn_tpu.kvtier.store import PrefixStore, PrefixHit  # noqa: F401

__all__ = ["RadixIndex", "RadixNode", "PrefixStore", "PrefixHit"]
