"""LLaMA-family decoder-only LM: RMSNorm, RoPE, SwiGLU, grouped-query
attention (GQA).

No counterpart exists in the reference (its only LM is the GPT-2 wrapper
family, /root/reference/partitions/gpt_model_parts.py); this module widens
the model zoo to the architecture most open-weight LMs ship today
(LLaMA 1/2/3, Mistral, Qwen2, TinyLlama — all this block, different
shapes). TPU-first choices:

  * separate q/k/v projections sized H*D and KV*D (GQA's point is the
    smaller KV projections and cache; a fused qkv matmul would erase the
    asymmetry) — all bias-free single matmuls on the MXU;
  * GQA attends GROUPED: q reshapes to (B, KV, G*T, D) so the score and
    value einsums run at KV heads with the group folded into the row dim
    — no repeat/materialization of K/V to H heads, on the forward AND on
    the cached decode path (the KV cache stores KV heads, which is the
    architecture's bandwidth win at decode time);
  * RoPE tables are computed per call from absolute positions (decode
    positions offset by the cache pointer) in f32, HF half-split
    convention (ops/attention.rope_cos_sin/apply_rope) so converted HF
    weights reproduce logits exactly;
  * pipeline partitioning, stacking, and the KV-cache decode reuse the
    same machinery as the GPT family (gpt.layer_ranges / prepare_stacked
    signatures, kvcache codecs), so every parallel runtime — stacked
    pipeline, dp x tp via generic specs, interleaved schedule — and the
    int8 weight/cache paths apply unchanged.

Param pytree (HF LlamaForCausalLM names map 1:1 — see
io/checkpoint.llama_params_from_state_dict):

  {"wte": {"embedding" (V, C)},
   "h_i": {"ln_1": {"scale"}, "attn": {"q","k","v","o": {"kernel"}},
           "ln_2": {"scale"}, "mlp": {"gate","up","down": {"kernel"}}},
   "ln_f": {"scale"}, "lm_head": {"kernel" (C, V)}}
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dnn_tpu.models import gpt
from dnn_tpu.ops.attention import apply_rope, merge_heads, rope_cos_sin, split_heads
from dnn_tpu.ops.nn import embedding, linear, rms_norm, silu
from dnn_tpu.registry import ModelSpec, StageSpec, register_model

_NEG_BIG = -1e30


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    block_size: int = 2048
    vocab_size: int = 32000
    n_layer: int = 22
    n_head: int = 32
    n_kv_head: int = 4
    n_embd: int = 2048
    d_ff: int = 5632
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    # Mistral-class sliding-window attention: each position attends only
    # the previous `sliding_window` positions (None = dense causal).
    # Dense forwards band-mask; cached decode either window-masks a
    # full-length cache (batcher/pipeline) or stores a rolling ring of
    # exactly `sliding_window` positions (solo generate) — both
    # attention-equivalent (runtime/kvcache.py docstring).
    sliding_window: Optional[int] = None
    # Long-context RoPE scaling (set block_size to the EXTENDED length):
    #   "linear" — positions divided by rope_scale before the tables
    #     (position interpolation; HF rope_scaling type "linear");
    #   "ntk" — theta multiplied by rope_scale^(d/(d-2)) (NTK-aware base
    #     stretch: high frequencies keep local resolution, low
    #     frequencies interpolate).
    # Every RoPE site goes through _rope_tables, so the dense forward,
    # cached/ring decode, batcher rows, and seq-parallel ring all scale
    # identically.
    rope_scaling: Optional[str] = None
    rope_scale: float = 1.0
    # Qwen2-class q/k/v projection biases (o and the MLP stay bias-free).
    # ops.nn.linear applies any "bias" leaf it finds, so the flag only
    # affects init and the HF config mapping — converted checkpoints
    # carry their biases regardless.
    attn_bias: bool = False
    # ---- Gemma-family architecture switches (all default off, so every
    # pre-Gemma preset is bit-identical to before they existed) ----
    # Gemma decouples head_dim from n_embd/n_head (e.g. 2048/8 heads but
    # d=256); None keeps the LLaMA relation.
    head_dim_override: Optional[int] = None
    # RMSNorm scales by (1 + w) — Gemma checkpoints store zero-centered
    # norm weights (ops.nn.rms_norm plus_one).
    norm_plus_one: bool = False
    # MLP gate nonlinearity: "silu" (LLaMA SwiGLU) or "gelu_tanh"
    # (Gemma GeGLU — torch gelu_pytorch_tanh == jax.nn.gelu approximate).
    mlp_act: str = "silu"
    # Tied input/output embeddings: params carry NO lm_head leaf; head()
    # projects through wte.embedding.T (true weight sharing — one copy in
    # HBM, and a training gradient that flows to the single table).
    tie_word_embeddings: bool = False
    # Gemma scales token embeddings by sqrt(n_embd) at input.
    embed_scale: bool = False
    # Gemma-2: attention scores divide by sqrt(query_scale) instead of
    # sqrt(head_dim) (HF query_pre_attn_scalar). Folded into q after RoPE
    # (q *= sqrt(head_dim/query_scale)) so every attention path — dense,
    # cached, per-row — inherits it through its existing 1/sqrt(d).
    query_scale: Optional[float] = None
    # Gemma-2 logit softcaps: s -> cap * tanh(s / cap) on attention
    # scores (before masking) and on the final lm_head logits.
    attn_softcap: Optional[float] = None
    final_softcap: Optional[float] = None
    # Gemma-2 block shape: RMSNorms AFTER attention and after the MLP
    # (applied to the branch output before its residual add), in addition
    # to the usual pre-norms — param leaves post_ln_1 / post_ln_2.
    post_norms: bool = False
    # Gemma-2 alternating attention: EVEN layers use sliding_window,
    # ODD layers attend globally (matches HF Gemma2's layer pattern).
    # Implemented by threading a per-layer window through the block scan
    # (kvcache._KernelDispatch docstring); a global layer's entry is
    # block_size, which makes the band's lower bound vacuous.
    alt_window: bool = False
    # ---- Phi-family architecture switches (all default off) ----
    # LayerNorm (scale + bias, like GPT-2) instead of RMSNorm at every
    # norm site; rms_eps doubles as the LayerNorm eps.
    layer_norm: bool = False
    # Parallel residual (Phi/GPT-J): attention AND MLP both read the
    # SAME ln_1 output; y = x + attn(h) + mlp(h). No ln_2 exists.
    parallel_block: bool = False
    # Partial rotary (Phi): only the first `rotary_dim` dims of each
    # head rotate; the rest pass through untouched. None = full head.
    rotary_dim: Optional[int] = None
    # Phi puts biases on EVERY projection (o/dense, the MLP pair, and
    # lm_head) — attn_bias covers q/k/v alone (Qwen2).
    dense_bias: bool = False
    # False = the plain 2-layer MLP (fc1 -> act -> fc2; params carry
    # "up"/"down" only, no "gate") instead of the gated SwiGLU/GeGLU.
    mlp_gated: bool = True
    # Qwen3/OLMo-2-class q/k RMSNorm BEFORE RoPE — the training
    # -stability recipe replacing qkv biases. Width "head" (Qwen3):
    # each head's D-vector norms independently (weights (head_dim,));
    # "proj" (OLMo-2): the FULL projected vector norms jointly across
    # heads (weights (H*D,)/(KV*D,)). Leaves attn.q_norm/k_norm.
    qk_norm: bool = False
    qk_norm_width: str = "head"
    # False (OLMo-2): NO pre-norms — attention and the MLP read the RAW
    # residual stream, and only the post-branch norms exist (requires
    # post_norms=True; blocks carry post_ln_1/post_ln_2 but no
    # ln_1/ln_2 leaves).
    pre_norm: bool = True

    def __post_init__(self):
        if self.parallel_block and self.post_norms:
            raise ValueError(
                "parallel_block (Phi) and post_norms (Gemma-2) describe "
                "incompatible residual structures")
        if not self.pre_norm and (not self.post_norms
                                  or self.parallel_block):
            raise ValueError(
                "pre_norm=False (OLMo-2) requires post_norms=True and a "
                "sequential block — without pre-norms the post-branch "
                "norms are the only normalization")
        if self.qk_norm_width not in ("head", "proj"):
            raise ValueError(
                f"qk_norm_width must be 'head' or 'proj', got "
                f"{self.qk_norm_width!r}")
        if self.rotary_dim is not None and (
                self.rotary_dim % 2 or not
                0 < self.rotary_dim <= self.head_dim):
            raise ValueError(
                f"rotary_dim must be an even value in (0, head_dim="
                f"{self.head_dim}], got {self.rotary_dim}")

    @property
    def head_dim(self):
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.n_embd // self.n_head

    def default_ffn(self, compute_dtype=None):
        """The config's MLP-override hook, resolved by every runtime
        entry point when no explicit `ffn` is passed (forward_with_cache,
        make_apply*, make_hidden_stacked, LlamaFamilyRows) — so
        dispatch-by-config call sites (beam, speculative, embeddings)
        work for MoE subclasses without knowing about them. None = the
        dense gated MLP; MixtralConfig (models/llama_moe.py) overrides
        this to return its expert hook."""
        return None


PRESETS = {
    # TinyLlama-1.1B shape — the smallest real open-weight GQA model
    "tinyllama-1.1b": LlamaConfig(),
    # LLaMA-2-7B shape (MHA: kv == q heads)
    "llama2-7b": LlamaConfig(block_size=4096, n_layer=32, n_head=32,
                             n_kv_head=32, n_embd=4096, d_ff=11008),
    # LLaMA-3-8B shape (GQA 4:1, big vocab, long rope)
    "llama3-8b": LlamaConfig(block_size=8192, vocab_size=128256, n_layer=32,
                             n_head=32, n_kv_head=8, n_embd=4096, d_ff=14336,
                             rope_theta=500000.0),
    # tiny config for tests / CPU-mesh CI (GQA 2:1, 4 layers)
    "llama-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                              n_head=4, n_kv_head=2, n_embd=64, d_ff=128),
    # Mistral-7B-v0.1 shape: the LLaMA block with GQA 4:1 and a 4096-token
    # sliding window (the architecture's long-context claim: cache and
    # attention cost are O(window), not O(seq))
    "mistral-7b": LlamaConfig(block_size=32768, vocab_size=32000,
                              n_layer=32, n_head=32, n_kv_head=8,
                              n_embd=4096, d_ff=14336,
                              rope_theta=10000.0, sliding_window=4096),
    # tiny sliding-window config for tests (window far below block_size
    # so CI exercises the wrap)
    "mistral-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                                n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                                sliding_window=16),
    # Qwen2-7B shape: the LLaMA block with q/k/v biases, GQA 7:1, long
    # rope base
    "qwen2-7b": LlamaConfig(block_size=32768, vocab_size=152064,
                            n_layer=28, n_head=28, n_kv_head=4,
                            n_embd=3584, d_ff=18944,
                            rope_theta=1_000_000.0, rms_eps=1e-6,
                            attn_bias=True),
    # tiny biased config for tests
    "qwen2-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                              n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                              attn_bias=True),
    # Gemma-2B shape: (1+w) RMSNorm, GeGLU, tied + sqrt(C)-scaled
    # embeddings, MQA with head_dim decoupled from n_embd/n_head
    "gemma-2b": LlamaConfig(block_size=8192, vocab_size=256000,
                            n_layer=18, n_head=8, n_kv_head=1,
                            n_embd=2048, d_ff=16384,
                            head_dim_override=256, rms_eps=1e-6,
                            norm_plus_one=True, mlp_act="gelu_tanh",
                            tie_word_embeddings=True, embed_scale=True),
    # Gemma-7B shape (MHA, same block recipe)
    "gemma-7b": LlamaConfig(block_size=8192, vocab_size=256000,
                            n_layer=28, n_head=16, n_kv_head=16,
                            n_embd=3072, d_ff=24576,
                            head_dim_override=256, rms_eps=1e-6,
                            norm_plus_one=True, mlp_act="gelu_tanh",
                            tie_word_embeddings=True, embed_scale=True),
    # tiny Gemma-1 config for tests (MQA + head_dim override exercised)
    "gemma-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                              n_head=4, n_kv_head=1, n_embd=64, d_ff=128,
                              head_dim_override=32, rms_eps=1e-6,
                              norm_plus_one=True, mlp_act="gelu_tanh",
                              tie_word_embeddings=True, embed_scale=True),
    # Gemma-2-9B shape: Gemma block + post-norms, logit softcaps,
    # query_pre_attn_scalar, alternating 4096-window/global layers
    "gemma2-9b": LlamaConfig(block_size=8192, vocab_size=256000,
                             n_layer=42, n_head=16, n_kv_head=8,
                             n_embd=3584, d_ff=14336,
                             head_dim_override=256, rms_eps=1e-6,
                             norm_plus_one=True, mlp_act="gelu_tanh",
                             tie_word_embeddings=True, embed_scale=True,
                             post_norms=True, query_scale=256.0,
                             attn_softcap=50.0, final_softcap=30.0,
                             sliding_window=4096, alt_window=True),
    # tiny Gemma-2 config for tests: window far below block_size and
    # query_scale != head_dim so every switch actually acts
    "gemma2-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                               n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                               head_dim_override=32, rms_eps=1e-6,
                               norm_plus_one=True, mlp_act="gelu_tanh",
                               tie_word_embeddings=True, embed_scale=True,
                               post_norms=True, query_scale=64.0,
                               attn_softcap=50.0, final_softcap=30.0,
                               sliding_window=16, alt_window=True),
    # Phi-2 shape: parallel residual block (attn + MLP both read ln_1's
    # output), biased LayerNorms, partial rotary (32 of 80 head dims),
    # plain gelu MLP, biases on every projection incl. lm_head
    "phi-2": LlamaConfig(block_size=2048, vocab_size=51200, n_layer=32,
                         n_head=32, n_kv_head=32, n_embd=2560,
                         d_ff=10240, rms_eps=1e-5, layer_norm=True,
                         parallel_block=True, rotary_dim=32,
                         attn_bias=True, dense_bias=True,
                         mlp_gated=False, mlp_act="gelu_tanh"),
    # tiny Phi config for tests (partial_rotary_factor 0.5 on 16-dim
    # heads so the rotate/pass-through split actually acts)
    "phi-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                            n_head=4, n_kv_head=4, n_embd=64, d_ff=128,
                            rms_eps=1e-5, layer_norm=True,
                            parallel_block=True, rotary_dim=8,
                            attn_bias=True, dense_bias=True,
                            mlp_gated=False, mlp_act="gelu_tanh"),
    # Qwen3-8B shape: the LLaMA block with per-head q/k RMSNorm
    # (qk_norm — replaces Qwen2's projection biases), GQA 4:1, decoupled
    # head_dim, long rope base
    "qwen3-8b": LlamaConfig(block_size=40960, vocab_size=151936,
                            n_layer=36, n_head=32, n_kv_head=8,
                            n_embd=4096, d_ff=12288,
                            head_dim_override=128,
                            rope_theta=1_000_000.0, rms_eps=1e-6,
                            qk_norm=True),
    # tiny qk-norm config for tests
    "qwen3-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                              n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                              head_dim_override=32, rms_eps=1e-6,
                              qk_norm=True),
    # OLMo-2-7B shape: POST-norm-only block (attention/MLP read the raw
    # residual stream; each branch output norms before its residual
    # add) + full-projection-width q/k norms
    "olmo2-7b": LlamaConfig(block_size=4096, vocab_size=100352,
                            n_layer=32, n_head=32, n_kv_head=32,
                            n_embd=4096, d_ff=11008,
                            rope_theta=500000.0, rms_eps=1e-6,
                            qk_norm=True, qk_norm_width="proj",
                            pre_norm=False, post_norms=True),
    # tiny OLMo-2 config for tests (GQA so the KV-width k_norm acts)
    "olmo2-test": LlamaConfig(block_size=64, vocab_size=256, n_layer=4,
                              n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                              rms_eps=1e-5, qk_norm=True,
                              qk_norm_width="proj", pre_norm=False,
                              post_norms=True),
}


def layer_windows(cfg: LlamaConfig):
    """Per-layer sliding-window array for alternating-attention configs:
    (L,) int32, cfg.sliding_window on EVEN layers, block_size (a vacuous
    band bound — positions never reach it) on ODD/global layers. None for
    uniform-attention configs, which keep the static codec window."""
    if not cfg.alt_window:
        return None
    if cfg.sliding_window is None:
        # silently returning None would make every layer attend globally —
        # a misconfigured Gemma-2-style preset must fail loudly, not degrade
        raise ValueError(
            "alt_window=True requires sliding_window to be set: alternating "
            "window/global layers need a window width for the even layers")
    return jnp.asarray(
        [cfg.sliding_window if i % 2 == 0 else cfg.block_size
         for i in range(cfg.n_layer)], jnp.int32)


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def _kernel(key, shape, dtype, std=0.02):
    return {"kernel": (jax.random.normal(key, shape) * std).astype(dtype)}


def init_block(key, cfg: LlamaConfig, dtype=jnp.float32, *,
               include_mlp: bool = True):
    """`include_mlp=False` builds the attention/norm half only — MoE
    families (llama_moe) add their expert stacks instead of allocating
    dense MLP weights just to delete them (22 GB of transient garbage at
    mixtral-8x7b scale)."""
    c, d = cfg.n_embd, cfg.head_dim
    ks = jax.random.split(key, 7)

    def _qkv(k, shape):
        p = _kernel(k, shape, dtype)
        if cfg.attn_bias:
            p["bias"] = jnp.zeros((shape[-1],), dtype)
        return p

    # Gemma norms init at ZERO ((1+w) scaling makes 0 the identity);
    # plain RMSNorm inits at one. LayerNorm (Phi) adds a bias leaf.
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones

    def _norm_p(shape):
        p = {"scale": norm_init(shape, dtype)}
        if cfg.layer_norm:
            p["bias"] = jnp.zeros(shape, dtype)
        return p

    def _dense(k, shape, std=0.02):
        p = _kernel(k, shape, dtype, std=std)
        if cfg.dense_bias:  # Phi biases every projection
            p["bias"] = jnp.zeros((shape[-1],), dtype)
        return p

    blk = {
        "ln_1": _norm_p((c,)),
        "attn": {
            "q": _qkv(ks[0], (c, cfg.n_head * d)),
            "k": _qkv(ks[1], (c, cfg.n_kv_head * d)),
            "v": _qkv(ks[2], (c, cfg.n_kv_head * d)),
            "o": _dense(ks[3], (cfg.n_head * d, c),
                        std=0.02 / (2 * cfg.n_layer) ** 0.5),
        },
    }
    if cfg.qk_norm:
        # "head" (Qwen3): per-head over head_dim; "proj" (OLMo-2): the
        # full projected width, jointly across heads
        qn = d if cfg.qk_norm_width == "head" else cfg.n_head * d
        kn = d if cfg.qk_norm_width == "head" else cfg.n_kv_head * d
        blk["attn"]["q_norm"] = {"scale": jnp.ones((qn,), dtype)}
        blk["attn"]["k_norm"] = {"scale": jnp.ones((kn,), dtype)}
    if not cfg.parallel_block:  # Phi's parallel block has ONE norm
        blk["ln_2"] = _norm_p((c,))
    if not cfg.pre_norm:  # OLMo-2: only the post-branch norms exist
        del blk["ln_1"]
        del blk["ln_2"]
    if include_mlp:
        if cfg.mlp_gated:
            blk["mlp"] = {
                "gate": _kernel(ks[4], (c, cfg.d_ff), dtype),
                "up": _kernel(ks[5], (c, cfg.d_ff), dtype),
                "down": _kernel(ks[6], (cfg.d_ff, c), dtype,
                                std=0.02 / (2 * cfg.n_layer) ** 0.5),
            }
        else:  # Phi plain MLP: fc1 -> act -> fc2
            blk["mlp"] = {
                "up": _dense(ks[5], (c, cfg.d_ff)),
                "down": _dense(ks[6], (cfg.d_ff, c),
                               std=0.02 / (2 * cfg.n_layer) ** 0.5),
            }
    if cfg.post_norms:
        blk["post_ln_1"] = _norm_p((c,))
        blk["post_ln_2"] = _norm_p((c,))
    return blk


def init(rng, cfg: LlamaConfig = PRESETS["llama-test"], dtype=jnp.float32,
         *, include_mlp: bool = True):
    keys = jax.random.split(rng, cfg.n_layer + 3)
    c = cfg.n_embd
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones
    ln_f = {"scale": norm_init((c,), dtype)}
    if cfg.layer_norm:
        ln_f["bias"] = jnp.zeros((c,), dtype)
    params = {
        "wte": {"embedding": (jax.random.normal(keys[0], (cfg.vocab_size, c))
                              * 0.02).astype(dtype)},
        "ln_f": ln_f,
    }
    if not cfg.tie_word_embeddings:
        # tied configs carry NO lm_head leaf — head() projects through
        # wte.embedding.T (one table in HBM, shared gradient)
        params["lm_head"] = _kernel(keys[1], (c, cfg.vocab_size), dtype)
        if cfg.dense_bias:  # Phi: lm_head carries a bias too
            params["lm_head"]["bias"] = jnp.zeros((cfg.vocab_size,), dtype)
    for i in range(cfg.n_layer):
        params[f"h_{i}"] = init_block(keys[2 + i], cfg, dtype,
                                      include_mlp=include_mlp)
    return params


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _rope_tables(cfg: LlamaConfig, positions):
    """cos/sin at `positions` with the config's long-context scaling
    applied — the ONE place scaling happens, shared by every attention
    path (dense, cached decode, batcher rows, seq-parallel ring)."""
    theta = cfg.rope_theta
    d = cfg.rotary_dim or cfg.head_dim  # partial rotary: narrow tables
    if cfg.rope_scaling is None:
        if cfg.rope_scale != 1.0:
            # the likely long-context typo: factor set, type forgotten —
            # serving an unscaled model here would silently collapse
            # quality past the trained range
            raise ValueError(
                f"rope_scale={cfg.rope_scale} has no effect without "
                "rope_scaling='linear' or 'ntk'")
        return rope_cos_sin(positions, d, theta=theta)
    if cfg.rope_scaling not in ("linear", "ntk"):
        raise ValueError(
            f"unknown rope_scaling {cfg.rope_scaling!r} "
            "(expected 'linear' or 'ntk')")
    if cfg.rope_scale == 1.0:
        return rope_cos_sin(positions, d, theta=theta)
    if cfg.rope_scale < 1.0:
        raise ValueError(f"rope_scale must be >= 1, got {cfg.rope_scale}")
    if cfg.rope_scaling == "linear":
        positions = positions.astype(jnp.float32) / cfg.rope_scale
    else:  # "ntk"
        theta = theta * cfg.rope_scale ** (d / (d - 2))
    return rope_cos_sin(positions, d, theta=theta)


def _norm(p, x, cfg: LlamaConfig):
    """The family's norm: RMSNorm with cfg.rms_eps ((1+w) scaling for
    Gemma, norm_plus_one) — or biased LayerNorm for Phi-class configs
    (layer_norm). EVERY norm site in this module goes through here."""
    if cfg.layer_norm:
        from dnn_tpu.ops.nn import layer_norm

        return layer_norm(p, x, eps=cfg.rms_eps)
    return rms_norm(p, x, eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)


def _mlp_act(cfg: LlamaConfig):
    if cfg.mlp_act == "silu":
        return silu
    if cfg.mlp_act == "gelu_tanh":  # Gemma GeGLU (gelu_pytorch_tanh)
        from dnn_tpu.ops.nn import gelu
        return gelu
    raise ValueError(f"unknown mlp_act {cfg.mlp_act!r}")


def _q_rescale(q, cfg: LlamaConfig):
    """Fold Gemma-2's query_pre_attn_scalar into q: every attention path
    divides scores by sqrt(head_dim), so scaling q by
    sqrt(head_dim/query_scale) makes the effective divisor
    sqrt(query_scale) with zero per-path plumbing."""
    if cfg.query_scale is not None:
        q = q * jnp.asarray((cfg.head_dim / cfg.query_scale) ** 0.5, q.dtype)
    return q


def _rope_apply(x, cos, sin, cfg: LlamaConfig):
    """apply_rope with the config's partial-rotary slice (Phi): only the
    first rotary_dim dims of each head rotate, the rest pass through.
    EVERY q/k rotation site in this module goes through here — the
    partial slice must never diverge between the dense forward, the
    cached decode, batcher rows, verify rows, and the seq-parallel
    paths."""
    if cfg.rotary_dim is None:
        return apply_rope(x, cos, sin)
    rot = apply_rope(x[..., :cfg.rotary_dim], cos, sin)
    return jnp.concatenate([rot, x[..., cfg.rotary_dim:]], axis=-1)


def _pre_normed(bp, x, cfg: LlamaConfig):
    """The block input the branches read: ln_1(x) for pre-norm blocks
    (LLaMA and every descendant), the RAW residual stream for OLMo-2's
    post-norm-only block (pre_norm=False). ONE definition for every
    block body."""
    if not cfg.pre_norm:
        return x
    return _norm(bp["ln_1"], x, cfg)


def _qk_normed(bp, q, k, cfg: LlamaConfig):
    """q/k RMSNorm BEFORE RoPE — the ONE definition every q/k projection
    site shares (_qkv_rope, the batcher's _block_rows, verify_rows), or
    the paths' parity contracts would diverge on qk_norm configs.
    Inputs arrive head-split ((B, H, T, D) / (B, KV, T, D)); width
    "head" (Qwen3) norms each D-vector, width "proj" (OLMo-2) norms the
    merged (H*D,)/(KV*D,) vector jointly across heads (merge -> norm ->
    split — XLA folds the transposes). Identity when the switch is
    off."""
    if not cfg.qk_norm:
        return q, k
    if cfg.qk_norm_width == "proj":
        hq, hk = q.shape[1], k.shape[1]
        q2 = rms_norm(bp["attn"]["q_norm"], merge_heads(q), eps=cfg.rms_eps)
        k2 = rms_norm(bp["attn"]["k_norm"], merge_heads(k), eps=cfg.rms_eps)
        return split_heads(q2, hq), split_heads(k2, hk)
    return (rms_norm(bp["attn"]["q_norm"], q, eps=cfg.rms_eps),
            rms_norm(bp["attn"]["k_norm"], k, eps=cfg.rms_eps))


def _qkv_rope(bp, h, positions, *, cfg: LlamaConfig, compute_dtype):
    """Project h (B, T, C) and rotate q/k at absolute `positions` (T,).
    Returns q (B, H, T, D), k/v (B, KV, T, D) — KV heads stay narrow."""
    q = split_heads(linear(bp["attn"]["q"], h, compute_dtype=compute_dtype),
                    cfg.n_head)
    k = split_heads(linear(bp["attn"]["k"], h, compute_dtype=compute_dtype),
                    cfg.n_kv_head)
    v = split_heads(linear(bp["attn"]["v"], h, compute_dtype=compute_dtype),
                    cfg.n_kv_head)
    q, k = _qk_normed(bp, q, k, cfg)
    cos, sin = _rope_tables(cfg, positions)
    return (_q_rescale(_rope_apply(q, cos, sin, cfg), cfg),
            _rope_apply(k, cos, sin, cfg), v)


def _mlp_out(bp, h, *, cfg: LlamaConfig, compute_dtype, ffn=None):
    """The MLP branch over an already-normed h: gated SwiGLU/GeGLU, the
    plain 2-layer Phi MLP (mlp_gated=False), or the `ffn` override
    (Mixtral MoE hook)."""
    if ffn is not None:
        return ffn(bp, h)
    act = _mlp_act(cfg)
    if not cfg.mlp_gated:
        return linear(bp["mlp"]["down"],
                      act(linear(bp["mlp"]["up"], h,
                                 compute_dtype=compute_dtype)),
                      compute_dtype=compute_dtype)
    return linear(bp["mlp"]["down"],
                  act(linear(bp["mlp"]["gate"], h,
                             compute_dtype=compute_dtype))
                  * linear(bp["mlp"]["up"], h, compute_dtype=compute_dtype),
                  compute_dtype=compute_dtype)


def _mlp_residual(bp, x, *, cfg: LlamaConfig, compute_dtype, ffn=None):
    """Post-attention half of the SEQUENTIAL block: norm + MLP
    (gated or plain), Gemma-2 post-MLP norm, residual. ONE definition
    shared by the stateless forward, the cached decode, and the per-slot
    batcher path — their parity contracts depend on these never
    diverging. `ffn(bp, h)` overrides the MLP (the Mixtral MoE hook —
    models/llama_moe.py; same convention as the GPT family's ffn)."""
    h = x if not cfg.pre_norm else _norm(bp["ln_2"], x, cfg)
    m = _mlp_out(bp, h, cfg=cfg, compute_dtype=compute_dtype, ffn=ffn)
    if cfg.post_norms:
        m = _norm(bp["post_ln_2"], m, cfg)
    return x + m.astype(x.dtype)


def _attn_out_residual(bp, x, o, cfg: LlamaConfig):
    """Attention branch output -> residual add, through Gemma-2's
    post-attention norm when configured. `o` is the o-projected branch
    output in x's dtype."""
    if cfg.post_norms:
        o = _norm(bp["post_ln_1"], o, cfg)
    return x + o.astype(x.dtype)


def _branches_residual(bp, x, o, h, *, cfg: LlamaConfig, compute_dtype,
                       ffn=None):
    """Compose the attention branch output `o` and the MLP into the
    residual stream — the ONE definition every block body (dense
    forward, cached decode, batcher rows, verify rows, seq-sharded
    decode) shares. Sequential (LLaMA): x + o, then ln_2 + MLP +
    residual. Parallel (Phi, parallel_block): both branches read the
    SAME ln_1 output `h`; y = x + o + mlp(h), no ln_2."""
    if cfg.parallel_block:
        m = _mlp_out(bp, h, cfg=cfg, compute_dtype=compute_dtype, ffn=ffn)
        return x + o.astype(x.dtype) + m.astype(x.dtype)
    x = _attn_out_residual(bp, x, o, cfg)
    return _mlp_residual(bp, x, cfg=cfg, compute_dtype=compute_dtype,
                         ffn=ffn)


def _gqa_scores_attend(q, k, v, mask_fn, softcap=None):
    """Grouped attention: q (B, H, T, D) vs k/v (B, KV, S, D) with
    H = G * KV. Folds the group into the row dim so einsums run at KV
    heads; `mask_fn(scores (B, KV, G, T, S)) -> masked scores`;
    `softcap` bounds scores via cap*tanh(s/cap) BEFORE masking
    (Gemma-2 attn_logit_softcapping)."""
    b, h, t, d = q.shape
    kv = k.shape[1]
    g = h // kv
    qg = q.reshape(b, kv, g, t, d)
    s = jnp.einsum("bkgtd,bksd->bkgts", qg.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) / jnp.sqrt(d)
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    p = jax.nn.softmax(mask_fn(s), axis=-1)
    y = jnp.einsum("bkgts,bksd->bkgtd", p, v.astype(jnp.float32))
    return y.reshape(b, h, t, d)


def _dense_attn(bp, h, *, cfg: LlamaConfig, compute_dtype, window=None):
    """Default attention: local causal GQA over the whole (B, T, C) h,
    band-limited to cfg.sliding_window when set. `window` overrides the
    config's window for this call (traced allowed) — the per-layer hook
    alternating-attention configs thread through blocks_scan."""
    t = h.shape[1]
    q, k, v = _qkv_rope(bp, h, jnp.arange(t), cfg=cfg,
                        compute_dtype=compute_dtype)
    rows = jnp.arange(t)
    w = window if window is not None else cfg.sliding_window

    def causal(s):
        qr = rows[None, None, None, :, None]
        kr = rows[None, None, None, None, :]
        keep = qr >= kr
        if w is not None:
            keep &= kr > qr - w
        return jnp.where(keep, s, _NEG_BIG)

    y = _gqa_scores_attend(q, k, v, causal, softcap=cfg.attn_softcap)
    return linear(bp["attn"]["o"], merge_heads(y.astype(h.dtype)),
                  compute_dtype=compute_dtype)


def block_apply(bp, x, *, cfg: LlamaConfig, compute_dtype=None, attn_fn=None,
                window=None, ffn=None):
    """Pre-RMSNorm block: GQA attention + gated MLP, both residual
    (Gemma-2 additionally norms each branch output — post_norms).
    `attn_fn(bp, h)` overrides the attention (the sequence-parallel ring
    plugs in here — same hook pattern as gpt._block_core); `window` is
    the per-layer window override for the default dense attention;
    `ffn(bp, h)` overrides the MLP (Mixtral MoE)."""
    fn = attn_fn or (lambda bp2, h: _dense_attn(
        bp2, h, cfg=cfg, compute_dtype=compute_dtype, window=window))
    # trace-time scopes: device profiles (obs/profile.py) name the
    # attention branch vs the residual/MLP compose; zero runtime cost
    with jax.named_scope("llama.block.attn"):
        h = _pre_normed(bp, x, cfg)
        o = fn(bp, h)
    with jax.named_scope("llama.block.mlp"):
        return _branches_residual(bp, x, o, h, cfg=cfg,
                                  compute_dtype=compute_dtype, ffn=ffn)


def _scaled_embed(p, ids, cfg: LlamaConfig):
    """Token lookup + Gemma's sqrt(C) input scaling — the ONE definition
    every path (dense forward, cached decode, batcher rows, seq-parallel,
    pipeline embed hook) must share, or their parity contracts break on
    embed_scale configs."""
    e = embedding(p["wte"], ids)
    if cfg.embed_scale:
        e = e * jnp.asarray(cfg.n_embd ** 0.5, e.dtype)
    return e


def embed(params, idx, *, cfg: LlamaConfig):
    t = idx.shape[-1]
    if t > cfg.block_size:
        raise ValueError(
            f"Cannot forward: sequence length {t} > block_size {cfg.block_size}")
    return _scaled_embed(params, idx, cfg)  # positions live in RoPE


def head(params, x, *, cfg: LlamaConfig, compute_dtype=None, logits_dtype=None):
    with jax.named_scope("llama.head"):
        x = _norm(params["ln_f"], x, cfg)
        if "lm_head" in params:
            lm = params["lm_head"]
        else:
            # tied embeddings (Gemma, LLaMA-3.2-1B class): project through
            # the input table's transpose — XLA folds the transpose into
            # the dot
            lm = {"kernel": params["wte"]["embedding"].T}
        if compute_dtype is None:
            out = linear(lm, x)
        else:
            out = linear(lm, x, compute_dtype=compute_dtype,
                         accum_dtype=jnp.float32)
        if cfg.final_softcap is not None:  # Gemma-2 final_logit_softcapping
            out = cfg.final_softcap * jnp.tanh(out / cfg.final_softcap)
        return out if logits_dtype is None else out.astype(logits_dtype)


def blocks_scan(stacked, x, *, cfg, compute_dtype, remat=False, attn_fn=None,
                windows=None, ffn=None):
    """Scan the stacked blocks. `windows` is the per-layer window array
    for alternating-attention configs ((L',) — already sliced to this
    stack's layer range); None scans without the extra input. `ffn`
    overrides every block's MLP (Mixtral MoE)."""
    block = (lambda bp, carry, window=None: block_apply(
        bp, carry, cfg=cfg, compute_dtype=compute_dtype,
        attn_fn=attn_fn, window=window, ffn=ffn))
    if remat:
        block = jax.checkpoint(block)

    if windows is None:
        def body(carry, bp):
            return block(bp, carry), None

        out, _ = jax.lax.scan(body, x, stacked)
    else:
        def body_w(carry, xs):
            bp, w = xs
            return block(bp, carry, w), None

        out, _ = jax.lax.scan(body_w, x, (stacked, windows))
    return out


def make_apply(cfg: LlamaConfig, *, compute_dtype=None, remat=False,
               ffn=None):
    ffn = ffn or cfg.default_ffn(compute_dtype)

    def apply(params, idx):
        x = embed(params, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        stacked = gpt.stack_blocks(params, range(cfg.n_layer))
        x = blocks_scan(stacked, x, cfg=cfg, compute_dtype=compute_dtype,
                         remat=remat, windows=layer_windows(cfg), ffn=ffn)
        return head(params, x.astype(jnp.float32), cfg=cfg,
                    compute_dtype=compute_dtype)

    return apply


def make_hidden_stacked(cfg: LlamaConfig, *, compute_dtype=None):
    """Final-normed hidden states over the prepare_stacked layout —
    make_apply_stacked minus the lm_head projection (== HF
    LlamaModel/GemmaModel.last_hidden_state, every family switch
    included). The embedding endpoint's forward
    (runtime/embeddings.py); kept HERE so it can never drift from the
    logits forward above."""

    ffn = cfg.default_ffn(compute_dtype)

    def hidden(prepared, idx):
        x = embed(prepared, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        x = blocks_scan(prepared["blocks"], x, cfg=cfg,
                        compute_dtype=compute_dtype,
                        windows=layer_windows(cfg), ffn=ffn)
        return _norm(prepared["ln_f"], x.astype(jnp.float32), cfg)

    return hidden


def make_apply_stacked(cfg: LlamaConfig, *, compute_dtype=None,
                       logits_dtype=None, remat=False):
    """Forward over the prepare_stacked layout (gpt.prepare_stacked works
    unchanged — it only needs h_i keys and cfg.n_layer)."""

    ffn = cfg.default_ffn(compute_dtype)

    def apply(prepared, idx):
        x = embed(prepared, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        x = blocks_scan(prepared["blocks"], x, cfg=cfg,
                         compute_dtype=compute_dtype, remat=remat,
                         windows=layer_windows(cfg), ffn=ffn)
        return head(prepared, x.astype(jnp.float32), cfg=cfg,
                    compute_dtype=compute_dtype, logits_dtype=logits_dtype)

    return apply


# --------------------------------------------------------------------------
# KV-cache decode (kvcache codecs; cache holds KV heads, not H)
# --------------------------------------------------------------------------

def _block_with_cache(bp, x, layer_cache, start_pos, *, cfg: LlamaConfig,
                      compute_dtype, codec, window=None, ffn=None):
    """Block over x (B, T, C) at absolute positions [start_pos,
    start_pos+T), writing ROTATED k (and v) into the narrow KV-head cache.
    GQA against the cache rides the same codec.attend as the GPT family by
    folding the q group into the row dim and tiling pos_limit. `window`
    overrides the codec's window for this layer (the alternating-attention
    per-layer value — traced allowed)."""
    b, t, c = x.shape
    kv, g = cfg.n_kv_head, cfg.n_head // cfg.n_kv_head
    with jax.named_scope("llama.block.cached_attn"):
        h = _pre_normed(bp, x, cfg)
        q, k, v = _qkv_rope(bp, h, start_pos + jnp.arange(t), cfg=cfg,
                            compute_dtype=compute_dtype)
        layer_cache = codec.write(layer_cache, k, v, start_pos)
        qg = q.reshape(b, kv, g * t, cfg.head_dim)
        if t == 1:
            # decode step: the folded group rows all share the slot's
            # limit — exactly attend_rows' contract, which streams through
            # the Pallas decode kernel when the codec carries use_kernel
            yg = codec.attend_rows(
                qg, layer_cache,
                jnp.broadcast_to(jnp.asarray(start_pos, jnp.int32), (b,)),
                window=window)
        else:
            pos_limit = start_pos + jnp.arange(t)
            yg = codec.attend(qg, layer_cache, jnp.tile(pos_limit, g),
                              window=window)
        y = yg.reshape(b, cfg.n_head, t, cfg.head_dim)
        o = linear(bp["attn"]["o"], merge_heads(y.astype(x.dtype)),
                   compute_dtype=compute_dtype)
    with jax.named_scope("llama.block.mlp"):
        return (_branches_residual(bp, x, o, h, cfg=cfg,
                                   compute_dtype=compute_dtype, ffn=ffn),
                layer_cache)


def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=jnp.float32):
    """KV cache at KV-head width (L, B, KV, S, D) — GQA's decode-bandwidth
    win made concrete: H/KV times fewer cache bytes per step than MHA.
    Codec dispatch (f32/bf16/"int8") is generate.init_cache's."""
    from dnn_tpu.runtime import generate

    gqa_cfg = dataclasses.replace(
        cfg, n_head=cfg.n_kv_head, n_embd=cfg.n_kv_head * cfg.head_dim)
    return generate.init_cache(gqa_cfg, batch, max_len, dtype)


def forward_with_cache(prepared, ids, cache, start_pos, *, cfg: LlamaConfig,
                       compute_dtype=None, attn_kernel="auto", rolling=False,
                       ffn=None):
    from dnn_tpu.runtime.kvcache import codec_for_cache

    ffn = ffn or cfg.default_ffn(compute_dtype)
    wins = layer_windows(cfg)  # (L,) for alternating configs, else None
    codec = codec_for_cache(cache, use_kernel=attn_kernel,
                            window=None if wins is not None
                            else cfg.sliding_window,
                            rolling=rolling, softcap=cfg.attn_softcap)
    x = _scaled_embed(prepared, ids, cfg)
    if compute_dtype is not None:
        x = x.astype(compute_dtype)

    if wins is None:
        def layer(carry, layer_in):
            bp, layer_cache = layer_in
            y, layer_cache = _block_with_cache(
                bp, carry, layer_cache, start_pos, cfg=cfg,
                compute_dtype=compute_dtype, codec=codec, ffn=ffn)
            return y, layer_cache

        x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache))
    else:
        def layer_w(carry, layer_in):
            bp, layer_cache, w = layer_in
            y, layer_cache = _block_with_cache(
                bp, carry, layer_cache, start_pos, cfg=cfg,
                compute_dtype=compute_dtype, codec=codec, window=w,
                ffn=ffn)
            return y, layer_cache

        x, new_cache = lax.scan(layer_w, x, (prepared["blocks"], cache, wins))
    logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                  compute_dtype=compute_dtype)
    return logits, new_cache


def _ring_from_prompt(prompt_cache, t: int, w: int):
    """Gather a prompt-length cache's live sliding-window band into a
    w-slot ring: slot j takes position ``a_j = (t-1) - ((t-1-j) % w)``
    (the latest prompt position congruent to j), zeroed where no such
    position exists (a_j < 0 — short prompts). Decode steps then keep
    writing positions t, t+1, ... at ``pos % w``; kvcache's ring
    predicate recovers exactly this occupancy at every later step."""
    from dnn_tpu.runtime.kvcache import ring_positions

    a = ring_positions(t - 1, w)  # (w,) absolute position per ring slot
    src = jnp.clip(a, 0, t - 1)
    out = {}
    for kk, leaf in prompt_cache.items():  # leaves (L, B, KV, S[, D])
        g = jnp.take(leaf, src, axis=3)
        live = (a >= 0).reshape((1, 1, 1, w) + (1,) * (leaf.ndim - 4))
        out[kk] = jnp.where(live, g, jnp.zeros_like(g))
    return out


def make_generate(cfg: LlamaConfig, *, max_new_tokens: int,
                  temperature: float = 0.0, top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  compute_dtype=None, kv_dtype=None, attn_kernel="auto",
                  ffn=None):
    """Jitted generate(prepared, ids, rng) — same contract as the GPT
    family's decoder, including kv_dtype (f32/bf16/"int8") cache storage
    and attn_kernel (Pallas streaming cache attention on decode steps).

    Sliding-window configs whose total stream exceeds the window decode
    on a ROLLING cache: prefill runs window-masked on a transient
    prompt-length cache, its live band is gathered into a
    `sliding_window`-slot ring, and every decode step reads/writes only
    the ring — cache bytes per step are O(window) regardless of how long
    the stream runs (the Mistral architecture's decode claim)."""
    from dnn_tpu.runtime.generate import _sample

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")

    @jax.jit
    def generate(prepared, ids, rng):
        b, t = ids.shape
        s_max = t + max_new_tokens
        if s_max > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        cache_dtype = kv_dtype if kv_dtype is not None else (compute_dtype or jnp.float32)
        w = cfg.sliding_window
        # alternating configs (Gemma-2) keep GLOBAL layers, so the cache
        # can never roll down to the window — full-length cache with the
        # per-layer band handled inside forward_with_cache
        rolling = w is not None and s_max > w and not cfg.alt_window
        if rolling:
            # transient prompt-length cache (window-masked attends), then
            # the live band moves into the ring
            prompt_cache = init_cache(cfg, b, t, cache_dtype)
            logits, prompt_cache = forward_with_cache(
                prepared, ids, prompt_cache, 0, cfg=cfg,
                compute_dtype=compute_dtype, ffn=ffn)
            cache = _ring_from_prompt(prompt_cache, t, w)
        else:
            cache = init_cache(cfg, b, s_max, cache_dtype)
            logits, cache = forward_with_cache(
                prepared, ids, cache, 0, cfg=cfg, compute_dtype=compute_dtype,
                attn_kernel=attn_kernel, ffn=ffn)
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature=temperature,
                      top_k=top_k, top_p=top_p)

        def step(carry, i):
            cache, tok, rng = carry
            logits, cache = forward_with_cache(
                prepared, tok[:, None], cache, t + i, cfg=cfg,
                compute_dtype=compute_dtype,
                attn_kernel=False if rolling else attn_kernel,
                rolling=rolling,
                ffn=ffn)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k, top_p=top_p)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    return generate


def make_apply_seq_parallel(cfg: LlamaConfig, mesh, *, axis_name=None,
                            compute_dtype=None):
    """Sequence-parallel (long-context) LLaMA forward over the "seq" mesh
    axis — ring attention with GQA-narrow K/V blocks.

    Embed/RMSNorm/SwiGLU/head act position-wise on local shards; RoPE uses
    each shard's GLOBAL positions; attention crosses shards by rotating
    K/V blocks around the ring at KV-HEAD width (H/KV times fewer ICI
    bytes per hop than an MHA ring — GQA's bandwidth advantage applies to
    the collective exactly as it does to the decode cache), with the
    query group folded into rows (parallel/ring_attention.py's GQA mode).

    apply(prepared, ids): ids (B, T), T divisible by the axis size;
    returns f32 logits sharded over the sequence axis. Parity vs the
    dense forward is pinned in tests/test_models_llama.py."""
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.parallel.mesh import SEQ_AXIS
    from dnn_tpu.parallel.ring_attention import ring_attention_local

    if cfg.alt_window:
        raise ValueError(
            "alternating-window configs (Gemma-2) are not supported on "
            "the sequence-parallel path: blocks share one attention "
            "body, and the per-layer window channel is not threaded "
            "through the ring (uniform sliding_window IS supported — "
            "the banded ring schedule)")
    if cfg.attn_softcap is not None:
        raise ValueError(
            "attention softcapping is not supported on the ring-attention "
            "path (the online-softmax hop combine assumes raw scores)")
    if cfg.default_ffn() is not None:
        raise ValueError(
            "MoE configs are not supported on the sequence-parallel path "
            "(per-shard routing groups would diverge from the dense "
            "routing — EP x SP composition is follow-on work)")
    axis = axis_name or SEQ_AXIS

    def local_fn(prepared, ids_local):
        b, t_local = ids_local.shape
        my = lax.axis_index(axis)
        pos = my * t_local + jnp.arange(t_local)  # global positions
        x = _scaled_embed(prepared, ids_local, cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        kv, g, d = cfg.n_kv_head, cfg.n_head // cfg.n_kv_head, cfg.head_dim

        def ring_attn(bp, h):
            q, k, v = _qkv_rope(bp, h, pos, cfg=cfg,
                                compute_dtype=compute_dtype)
            qg = q.reshape(b, kv, g * t_local, d)  # fold group into rows
            # sliding-window configs ride the banded ring: the band's
            # lower bound masks per block AND the ring stops after the
            # live hops (parallel/ring_attention.py)
            y = ring_attention_local(qg, k, v, axis_name=axis, causal=True,
                                     window=cfg.sliding_window)
            y = y.reshape(b, cfg.n_head, t_local, d)
            return linear(bp["attn"]["o"], merge_heads(y.astype(h.dtype)),
                          compute_dtype=compute_dtype)

        x = blocks_scan(prepared["blocks"], x, cfg=cfg,
                        compute_dtype=compute_dtype, attn_fn=ring_attn)
        return head(prepared, x.astype(jnp.float32), cfg=cfg,
                    compute_dtype=compute_dtype)

    def apply(prepared, ids):
        t = ids.shape[-1]
        if t > cfg.block_size:
            raise ValueError(
                f"Cannot forward: sequence length {t} > block_size "
                f"{cfg.block_size}")
        n = mesh.shape[axis]
        if t % n != 0:
            raise ValueError(
                f"sequence length {t} not divisible by seq axis size {n}")
        return jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(P(), P(None, axis)),
            out_specs=P(None, axis, None),
            check_vma=False,
        )(prepared, ids)

    return apply


def make_generate_seq_sharded(cfg: LlamaConfig, mesh, *, max_new_tokens: int,
                              temperature: float = 0.0,
                              top_k: Optional[int] = None,
                              top_p: Optional[float] = None,
                              compute_dtype=None, axis_name=None):
    """Sequence-sharded KV-cache decode for the LLaMA family: each device
    of the "seq" axis owns a contiguous block of cache POSITIONS at
    KV-head width, and every decode step combines per-shard partial
    attention with the exact distributed online-softmax
    (runtime/generate_seq.py's design — pmax + two psums, no K/V
    movement), with the GQA query group folded into the stats rows and
    RoPE at absolute positions. Token-parity with llama.make_generate
    while each shard holds only ceil(S_max/n) positions.

    NOTE: mirrors runtime/generate_seq.make_generate_seq_sharded's loop
    (same reason as the EP x PP decoder's mirror — the per-family block
    internals differ where that module's are GPT-fixed); drift is caught
    by each file's parity tests against its own solo decoder."""
    from dnn_tpu.parallel.mesh import SEQ_AXIS
    from dnn_tpu.runtime.generate import _sample
    from dnn_tpu.runtime.generate_seq import _local_attn_stats

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if cfg.sliding_window is not None:
        raise ValueError(
            "sequence-sharded decode keeps full history shards; "
            "sliding-window configs are not supported on this path")
    if cfg.attn_softcap is not None:
        raise ValueError(
            "attention softcapping is not supported on the seq-sharded "
            "decode path (the distributed online-softmax combines raw "
            "per-shard score stats)")
    if cfg.default_ffn() is not None:
        raise ValueError(
            "MoE configs are not supported on the seq-sharded decode "
            "path (its inline block body has no ffn hook)")
    axis = axis_name or SEQ_AXIS
    n = mesh.shape[axis]
    kv, g, hd = cfg.n_kv_head, cfg.n_head // cfg.n_kv_head, cfg.head_dim

    def per_device(prepared, ids, rng):
        b, t = ids.shape
        s_max = t + max_new_tokens
        sd = -(-s_max // n)
        i = lax.axis_index(axis)
        lo = i * sd

        # prefill: full forward over a transient prompt-length KV-width
        # cache; each device gathers its own position columns
        prompt_cache = init_cache(cfg, b, t, compute_dtype or jnp.float32)
        # attn_kernel pinned off: this forward runs INSIDE shard_map,
        # where the "auto" policy's Pallas engagement is untested — the
        # sharded path keeps the einsum unconditionally
        logits, prompt_cache = forward_with_cache(
            prepared, ids, prompt_cache, 0, cfg=cfg,
            compute_dtype=compute_dtype, attn_kernel=False)
        gpos = lo + jnp.arange(sd)
        in_prompt = gpos < t
        local = {
            kk: jnp.where(
                in_prompt[None, None, None, :, None],
                jnp.take(prompt_cache[kk], jnp.clip(gpos, 0, t - 1), axis=3),
                0,
            )
            for kk in ("k", "v")
        }  # (L, B, KV, Sd, D)
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature=temperature,
                      top_k=top_k, top_p=top_p)

        def block_step(bp, x, lc_k, lc_v, p):
            h = _pre_normed(bp, x, cfg)
            q, k, v = _qkv_rope(bp, h, p + jnp.arange(1), cfg=cfg,
                                compute_dtype=compute_dtype)
            p_loc = jnp.clip(p - lo, 0, sd - 1)
            own = jnp.logical_and(p >= lo, p < lo + sd)
            lc_k = jnp.where(own, lax.dynamic_update_slice_in_dim(
                lc_k, k.astype(lc_k.dtype), p_loc, axis=2), lc_k)
            lc_v = jnp.where(own, lax.dynamic_update_slice_in_dim(
                lc_v, v.astype(lc_v.dtype), p_loc, axis=2), lc_v)
            local_limit = jnp.minimum(p - lo, sd - 1)
            qg = q.reshape(b, kv, g, hd)  # fold group into stats rows
            m, l, o = _local_attn_stats(qg, lc_k, lc_v, local_limit)
            g_m = lax.pmax(m, axis)
            w = jnp.exp(m - g_m)
            g_l = lax.psum(l * w, axis)
            g_o = lax.psum(o * w[..., None], axis)
            y = g_o / jnp.maximum(g_l, 1e-30)[..., None]
            y = y.reshape(b, cfg.n_head, 1, hd)
            o = linear(bp["attn"]["o"], merge_heads(y.astype(x.dtype)),
                       compute_dtype=compute_dtype)
            return (_branches_residual(bp, x, o, h, cfg=cfg,
                                       compute_dtype=compute_dtype),
                    lc_k, lc_v)

        def decode_one(local, tok, rng, p):
            x = _scaled_embed(prepared, tok[:, None], cfg)
            if compute_dtype is not None:
                x = x.astype(compute_dtype)

            def layer(carry, layer_in):
                bp, lk, lv = layer_in
                y, lk, lv = block_step(bp, carry, lk, lv, p)
                return y, (lk, lv)

            x, (k_new, v_new) = lax.scan(
                layer, x, (prepared["blocks"], local["k"], local["v"]))
            logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                          compute_dtype=compute_dtype)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k, top_p=top_p)
            return {"k": k_new, "v": v_new}, nxt, rng

        def step(carry, j):
            local, tok, rng = carry
            local, nxt, rng = decode_one(local, tok, rng, t + j)
            return (local, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (local, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    @jax.jit
    def generate(prepared, ids, rng):
        from jax.sharding import PartitionSpec as P

        b, t = ids.shape
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )(prepared, ids, rng)

    return generate


class LlamaFamilyRows:
    """ContinuousBatcher family adapter (see
    runtime/serving.GPTFamilyRows for the protocol): per-slot LLaMA decode
    with RoPE at each slot's own position and the KV-head-width cache. The
    GQA fold for per-row attention treats the query group as the row dim —
    q (B, H, 1, D) -> (B, KV, G, D) — since every group row shares its
    slot's position limit."""

    def __init__(self, cfg: LlamaConfig, *, compute_dtype=None,
                 attn_kernel="auto", ffn=None):
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        # picked up by ContinuousBatcher for the decode-rows codec too
        self.attn_kernel = attn_kernel
        # MLP override (Mixtral MoE — llama_moe.make_ffn); rides every
        # path of this adapter: prefill, decode rows, verify rows.
        # Resolved from the config when not passed, so
        # LlamaFamilyRows(mixtral_cfg) just works.
        self.ffn = ffn or cfg.default_ffn(compute_dtype)
        # paged-pool head width: the cache stores KV heads (GQA)
        self.kv_heads = cfg.n_kv_head
        # picked up by ContinuousBatcher: sliding-window masking over the
        # slot pool's full-length cache (storage unchanged — the pool is
        # shared across slots, so the ring form doesn't apply here).
        # Alternating-window configs (Gemma-2) keep the CODEC dense and
        # thread the per-layer window through the block scan instead.
        self._wins = layer_windows(cfg)
        self.window = None if self._wins is not None else cfg.sliding_window
        # alt-window configs keep window=None (per-layer channel) — the
        # paged batcher needs the distinction to reject them explicitly
        self.alt_window = cfg.alt_window
        # Gemma-2 attention softcapping rides the codec (serving builds
        # the decode codec from this attr)
        self.softcap = cfg.attn_softcap
        # "attends plain dense causal" — what the SPECULATIVE verifier
        # requires (its codecs attend dense; serving_spec checks this
        # flag). The paged pool no longer keys on it: it gates on
        # softcap/alt_window directly and band-masks uniform windows
        # itself (runtime/paged_kvcache.PagedKV window=).
        self.paged_ok = (cfg.sliding_window is None
                         and cfg.attn_softcap is None)

    def init_cache(self, batch, max_len, dtype):
        return init_cache(self.cfg, batch, max_len, dtype)

    def prefill(self, prepared, padded, row_cache, start_pos=0):
        return forward_with_cache(
            prepared, padded, row_cache, start_pos, cfg=self.cfg,
            compute_dtype=self.compute_dtype, attn_kernel=self.attn_kernel,
            ffn=self.ffn)

    def _block_rows(self, bp, x, layer_cache, pos, write, codec,
                    window=None):
        cfg, compute_dtype = self.cfg, self.compute_dtype
        b = x.shape[0]
        kv, g, d = cfg.n_kv_head, cfg.n_head // cfg.n_kv_head, cfg.head_dim
        h = _pre_normed(bp, x, cfg)
        q = split_heads(linear(bp["attn"]["q"], h, compute_dtype=compute_dtype),
                        cfg.n_head)
        k = split_heads(linear(bp["attn"]["k"], h, compute_dtype=compute_dtype),
                        kv)
        v = split_heads(linear(bp["attn"]["v"], h, compute_dtype=compute_dtype),
                        kv)
        q, k = _qk_normed(bp, q, k, cfg)
        cos, sin = _rope_tables(cfg, pos)  # (B, D)
        cos, sin = cos[:, None, None, :], sin[:, None, None, :]
        q, k = _rope_apply(q, cos, sin, cfg), _rope_apply(k, cos, sin, cfg)
        q = _q_rescale(q, cfg)
        layer_cache = codec.write_rows(layer_cache, k, v, pos, write)
        qg = q.reshape(b, kv, g, d)  # group rows share the slot's limit
        y = codec.attend_rows(qg, layer_cache, pos, window=window)
        y = y.reshape(b, cfg.n_head, 1, d)
        o = linear(bp["attn"]["o"], merge_heads(y.astype(x.dtype)),
                   compute_dtype=compute_dtype)
        return (_branches_residual(bp, x, o, h, cfg=cfg,
                                   compute_dtype=compute_dtype,
                                   ffn=self.ffn),
                layer_cache)

    def verify_rows(self, prepared, cache, chunk, pos, active, codec):
        """A (B, T) token block at PER-ROW start positions pos (B,) —
        the speculative batcher's target-scoring / draft-sync program
        (see runtime/serving.GPTFamilyRows.verify_rows): writes ROTATED
        K/V for positions pos..pos+T-1 of each active row, attends GQA
        with per-row within-block causality, row t's logits predict the
        token at position pos+t+1.

        Restrictions match the speculative batcher's: float caches
        (attention reads the cache leaves directly — the codec handles
        the write gate) and dense attention (no window/softcap; those
        families are rejected at batcher construction). The score/probs
        dtype recipe mirrors kvcache.FloatKV.attend_rows exactly, so a
        greedy verify reproduces the step-by-step decode's argmax even
        under bf16 compute (the spec batcher's token-identity
        contract)."""
        cfg, compute_dtype = self.cfg, self.compute_dtype
        if cfg.sliding_window is not None or cfg.attn_softcap is not None:
            raise ValueError(
                "speculative verify supports dense-attention LLaMA-family "
                "configs only (no sliding window / softcap)")
        b, t = chunk.shape
        kv, g, hd = cfg.n_kv_head, cfg.n_head // cfg.n_kv_head, cfg.head_dim
        positions = pos[:, None] + jnp.arange(t)  # (B, T)
        x = _scaled_embed(prepared, chunk, cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        # loop-invariant: one table for all layers (a scan body would
        # recompute it per layer — JAX does not hoist out of scan)
        cos, sin = _rope_tables(cfg, positions)  # (B, T, D)
        cos_, sin_ = cos[:, None], sin[:, None]  # broadcast over heads

        def layer(carry, layer_in):
            bp, lc = layer_in
            h = _pre_normed(bp, carry, cfg)
            q = split_heads(linear(bp["attn"]["q"], h,
                                   compute_dtype=compute_dtype), cfg.n_head)
            kk = split_heads(linear(bp["attn"]["k"], h,
                                    compute_dtype=compute_dtype), kv)
            vv = split_heads(linear(bp["attn"]["v"], h,
                                    compute_dtype=compute_dtype), kv)
            q, kk = _qk_normed(bp, q, kk, cfg)
            q, kk = (_rope_apply(q, cos_, sin_, cfg),
                     _rope_apply(kk, cos_, sin_, cfg))
            q = _q_rescale(q, cfg)
            lc = codec.write_rows(lc, kk, vv, pos, active)
            # GQA per-row causal attend on the float cache: fold the
            # group NEXT TO the row dim (5-D scores) so each row keeps
            # its own within-block limit — the 4-D fold used by decode
            # (all rows share one limit) cannot express this
            ck, cv = lc["k"], lc["v"]  # (B, KV, S, D)
            qg = q.reshape(b, kv, g, t, hd)
            s = jnp.einsum("bkgtd,bksd->bkgts", qg,
                           ck).astype(jnp.float32) / jnp.sqrt(hd)
            cols = jnp.arange(ck.shape[2])
            limit = (pos[:, None, None, None, None]
                     + jnp.arange(t)[None, None, None, :, None])
            s = jnp.where(cols[None, None, None, None, :] <= limit, s,
                          _NEG_BIG)
            p = jax.nn.softmax(s, axis=-1)
            y = jnp.einsum("bkgts,bksd->bkgtd", p.astype(cv.dtype), cv)
            y = y.reshape(b, cfg.n_head, t, hd)
            o = linear(bp["attn"]["o"], merge_heads(y.astype(carry.dtype)),
                       compute_dtype=compute_dtype)
            return (_branches_residual(bp, carry, o, h, cfg=cfg,
                                       compute_dtype=compute_dtype,
                                       ffn=self.ffn), lc)

        x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache))
        logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                      compute_dtype=compute_dtype)
        return logits, new_cache

    def decode_rows(self, prepared, cache, tok, pos, active, codec):
        x = _scaled_embed(prepared, tok[:, None], self.cfg)  # (B, 1, C)
        if self.compute_dtype is not None:
            x = x.astype(self.compute_dtype)

        if self._wins is None:
            def layer(carry, layer_in):
                bp, layer_cache = layer_in
                y, layer_cache = self._block_rows(
                    bp, carry, layer_cache, pos, active, codec)
                return y, layer_cache

            x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache))
        else:
            def layer_w(carry, layer_in):
                bp, layer_cache, w = layer_in
                y, layer_cache = self._block_rows(
                    bp, carry, layer_cache, pos, active, codec, window=w)
                return y, layer_cache

            x, new_cache = lax.scan(
                layer_w, x, (prepared["blocks"], cache, self._wins))
        logits = head(prepared, x.astype(jnp.float32), cfg=self.cfg,
                      compute_dtype=self.compute_dtype)
        return logits[:, -1], new_cache


class LlamaPipelineFamily:
    """Pipeline-parallel decode hooks (see
    runtime/generate.GPTPipelineFamily): stage-local cache shards at
    KV-head width, RoPE at the ring's absolute positions."""

    def __init__(self, cfg: LlamaConfig, *, compute_dtype=None, kv_dtype=None):
        if cfg.alt_window:
            raise ValueError(
                "alternating-window configs (Gemma-2) are not supported on "
                "the pipeline decode path: the stage scan has no per-layer "
                "window channel (use the solo decoder or the batcher)")
        if cfg.default_ffn() is not None:
            raise ValueError(
                "MoE configs are not supported on this pipeline decode "
                "path (MoE pipeline decode is runtime/generate_moe's "
                "machinery)")
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        self.kv_dtype = kv_dtype  # None follows compute_dtype; "int8" quantizes

    def stage_cache(self, per_stage, batch, s_max):
        dt = self.kv_dtype if self.kv_dtype is not None else (
            self.compute_dtype or jnp.float32)
        stage_cfg = dataclasses.replace(self.cfg, n_layer=per_stage)
        return init_cache(stage_cfg, batch, s_max, dt)

    def block_with_cache(self, bp, x, layer_cache, start_pos):
        from dnn_tpu.runtime.kvcache import codec_for_cache

        return _block_with_cache(
            bp, x, layer_cache, start_pos, cfg=self.cfg,
            compute_dtype=self.compute_dtype,
            codec=codec_for_cache(layer_cache,
                                  window=self.cfg.sliding_window,
                                  softcap=self.cfg.attn_softcap))

    def embed(self, aux, ids, start_pos):
        x = _scaled_embed(aux, ids, self.cfg)
        if self.compute_dtype is not None:
            x = x.astype(self.compute_dtype)
        return x

    def head(self, aux, h):
        return head(aux, h.astype(jnp.float32), cfg=self.cfg,
                    compute_dtype=self.compute_dtype)


def make_pipeline_generate(cfg: LlamaConfig, mesh, *, max_new_tokens: int,
                           temperature: float = 0.0,
                           top_k: Optional[int] = None,
                           top_p: Optional[float] = None,
                           compute_dtype=None, axis_name=None,
                           kv_dtype=None):
    """Pipeline-parallel KV-cache generation for the LLaMA family: each
    stage keeps its blocks AND its KV-head-width cache shard, the hidden
    state rides the ppermute ring per token (runtime/generate's ring
    schedule with this family's hooks). Token-for-token identical to
    llama.make_generate."""
    from dnn_tpu.runtime.generate import (
        make_pipeline_generate as _mk,
    )

    return _mk(cfg, mesh, max_new_tokens=max_new_tokens,
               temperature=temperature, top_k=top_k, top_p=top_p,
               compute_dtype=compute_dtype, axis_name=axis_name,
               family=LlamaPipelineFamily(cfg, compute_dtype=compute_dtype,
                                          kv_dtype=kv_dtype))


# --------------------------------------------------------------------------
# pipeline partitioning + registry
# --------------------------------------------------------------------------

def make_partition(cfg: LlamaConfig, *, compute_dtype=None):
    part_ffn = cfg.default_ffn(compute_dtype)

    def partition(num_parts):
        ranges = gpt.layer_ranges(cfg.n_layer, num_parts)
        stages = []
        wins = layer_windows(cfg)
        for p, (lo, hi) in enumerate(ranges):
            is_first, is_last = p == 0, p == num_parts - 1
            param_keys = tuple(f"h_{i}" for i in range(lo, hi))
            if is_first:
                param_keys = ("wte",) + param_keys
            if is_last:
                param_keys = param_keys + ("ln_f",)
                if cfg.tie_word_embeddings:
                    # tied head projects through the embedding table — the
                    # LAST stage needs wte too (both stages then hold a
                    # copy, the standard tied-embeddings PP trade)
                    if not is_first:
                        param_keys = param_keys + ("wte",)
                else:
                    param_keys = param_keys + ("lm_head",)

            def stage_fn(params, x, _lo=lo, _hi=hi, _first=is_first, _last=is_last):
                if _first:
                    x = embed(params, x, cfg=cfg)
                if compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(compute_dtype)
                if _hi > _lo:
                    stacked = gpt.stack_blocks(params, range(_lo, _hi))
                    x = blocks_scan(stacked, x, cfg=cfg,
                                     compute_dtype=compute_dtype,
                                     windows=None if wins is None
                                     else wins[_lo:_hi], ffn=part_ffn)
                if _last:
                    x = head(params, x.astype(jnp.float32), cfg=cfg,
                             compute_dtype=compute_dtype)
                return x

            stages.append(StageSpec(
                name=f"llama_blocks[{lo}:{hi}]"
                + ("+embed" if is_first else "") + ("+head" if is_last else ""),
                apply=stage_fn,
                param_keys=param_keys,
            ))
        return stages

    return partition


def to_hf_config(cfg: LlamaConfig, *, tie_word_embeddings: bool = False,
                 **overrides):
    """The one LlamaConfig -> transformers config mapping (tests, the
    HF-serve example, and any converter round-trip share it — the field
    list must not fork). Sliding-window configs map to
    transformers.MistralConfig (the HF class that implements the window);
    attn_bias configs to Qwen2Config (the HF class with q/k/v biases);
    dense bias-free ones to LlamaConfig. Requires transformers; extra
    kwargs pass through (e.g. attn_implementation="eager")."""
    import transformers

    kw = dict(
        vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layer,
        num_attention_heads=cfg.n_head, num_key_value_heads=cfg.n_kv_head,
        max_position_embeddings=cfg.block_size, rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps,
        tie_word_embeddings=tie_word_embeddings or cfg.tie_word_embeddings,
    )
    if cfg.parallel_block:
        # Phi family: parallel residual, biased LayerNorms, partial
        # rotary, plain gelu MLP (HF "gelu_new" IS the tanh approx).
        # Reuses kw (the one-mapping contract) — only the eps key
        # renames and the Phi-specific fields add on top.
        kw["layer_norm_eps"] = kw.pop("rms_norm_eps")
        kw.update(
            partial_rotary_factor=(cfg.rotary_dim or cfg.head_dim)
            / cfg.head_dim,
            hidden_act="gelu_new")
        kw.update(overrides)
        return transformers.PhiConfig(**kw)
    if cfg.norm_plus_one:
        # Gemma family: (1+w) norms, GeGLU, scaled+tied embeddings
        kw.update(head_dim=cfg.head_dim,
                  hidden_activation="gelu_pytorch_tanh")
        if cfg.post_norms:  # Gemma-2
            kw.update(
                query_pre_attn_scalar=cfg.query_scale or cfg.head_dim,
                attn_logit_softcapping=cfg.attn_softcap,
                final_logit_softcapping=cfg.final_softcap,
                sliding_window=cfg.sliding_window,
            )
            kw.update(overrides)
            return transformers.Gemma2Config(**kw)
        kw.update(overrides)
        return transformers.GemmaConfig(**kw)
    if cfg.rope_scaling == "linear" and cfg.rope_scale != 1.0:
        kw["rope_scaling"] = {"rope_type": "linear",
                              "factor": cfg.rope_scale}
    elif cfg.rope_scaling == "ntk" and cfg.rope_scale != 1.0:
        # transformers has no STATIC ntk type (its "dynamic" rescales
        # with runtime length) — an equivalent HF config is theta
        # pre-multiplied, which we emit rather than a silent mismatch
        kw["rope_theta"] = cfg.rope_theta * cfg.rope_scale ** (
            cfg.head_dim / (cfg.head_dim - 2))
    if not cfg.pre_norm:
        # OLMo-2: post-norm-only block. HF Olmo2 hard-codes proj-width
        # q/k norms, no decoupled head_dim, no biases, no window —
        # anything else has no Olmo2Config mapping; emit an error
        # rather than a silently-dropped field (this function's
        # convention)
        if (not (cfg.qk_norm and cfg.qk_norm_width == "proj")
                or cfg.head_dim_override is not None or cfg.attn_bias
                or cfg.sliding_window is not None):
            raise ValueError(
                "pre_norm=False maps to Olmo2Config only with "
                "qk_norm=True/qk_norm_width='proj' and no "
                "head_dim_override/attn_bias/sliding_window — map this "
                "config by hand")
        kw.update(overrides)
        return transformers.Olmo2Config(**kw)
    if cfg.qk_norm:
        # Qwen3: PER-HEAD q/k RMSNorm, bias-free, decoupled head_dim
        if (cfg.attn_bias or cfg.sliding_window is not None
                or cfg.qk_norm_width != "head"):
            raise ValueError(
                "qk_norm with attn_bias/sliding_window/proj-width norms "
                "has no direct Qwen3Config mapping here — map this "
                "config by hand")
        kw.update(head_dim=cfg.head_dim, attention_bias=False)
        kw.update(overrides)
        return transformers.Qwen3Config(**kw)
    if cfg.sliding_window is not None:
        if cfg.attn_bias:
            raise ValueError(
                "attn_bias + sliding_window has no single HF class "
                "(MistralConfig is bias-free, Qwen2Config's window "
                "support differs) — map this config by hand")
        kw.update(sliding_window=cfg.sliding_window, head_dim=cfg.head_dim)
        kw.update(overrides)  # after defaults: overrides must win
        return transformers.MistralConfig(**kw)
    if cfg.attn_bias:
        # Qwen2's sliding window is OFF unless use_sliding_window is set
        kw.update(overrides)
        return transformers.Qwen2Config(**kw)
    kw.update(attention_bias=False, mlp_bias=False)
    kw.update(overrides)
    return transformers.LlamaConfig(**kw)


def _register(name: str, cfg: LlamaConfig):
    def convert(sd, _cfg=cfg):
        if _cfg.parallel_block:  # Phi layout (fc1/fc2, dense, LN biases)
            from dnn_tpu.io.checkpoint import phi_params_from_state_dict

            return phi_params_from_state_dict(sd, n_layer=_cfg.n_layer)
        from dnn_tpu.io.checkpoint import llama_params_from_state_dict

        return llama_params_from_state_dict(
            sd, n_layer=_cfg.n_layer, post_norms=_cfg.post_norms,
            tied_head="omit" if _cfg.tie_word_embeddings else "materialize")

    register_model(ModelSpec(
        name=name,
        init=lambda rng, dtype=jnp.float32, _cfg=cfg: init(rng, _cfg, dtype),
        apply=make_apply(cfg),
        partition=make_partition(cfg),
        example_input=gpt.make_example_input(cfg),
        supported_parts=tuple(range(1, cfg.n_layer + 1)),
        convert_state_dict=convert,
        config=cfg,
        extras={
            "make_apply": lambda compute_dtype=None, **_kw: make_apply(
                cfg, compute_dtype=compute_dtype),
            "make_partition": lambda compute_dtype=None, **_kw: make_partition(
                cfg, compute_dtype=compute_dtype),
        },
    ))


for _name, _cfg in PRESETS.items():
    _register(_name, _cfg)
