"""GPT-2 model family, TPU-native.

The reference ships GPT partition wrappers
(/root/reference/partitions/gpt_model_parts.py) over a nanoGPT-style
`GPT/GPTConfig/Block` imported from a `model.py` that is ABSENT from its
repo (gpt_model_parts.py:4) — so this module re-authors the base model from
the standard GPT-2 architecture (the reference survey mandates this:
SURVEY.md §7g), weight-compatible with HuggingFace GPT-2 checkpoints via
the converter in dnn_tpu/io/checkpoint.py.

Partitioning mirrors the reference's three wrapper classes:
  * first stage  = wte + wpe + blocks[0..k]      (ModelPart0, :6-22)
  * middle stage = blocks[i..j]                  (ModelPartIntermediate, :26-34)
  * final stage  = blocks[..] + ln_f + lm_head   (ModelPartFinal_GPT, :36-50)
and generalizes to any num_parts <= n_layer.

TPU-first choices (vs a torch translation):
  * params are a flat dict keyed by stage-sliceable units
    ({"wte","wpe","h_0".."h_{L-1}","ln_f","lm_head"});
  * blocks are a single pure function -> stacked-params `lax.scan` over
    layers inside a stage (one compiled block body, MXU-friendly);
  * bf16 compute / f32 params via `compute_dtype`;
  * optional Pallas flash attention for long sequences.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from dnn_tpu.ops.attention import causal_self_attention
from dnn_tpu.ops.nn import embedding, gelu, layer_norm, linear
from dnn_tpu.registry import ModelSpec, StageSpec, register_model


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Mirrors the nanoGPT GPTConfig the reference depends on
    (gpt_model_parts.py:4,15 uses config.block_size)."""

    block_size: int = 1024
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    ln_eps: float = 1e-5


PRESETS = {
    "gpt2": GPTConfig(n_layer=12, n_head=12, n_embd=768),
    "gpt2-medium": GPTConfig(n_layer=24, n_head=16, n_embd=1024),
    "gpt2-large": GPTConfig(n_layer=36, n_head=20, n_embd=1280),
    "gpt2-xl": GPTConfig(n_layer=48, n_head=25, n_embd=1600),
    # long-context variants (train-from-scratch; the classic presets cap
    # block_size at GPT-2's 1024, below the flash-attention auto crossover —
    # these are the configs where use_flash="auto" engages the Pallas
    # kernel and where the seq-parallel ring is worth its collectives)
    "gpt2-4k": GPTConfig(block_size=4096, n_layer=12, n_head=12, n_embd=768),
    "gpt2-8k": GPTConfig(block_size=8192, n_layer=12, n_head=12, n_embd=768),
    # tiny config for tests / CPU-mesh CI
    "gpt2-test": GPTConfig(block_size=64, vocab_size=256, n_layer=4, n_head=4, n_embd=64),
}


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def _normal(key, shape, dtype, std=0.02):
    return (jax.random.normal(key, shape) * std).astype(dtype)


def init_block(key, cfg: GPTConfig, dtype=jnp.float32):
    c = cfg.n_embd
    ks = jax.random.split(key, 4)
    # GPT-2 scales residual-projection init by 1/sqrt(2*n_layer).
    proj_std = 0.02 / (2 * cfg.n_layer) ** 0.5
    return {
        "ln_1": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
        "attn": {
            "qkv": {"kernel": _normal(ks[0], (c, 3 * c), dtype), "bias": jnp.zeros((3 * c,), dtype)},
            "proj": {"kernel": _normal(ks[1], (c, c), dtype, proj_std), "bias": jnp.zeros((c,), dtype)},
        },
        "ln_2": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
        "mlp": {
            "fc": {"kernel": _normal(ks[2], (c, 4 * c), dtype), "bias": jnp.zeros((4 * c,), dtype)},
            "proj": {"kernel": _normal(ks[3], (4 * c, c), dtype, proj_std), "bias": jnp.zeros((c,), dtype)},
        },
    }


def init(rng, cfg: GPTConfig = PRESETS["gpt2"], dtype=jnp.float32, tie_lm_head=True):
    keys = jax.random.split(rng, cfg.n_layer + 3)
    c = cfg.n_embd
    params = {
        "wte": {"embedding": _normal(keys[0], (cfg.vocab_size, c), dtype)},
        "wpe": {"embedding": _normal(keys[1], (cfg.block_size, c), dtype, std=0.01)},
        "ln_f": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
    }
    for i in range(cfg.n_layer):
        params[f"h_{i}"] = init_block(keys[2 + i], cfg, dtype)
    # GPT-2 ties lm_head to wte; we materialize the tied weight under its own
    # key so pipeline stages stay cleanly sliceable (the reference's final
    # stage likewise carries original_model.lm_head — gpt_model_parts.py:42).
    params["lm_head"] = {
        "kernel": params["wte"]["embedding"].T if tie_lm_head else _normal(keys[-1], (c, cfg.vocab_size), dtype)
    }
    return params


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _block_core(block_params, x, attn_fn, *, cfg: GPTConfig, compute_dtype=None):
    """Pre-LN transformer block with a pluggable attention implementation
    (local causal MHA, Pallas flash, or sequence-parallel ring).

    The named_scopes are trace-time only (zero runtime cost post-compile):
    they ride into XLA op metadata so device profiles (POST /profilez,
    dnn_tpu/obs/profile.py) name attention vs MLP instead of fused-op soup."""
    with jax.named_scope("gpt.block.attn"):
        h = layer_norm(block_params["ln_1"], x, eps=cfg.ln_eps)
        x = x + attn_fn(block_params["attn"], h)
    with jax.named_scope("gpt.block.mlp"):
        h = layer_norm(block_params["ln_2"], x, eps=cfg.ln_eps)
        m = linear(
            block_params["mlp"]["proj"],
            gelu(linear(block_params["mlp"]["fc"], h, compute_dtype=compute_dtype)),
            compute_dtype=compute_dtype,
        )
    return x + m


def block_apply(block_params, x, *, cfg: GPTConfig, use_flash=False, compute_dtype=None):
    """Pre-LN transformer block (nanoGPT Block semantics). With
    `compute_dtype=bf16`, every matmul runs bf16 on the MXU while residuals
    and layer norms stay in the activation dtype."""
    return _block_core(
        block_params, x,
        lambda ap, h: causal_self_attention(
            ap, h, n_head=cfg.n_head, use_flash=use_flash, compute_dtype=compute_dtype
        ),
        cfg=cfg, compute_dtype=compute_dtype,
    )


def stack_blocks(params, layer_ids):
    """Stack per-layer block params along a leading axis (for lax.scan over
    layers, and for sharding the stack over a pipeline mesh axis).

    Do this ONCE at load time (see `prepare_stacked` / the pipeline engine),
    not per forward call — restacking is an O(params) copy."""
    blocks = [params[f"h_{i}"] for i in layer_ids]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def prepare_stacked(params, cfg: GPTConfig):
    """One-time load-side transform: {'h_0'..'h_{L-1}', ...} ->
    {'blocks': stacked, 'wte', 'wpe', 'ln_f', 'lm_head'} for use with
    `make_apply_stacked`. The stacked layout is also what the pipeline
    runtime shards over the 'stage' mesh axis."""
    out = {k: v for k, v in params.items() if not k.startswith("h_")}
    out["blocks"] = stack_blocks(params, range(cfg.n_layer))
    return out


def blocks_scan(stacked, x, *, cfg: GPTConfig, use_flash=False, compute_dtype=None,
                attn_fn=None, remat=False):
    """Run a stack of blocks via lax.scan: one compiled block body regardless
    of depth (the TPU-idiomatic form of the reference's Python
    `for block in self.h` loop, gpt_model_parts.py:20-21). `attn_fn`
    overrides the attention implementation (e.g. the sequence-parallel ring
    — see make_apply_seq_parallel); default is local causal MHA.

    `remat=True` wraps the block body in `jax.checkpoint`: the backward
    pass recomputes each block's internals instead of keeping all
    intermediates alive across the scan — activation memory drops from
    O(L x intermediates) to O(L x residual + 1 block), the standard
    FLOPs-for-HBM trade for training deep stacks."""

    def block(layer_params, carry):
        if attn_fn is None:
            return block_apply(layer_params, carry, cfg=cfg, use_flash=use_flash,
                               compute_dtype=compute_dtype)
        return _block_core(layer_params, carry, attn_fn, cfg=cfg,
                           compute_dtype=compute_dtype)

    if remat:
        block = jax.checkpoint(block)

    def body(carry, layer_params):
        return block(layer_params, carry), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


def embed(params, idx, *, cfg: GPTConfig):
    """Token + position embedding (ModelPart0 semantics,
    gpt_model_parts.py:13-18, incl. the T <= block_size guard)."""
    t = idx.shape[-1]
    if t > cfg.block_size:
        raise ValueError(f"Cannot forward: sequence length {t} > block_size {cfg.block_size}")
    pos = jnp.arange(t)
    with jax.named_scope("gpt.embed"):
        return embedding(params["wte"], idx) + embedding(params["wpe"], pos)


def head(params, x, *, cfg: GPTConfig, compute_dtype=None, logits_dtype=None):
    """Final LN + lm_head (ModelPartFinal_GPT semantics,
    gpt_model_parts.py:44-50).

    With `compute_dtype=bf16` the lm_head matmul reads bf16 operands and
    accumulates f32 (`preferred_element_type`) — logits stay f32. This is
    the dominant-cost matmul of a forward (C x V = 768 x 50257 for
    gpt2-small). On v5e the default f32 matmul "precision" is a bf16 MXU
    pass already, so output is bit-identical (measured: zero logit diff)
    and throughput is within noise; the explicit operand dtype matters on
    platforms where f32 matmul really runs f32, and makes the memory
    traffic intent visible rather than relying on a backend default.

    `logits_dtype=bf16` rounds the f32-accumulated logits on the way out
    (XLA fuses the cast into the matmul epilogue): the (B, T, V) logit
    write is the single largest HBM store of a forward — 823 MB at
    B=8/T=512/V=50257 in f32 — and halving it measures +11% end-to-end
    throughput on v5e (benchmarks/explore_fwd_perf.py). Accumulation is
    still f32; only the stored values are rounded. Default None keeps f32
    logits (the parity-test configuration)."""
    with jax.named_scope("gpt.head"):
        x = layer_norm(params["ln_f"], x, eps=cfg.ln_eps)
        if compute_dtype is None:
            out = linear(params["lm_head"], x)
        else:
            out = linear(params["lm_head"], x, compute_dtype=compute_dtype,
                         accum_dtype=jnp.float32)
        return out if logits_dtype is None else out.astype(logits_dtype)


def make_apply(cfg: GPTConfig, *, use_flash=False, compute_dtype=None, remat=False):
    """Full-model forward over the per-layer param layout (restacks blocks
    per call — fine under jit for tests/small models; perf paths should use
    `prepare_stacked` + `make_apply_stacked`). `remat=True` checkpoints
    each block for training memory (see blocks_scan)."""

    def apply(params, idx):
        x = embed(params, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        stacked = stack_blocks(params, range(cfg.n_layer))
        x = blocks_scan(stacked, x, cfg=cfg, use_flash=use_flash,
                        compute_dtype=compute_dtype, remat=remat)
        logits = head(params, x.astype(jnp.float32), cfg=cfg, compute_dtype=compute_dtype)
        return logits

    return apply


def make_hidden_stacked(cfg: GPTConfig, *, compute_dtype=None):
    """Final-normed hidden states over the prepare_stacked layout —
    make_apply_stacked minus the lm_head projection (== HF
    GPT2Model.last_hidden_state). The embedding endpoint's forward
    (runtime/embeddings.py); kept HERE so it can never drift from the
    logits forward below."""

    def hidden(prepared, idx):
        x = embed(prepared, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        x = blocks_scan(prepared["blocks"], x, cfg=cfg,
                        compute_dtype=compute_dtype)
        return layer_norm(prepared["ln_f"], x.astype(jnp.float32),
                          eps=cfg.ln_eps)

    return hidden


def make_apply_stacked(cfg: GPTConfig, *, use_flash=False, compute_dtype=None,
                       remat=False, logits_dtype=None):
    """Forward over `prepare_stacked` params: zero per-call restacking.
    When `compute_dtype` is set, the head matmul also runs in it (f32
    accumulation — see `head`). `logits_dtype=bf16` halves the logit
    store, the serving-path configuration (see `head`)."""

    def apply(prepared, idx):
        x = embed(prepared, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        x = blocks_scan(prepared["blocks"], x, cfg=cfg, use_flash=use_flash,
                        compute_dtype=compute_dtype, remat=remat)
        return head(prepared, x.astype(jnp.float32), cfg=cfg,
                    compute_dtype=compute_dtype, logits_dtype=logits_dtype)

    return apply


def prepare_tp_blocks(stacked_blocks, cfg: GPTConfig, tp: int):
    """One-time load-side transform for MANUAL (shard_map) tensor
    parallelism over the fused-qkv layout: reorder the qkv output columns
    SHARD-MAJOR so that slicing the last axis into `tp` equal parts hands
    each tensor-parallel rank its own n_head/tp heads of q, k AND v
    contiguously.

    The fused kernel stores columns as [Q(C) | K(C) | V(C)] (one matmul —
    ops/attention.py:52); naively sharding that axis would give rank 0 all
    of Q plus half of K at tp=2, which no local attention can use. After
    the reorder the columns read [Q_0 K_0 V_0 | Q_1 K_1 V_1 | ...] where
    X_t is rank t's head slice, so the sharded local (C, 3C/tp) kernel
    splits into three (C, C/tp) head-aligned pieces (make_tp_block_fn).
    attn.proj / mlp.* need no reorder: merged heads already put rank t's
    activation columns at rows [t*C/tp, (t+1)*C/tp) of the row-sharded
    projection, and the MLP hidden axis is a single contiguous block.

    Works on any leaf layout whose LAST axis is the fused 3C — per-layer,
    (L, ...)-stacked, or (S, L/S, ...)-stage-stacked trees alike."""
    if cfg.n_head % tp:
        raise ValueError(f"n_head {cfg.n_head} not divisible by tp {tp}")
    c = cfg.n_embd
    shard = c // tp

    def reorder(a):  # (..., 3C) -> (..., 3C) shard-major
        q, k, v = a[..., :c], a[..., c:2 * c], a[..., 2 * c:]
        parts = []
        for t in range(tp):
            sl = slice(t * shard, (t + 1) * shard)
            parts += [q[..., sl], k[..., sl], v[..., sl]]
        return jnp.concatenate(parts, axis=-1)

    return {
        **stacked_blocks,
        "attn": {
            **stacked_blocks["attn"],
            "qkv": {
                "kernel": reorder(stacked_blocks["attn"]["qkv"]["kernel"]),
                "bias": reorder(stacked_blocks["attn"]["qkv"]["bias"]),
            },
        },
    }


def make_tp_block_fn(cfg: GPTConfig, *, axis_name=None, compute_dtype=None,
                     remat=False):
    """Tensor-parallel stacked-block function for the pipeline runtimes —
    the Megatron recipe inside shard_map (TP x PP composition):

      * qkv and mlp.fc are COLUMN-parallel: the local kernel holds this
        rank's output slice ((C, 3C/tp) head-aligned via prepare_tp_blocks,
        (C, 4C/tp) hidden slice), operand replicated, no communication;
      * attention runs on the rank's own n_head/tp heads (heads are
        independent, so local heads need no collective);
      * attn.proj and mlp.proj are ROW-parallel: local (C/tp, C) /
        (4C/tp, C) kernels produce partial sums combined by one
        `lax.psum`, with the replicated bias added ONCE after the reduce.

    Two psums per block over the `model` axis — the standard Megatron
    count. Unlike classic Megatron there is NO explicit conjugate `f`/`g`
    operator at the column-parallel inputs: shard_map's AD tracks per-axis
    replication and inserts the exact transposes itself (gradient parity
    vs the 1D pipeline is pinned by tests/test_tp_pp.py — see the note in
    parallel/collectives.py). Returns block_fn(local_stacked, x) for
    `spmd_pipeline_stacked(..., model_axis=...)`, where local_stacked
    leaves carry (L_per_stage, ...) with model-sharded trailing dims.
    `remat=True` checkpoints each block body (backward recomputes block
    internals; the two forward psums replay in the recompute)."""
    from jax import lax

    from dnn_tpu.ops.pallas.flash_attention import reference_attention
    from dnn_tpu.parallel.mesh import MODEL_AXIS

    axis = axis_name or MODEL_AXIS

    def one_block(bp, x):
        tp = lax.axis_size(axis)
        local_heads = cfg.n_head // tp
        from dnn_tpu.ops.attention import merge_heads, split_heads

        h = layer_norm(bp["ln_1"], x, eps=cfg.ln_eps)
        qkv = linear(bp["attn"]["qkv"], h, compute_dtype=compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (split_heads(t, local_heads) for t in (q, k, v))
        y = merge_heads(reference_attention(q, k, v, causal=True))
        att = linear({"kernel": bp["attn"]["proj"]["kernel"]}, y,
                     compute_dtype=compute_dtype)
        att = lax.psum(att, axis) + bp["attn"]["proj"]["bias"].astype(x.dtype)
        x = x + att

        h = layer_norm(bp["ln_2"], x, eps=cfg.ln_eps)
        m = gelu(linear(bp["mlp"]["fc"], h, compute_dtype=compute_dtype))
        mm = linear({"kernel": bp["mlp"]["proj"]["kernel"]}, m,
                    compute_dtype=compute_dtype)
        mm = lax.psum(mm, axis) + bp["mlp"]["proj"]["bias"].astype(x.dtype)
        return x + mm

    if remat:
        one_block = jax.checkpoint(one_block)

    def block_fn(local, x):
        def body(carry, lp):
            return one_block(lp, carry), None

        out, _ = jax.lax.scan(body, x, local)
        return out

    return block_fn


def make_apply_seq_parallel(cfg: GPTConfig, mesh, *, axis_name=None,
                            compute_dtype=None, method: str = "ring"):
    """Sequence-parallel (long-context) full-model forward.

    The reference hard-caps sequence length (`T <= block_size` assert,
    gpt_model_parts.py:15) and holds every activation whole on one device.
    This path shards the SEQUENCE dimension over the mesh's "seq" axis:
    embed/LN/MLP/head act position-wise and run on local shards; attention
    crosses shards via one of two strategies (`method`):

      * "ring": K/V blocks rotate the ring via `lax.ppermute` with
        online-softmax accumulation (dnn_tpu/parallel/ring_attention.py) —
        per-device activation memory is O(T/n) and the full (T, T) score
        matrix never exists anywhere; works for any head count.
      * "ulysses": two `lax.all_to_all`s swap sequence sharding for head
        sharding around one dense local attention
        (dnn_tpu/parallel/ulysses.py) — fewer, denser collectives;
        needs n_head divisible by the axis size.

    `apply(prepared, ids)`: `prepared` from `prepare_stacked` (replicated);
    ids (B, T) with T divisible by the seq-axis size. Returns f32 logits
    sharded over the sequence axis.
    """
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.ops.attention import merge_heads, split_heads
    from dnn_tpu.parallel.mesh import SEQ_AXIS
    from dnn_tpu.parallel.ring_attention import ring_attention_local
    from dnn_tpu.parallel.ulysses import ulysses_attention_local

    if method not in ("ring", "ulysses"):
        raise ValueError(f"method must be ring|ulysses, got {method!r}")
    axis = axis_name or SEQ_AXIS
    if method == "ulysses" and cfg.n_head % mesh.shape[axis] != 0:
        raise ValueError(
            f"ulysses needs n_head ({cfg.n_head}) divisible by the seq-axis "
            f"size ({mesh.shape[axis]}); use method='ring'"
        )

    def ring_attn(attn_params, h):
        qkv = linear(attn_params["qkv"], h, compute_dtype=compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (split_heads(t, cfg.n_head) for t in (q, k, v))
        if method == "ring":
            y = ring_attention_local(q, k, v, axis_name=axis, causal=True)
        else:
            y = ulysses_attention_local(q, k, v, axis_name=axis, causal=True)
        return linear(attn_params["proj"], merge_heads(y), compute_dtype=compute_dtype)

    def local_fn(prepared, ids_local):
        t_local = ids_local.shape[-1]
        my = jax.lax.axis_index(axis)
        pos = my * t_local + jnp.arange(t_local)  # global positions
        x = embedding(prepared["wte"], ids_local) + embedding(prepared["wpe"], pos)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        x = blocks_scan(prepared["blocks"], x, cfg=cfg,
                        compute_dtype=compute_dtype, attn_fn=ring_attn)
        return head(prepared, x.astype(jnp.float32), cfg=cfg,
                    compute_dtype=compute_dtype)

    def apply(prepared, ids):
        t = ids.shape[-1]
        if t > cfg.block_size:
            raise ValueError(
                f"Cannot forward: sequence length {t} > block_size {cfg.block_size}"
            )
        n = mesh.shape[axis]
        if t % n != 0:
            raise ValueError(f"sequence length {t} not divisible by seq axis size {n}")
        return jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(P(), P(None, axis)),
            out_specs=P(None, axis, None),
            check_vma=False,
        )(prepared, ids)

    return apply


# --------------------------------------------------------------------------
# partitioning (mirrors gpt_model_parts.py stage layout)
# --------------------------------------------------------------------------

def layer_ranges(n_layer: int, num_parts: int):
    """Split n_layer blocks into num_parts contiguous ranges, earlier stages
    taking the remainder (matches the reference's inclusive
    [start_layer, end_layer] convention, gpt_model_parts.py:12,30,40)."""
    if not 1 <= num_parts <= n_layer:
        raise ValueError(f"num_parts must be in [1, {n_layer}], got {num_parts}")
    base, rem = divmod(n_layer, num_parts)
    ranges, lo = [], 0
    for p in range(num_parts):
        hi = lo + base + (1 if p < rem else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def make_partition(cfg: GPTConfig, *, use_flash=False, compute_dtype=None):
    def partition(num_parts):
        ranges = layer_ranges(cfg.n_layer, num_parts)
        stages = []
        for p, (lo, hi) in enumerate(ranges):
            is_first, is_last = p == 0, p == num_parts - 1
            hkeys = tuple(f"h_{i}" for i in range(lo, hi))
            param_keys = hkeys
            if is_first:
                param_keys = ("wte", "wpe") + param_keys
            if is_last:
                param_keys = param_keys + ("ln_f", "lm_head")

            def stage_fn(params, x, _lo=lo, _hi=hi, _first=is_first, _last=is_last):
                if _first:
                    x = embed(params, x, cfg=cfg)
                if compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(compute_dtype)
                if _hi > _lo:
                    stacked = stack_blocks(params, range(_lo, _hi))
                    x = blocks_scan(
                        stacked, x, cfg=cfg, use_flash=use_flash, compute_dtype=compute_dtype
                    )
                if _last:
                    x = head(params, x.astype(jnp.float32), cfg=cfg,
                             compute_dtype=compute_dtype)
                return x

            stages.append(
                StageSpec(
                    name=f"gpt_blocks[{lo}:{hi}]"
                    + ("+embed" if is_first else "")
                    + ("+head" if is_last else ""),
                    apply=stage_fn,
                    param_keys=param_keys,
                )
            )
        return stages

    return partition


def make_example_input(cfg: GPTConfig):
    def example_input(batch_size=1, seq_len=None, rng=None):
        t = min(seq_len or cfg.block_size, cfg.block_size)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        return jax.random.randint(rng, (batch_size, t), 0, cfg.vocab_size, dtype=jnp.int32)

    return example_input


def _register(name: str, cfg: GPTConfig):
    def convert(sd, _cfg=cfg):
        from dnn_tpu.io.checkpoint import gpt_params_from_state_dict

        return gpt_params_from_state_dict(sd, n_layer=_cfg.n_layer)

    register_model(
        ModelSpec(
            name=name,
            init=lambda rng, dtype=jnp.float32, _cfg=cfg: init(rng, _cfg, dtype),
            apply=make_apply(cfg),
            partition=make_partition(cfg),
            example_input=make_example_input(cfg),
            supported_parts=tuple(range(1, cfg.n_layer + 1)),
            convert_state_dict=convert,
            config=cfg,
            extras={
                # dtype/flash-aware factories so the engine can honor the
                # config's `dtype` key (make_apply/make_partition above are
                # the f32 defaults).
                "make_apply": lambda compute_dtype=None, use_flash=False, _cfg=cfg: make_apply(
                    _cfg, compute_dtype=compute_dtype, use_flash=use_flash
                ),
                "make_partition": lambda compute_dtype=None, use_flash=False, _cfg=cfg: make_partition(
                    _cfg, compute_dtype=compute_dtype, use_flash=use_flash
                ),
            },
        )
    )


for _name, _cfg in PRESETS.items():
    _register(_name, _cfg)
