"""Mixtral: the LLaMA block with a sparse mixture-of-experts MLP.

No counterpart exists in the reference (no MoE anywhere — SURVEY §2);
this closes the last major open-weight family gap in the zoo: Mixtral =
LLaMA attention (GQA, RoPE, RMSNorm) + per-layer top-2-of-8 SwiGLU
experts with renormalized routing.

TPU-first composition, not a new model implementation:

  * the block is llama.py's — every Mixtral path (dense forward, cached
    decode, batcher rows, speculative verify) is the LLaMA path with the
    `ffn` hook installed, so parity contracts and runtime features
    (int8 caches, constraints, streaming, beam) carry over wherever the
    hook threads;
  * the expert math is parallel/moe.py's GShard-style static-capacity
    dispatch with the GATED expert stack (silu(x@wg)*(x@wu)@wd — one
    batched matmul triple over (E, cap, D)); `route_topk(normalize=True)`
    IS Mixtral's routing (softmax over all experts, take top-k,
    renormalize the selected weights);
  * capacity is the TPU-shaped trade: HF computes every selected token
    densely, we cap per-expert slots for static shapes. With
    `capacity_factor >= n_expert` nothing can drop and logits match HF
    exactly (the parity-test setting); serving configs size it down and
    dropped tokens degrade to the residual (the standard MoE fallback).

Param pytree: llama's, with each block's "mlp" replaced by
  "moe": {"router": {"kernel" (D, E)}, "wg"/"wu" (E, D, F), "wd" (E, F, D)}
(HF MixtralForCausalLM: block_sparse_moe.gate + experts.i.{w1,w3,w2}).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from dnn_tpu.models import gpt, llama
from dnn_tpu.parallel.moe import init_moe_gated, moe_ffn
from dnn_tpu.registry import ModelSpec, register_model


@dataclasses.dataclass(frozen=True)
class MixtralConfig(llama.LlamaConfig):
    n_expert: int = 8
    router_top_k: int = 2
    # >= n_expert guarantees no token ever drops (parity configs);
    # serving configs trade capacity for static-shape efficiency
    capacity_factor: float = 8.0
    # ---- Qwen2-MoE-class switches (defaults = Mixtral semantics) ----
    # Always-on SHARED expert (DeepSeek/Qwen-MoE recipe): a dense SwiGLU
    # of width d_shared whose output, scaled by a per-token sigmoid gate
    # (shared_expert_gate), adds to the routed experts' output.
    d_shared: Optional[int] = None
    # True (Mixtral): renormalize the selected top-k router weights.
    # False (Qwen2-MoE norm_topk_prob=false): keep raw softmax probs.
    router_norm_topk: bool = True

    def default_ffn(self, compute_dtype=None):
        """The config-resolved MLP override every llama runtime entry
        point picks up (LlamaConfig.default_ffn) — beam, speculative,
        embeddings, partitions, and the family adapter all route through
        the experts without Mixtral-specific dispatch."""
        return make_ffn(self, compute_dtype=compute_dtype)


PRESETS = {
    # Mixtral-8x7B shape: LLaMA-2-ish block, GQA 4:1, 8 experts top-2
    "mixtral-8x7b": MixtralConfig(block_size=32768, vocab_size=32000,
                                  n_layer=32, n_head=32, n_kv_head=8,
                                  n_embd=4096, d_ff=14336,
                                  rope_theta=1_000_000.0, rms_eps=1e-5,
                                  n_expert=8, router_top_k=2),
    # tiny config for tests/CI (4 experts top-2, GQA 2:1)
    "mixtral-test": MixtralConfig(block_size=64, vocab_size=256,
                                  n_layer=3, n_head=4, n_kv_head=2,
                                  n_embd=64, d_ff=128,
                                  n_expert=4, router_top_k=2,
                                  capacity_factor=4.0),
    # Qwen1.5-MoE-A2.7B shape: Qwen2 attention (q/k/v biases), 60
    # fine-grained experts top-4 with RAW softmax weights
    # (norm_topk_prob=false), plus the always-on sigmoid-gated shared
    # expert — the modern shared-expert MoE recipe
    "qwen15-moe-a2.7b": MixtralConfig(block_size=8192, vocab_size=151936,
                                      n_layer=24, n_head=16, n_kv_head=16,
                                      n_embd=2048, d_ff=1408,
                                      rope_theta=1_000_000.0,
                                      rms_eps=1e-6, attn_bias=True,
                                      n_expert=60, router_top_k=4,
                                      # no-drop (>= n_expert): the HF
                                      # parity convention; serving can
                                      # size it down (capacity trade)
                                      capacity_factor=60.0,
                                      d_shared=5632,
                                      router_norm_topk=False),
    # tiny shared-expert config for tests (every switch acts: biases,
    # raw top-k weights, shared expert + gate)
    "qwen2moe-test": MixtralConfig(block_size=64, vocab_size=256,
                                   n_layer=3, n_head=4, n_kv_head=2,
                                   n_embd=64, d_ff=32, attn_bias=True,
                                   n_expert=4, router_top_k=2,
                                   capacity_factor=4.0, d_shared=96,
                                   router_norm_topk=False),
}


def _shared_expert_out(moe_p, h, *, compute_dtype=None):
    """The always-on shared expert (Qwen2-MoE / DeepSeek recipe): a
    dense SwiGLU over h, scaled per token by sigmoid(h @ shared_gate).
    Adds to the ROUTED output — identical math on the dense-grouped and
    EP paths (the shared weights replicate; only routed experts
    shard)."""
    from dnn_tpu.ops.nn import linear, silu

    sp = moe_p["shared"]
    s = linear(sp["down"],
               silu(linear(sp["gate"], h, compute_dtype=compute_dtype))
               * linear(sp["up"], h, compute_dtype=compute_dtype),
               compute_dtype=compute_dtype)
    g = jax.nn.sigmoid(
        linear(moe_p["shared_gate"], h,
               compute_dtype=compute_dtype).astype(jnp.float32))
    return (g * s.astype(jnp.float32)).astype(h.dtype)


def _local_ep_ffn(cfg: MixtralConfig, *, axis: str, capacity: int,
                  compute_dtype=None):
    """The per-device EP ffn closure every expert-parallel builder
    installs (make_apply_ep, make_generate_ep, make_pipeline_generate_ep
    — ONE definition so a new MoE switch cannot silently diverge
    between them): routed experts via moe_ffn_local (all_to_all over
    `axis`), plus the locally-computed shared expert for d_shared
    configs (its weights replicate; only routed experts shard)."""
    from dnn_tpu.parallel.moe import moe_ffn_local

    def ffn(bp, h):
        d = h.shape[-1]
        out = moe_ffn_local(
            bp["moe"], h.reshape(-1, d), top_k=cfg.router_top_k,
            capacity=capacity, axis_name=axis,
            compute_dtype=compute_dtype,
            normalize=cfg.router_norm_topk,
        ).reshape(h.shape).astype(h.dtype)
        if cfg.d_shared:
            out = out + _shared_expert_out(bp["moe"], h,
                                           compute_dtype=compute_dtype)
        return out

    return ffn


def make_ffn(cfg: MixtralConfig, *, compute_dtype=None, groups: int = 1):
    """The llama `ffn` hook: (block_params, h) -> MoE MLP output.
    `groups` must match between paths that share a cache for
    token-identical decode (1 everywhere by default)."""

    def ffn(bp, h):
        out = moe_ffn(bp["moe"], h, top_k=cfg.router_top_k,
                      capacity_factor=cfg.capacity_factor, groups=groups,
                      compute_dtype=compute_dtype,
                      normalize=cfg.router_norm_topk)
        if cfg.d_shared:
            out = out + _shared_expert_out(bp["moe"], h,
                                           compute_dtype=compute_dtype)
        return out

    return ffn


def init(rng, cfg: MixtralConfig = PRESETS["mixtral-test"],
         dtype=jnp.float32):
    """llama.init minus the dense MLPs (include_mlp=False — no transient
    dense weights at 8x7b scale), plus each block's gated expert stack
    (and, for d_shared configs, the always-on shared expert + its
    sigmoid gate)."""
    import math

    params = llama.init(rng, cfg, dtype, include_mlp=False)
    keys = jax.random.split(jax.random.fold_in(rng, 7), cfg.n_layer)
    for i in range(cfg.n_layer):
        moe = init_moe_gated(keys[i], cfg.n_embd, cfg.n_expert, cfg.d_ff,
                             dtype)
        if cfg.d_shared:
            ks = jax.random.split(jax.random.fold_in(keys[i], 1), 4)
            si = 1.0 / math.sqrt(cfg.n_embd)
            so = 1.0 / math.sqrt(cfg.d_shared)
            moe["shared"] = {
                "gate": {"kernel": (jax.random.normal(
                    ks[0], (cfg.n_embd, cfg.d_shared)) * si).astype(dtype)},
                "up": {"kernel": (jax.random.normal(
                    ks[1], (cfg.n_embd, cfg.d_shared)) * si).astype(dtype)},
                "down": {"kernel": (jax.random.normal(
                    ks[2], (cfg.d_shared, cfg.n_embd)) * so).astype(dtype)},
            }
            moe["shared_gate"] = {"kernel": (jax.random.normal(
                ks[3], (cfg.n_embd, 1)) * si).astype(dtype)}
        params[f"h_{i}"]["moe"] = moe
    return params


def make_apply(cfg: MixtralConfig, *, compute_dtype=None, remat=False):
    # cfg.default_ffn resolves the expert hook inside llama.make_apply
    return llama.make_apply(cfg, compute_dtype=compute_dtype, remat=remat)


def make_generate(cfg: MixtralConfig, *, max_new_tokens: int,
                  temperature: float = 0.0, top_k: Optional[int] = None,
                  top_p: Optional[float] = None, compute_dtype=None,
                  kv_dtype=None, attn_kernel="auto"):
    """llama.make_generate with the MoE hook (config-resolved) — prefill
    routes (B, T) tokens, each decode step routes (B, 1); same KV-width
    GQA cache, same attn_kernel/kv_dtype options."""
    return llama.make_generate(
        cfg, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, compute_dtype=compute_dtype,
        kv_dtype=kv_dtype, attn_kernel=attn_kernel)


def family_rows(cfg: MixtralConfig, *, compute_dtype=None,
                attn_kernel="auto"):
    """ContinuousBatcher adapter: LlamaFamilyRows resolves the MoE hook
    from the config — prefill chunks, per-slot decode rows, and
    speculative verify all route through the experts."""
    return llama.LlamaFamilyRows(cfg, compute_dtype=compute_dtype,
                                 attn_kernel=attn_kernel)


def _ep_param_spec(path, leaf, *, axis, stage_axis=None):
    """PartitionSpec for one param leaf under expert parallelism, derived
    from the ACTUAL pytree (config variants — attn_bias, post-norms,
    tied/no-lm_head — shard correctly instead of tripping a hardcoded
    structure): only the expert stacks shard on their E axis; everything
    else replicates (or shards over `stage_axis` for pipeline stage
    blocks, whose leaves carry a leading (S, per_stage, ...) so E sits at
    index 2)."""
    from jax.sharding import PartitionSpec as P

    keys = [p.key for p in path if hasattr(p, "key")]
    expert_leaf = "moe" in keys and keys and keys[-1] in (
        "wg", "wu", "wd", "wg_scale", "wu_scale", "wd_scale")
    if stage_axis is not None:
        if expert_leaf:
            return P(stage_axis, None, axis)
        return P(stage_axis)
    if expert_leaf:
        return P(None, axis)
    return P()


def make_apply_ep(cfg: MixtralConfig, mesh, *, axis_name: Optional[str] = None,
                  compute_dtype=None):
    """Expert-parallel Mixtral forward over `mesh`'s expert axis — the
    GShard fabric (parallel/moe.moe_ffn_local: two all_to_alls move
    tokens to their experts' owners and back over ICI) under the llama
    block via the ffn hook.

    apply(params, ids): ids (B, T), B divisible by the axis size; the
    batch shards over the expert axis (each device's local batch is its
    routing group), expert stacks shard on their E axis, attention/norm
    weights replicate. Identical math to the dense forward with
    `make_ffn(cfg, groups=n)` — the parity contract
    tests/test_mixtral.py pins (same as the GPT-MoE family's)."""
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.parallel.mesh import EXPERT_AXIS
    from dnn_tpu.parallel.moe import moe_capacity, moe_ffn_local

    axis = axis_name or EXPERT_AXIS
    n = mesh.shape[axis]
    if cfg.n_expert % n:
        raise ValueError(
            f"n_expert={cfg.n_expert} not divisible by axis size {n}")

    def local_fn(prep_local, ids_local):
        x = llama._scaled_embed(prep_local, ids_local, cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        b_local, t = ids_local.shape
        s = b_local * t  # this device's tokens = one routing group
        capacity = moe_capacity(s, cfg.n_expert, cfg.router_top_k,
                                cfg.capacity_factor)

        ep_ffn = _local_ep_ffn(cfg, axis=axis, capacity=capacity,
                               compute_dtype=compute_dtype)

        x = llama.blocks_scan(prep_local["blocks"], x, cfg=cfg,
                              compute_dtype=compute_dtype, ffn=ep_ffn,
                              windows=llama.layer_windows(cfg))
        return llama.head(prep_local, x.astype(jnp.float32), cfg=cfg,
                          compute_dtype=compute_dtype)

    def apply(params, ids):
        b = ids.shape[0]
        if b % n:
            raise ValueError(
                f"batch {b} not divisible by expert-axis size {n}")
        prepared = _as_prepared(params, cfg)
        param_specs = jax.tree_util.tree_map_with_path(
            lambda p, leaf: _ep_param_spec(p, leaf, axis=axis), prepared)
        return jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(param_specs, P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )(prepared, ids)

    return apply


def _as_prepared(params, cfg):
    """Accept either the raw h_i layout or the stacked-blocks layout."""
    if "blocks" in params:
        return params
    prepared = {k: v for k, v in params.items() if not k.startswith("h_")}
    prepared["blocks"] = gpt.stack_blocks(params, range(cfg.n_layer))
    return prepared


def make_generate_ep(cfg: MixtralConfig, mesh, *, max_new_tokens: int,
                     temperature: float = 0.0,
                     sample_top_k: Optional[int] = None,
                     compute_dtype=None, kv_dtype=None,
                     axis_name: Optional[str] = None):
    """Expert-parallel Mixtral KV-cache generation over `mesh`'s expert
    axis — the serving form of make_apply_ep: the WHOLE generate (prefill
    + lax.scan decode) is one shard_map program; batch and its KV cache
    shard over the expert axis (each device's local batch is its routing
    group, so the cache lives with the tokens it serves), expert stacks
    shard on E, and tokens reach their experts via all_to_all inside
    every prefill and decode-step forward
    (parallel/moe.moe_ffn_local).

    generate(params, ids, rng): ids (B, T), B divisible by the axis size.
    Greedy output equals the solo decoder with `make_ffn(cfg,
    groups=axis_size)` token-for-token (same per-column routing groups —
    the GPT-MoE family's EP parity contract, generate_moe.py, extended to
    this family); sampled output folds the device index into the rng
    stream, matching in distribution rather than draw-for-draw."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.parallel.mesh import EXPERT_AXIS
    from dnn_tpu.parallel.moe import moe_capacity, moe_ffn_local
    from dnn_tpu.runtime.generate import _sample

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    axis = axis_name or EXPERT_AXIS
    n = mesh.shape[axis]
    if cfg.n_expert % n:
        raise ValueError(
            f"n_expert={cfg.n_expert} not divisible by axis size {n}")

    def per_device(prep_local, ids_local, rng):
        b, t = ids_local.shape  # local batch = this device's routing group
        s_max = t + max_new_tokens
        cache_dtype = kv_dtype if kv_dtype is not None else (
            compute_dtype or jnp.float32)
        cache = llama.init_cache(cfg, b, s_max, cache_dtype)

        def ffn_for(tokens_per_group):
            capacity = moe_capacity(tokens_per_group, cfg.n_expert,
                                    cfg.router_top_k, cfg.capacity_factor)
            return _local_ep_ffn(cfg, axis=axis, capacity=capacity,
                                 compute_dtype=compute_dtype)

        logits, cache = llama.forward_with_cache(
            prep_local, ids_local, cache, 0, cfg=cfg,
            compute_dtype=compute_dtype, ffn=ffn_for(b * t),
            attn_kernel=False)  # inside shard_map: keep the einsum
        rng = jax.random.fold_in(rng, lax.axis_index(axis))
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature=temperature,
                      top_k=sample_top_k)
        step_ffn = ffn_for(b)

        def step(carry, i):
            cache, tok, rng = carry
            logits, cache = llama.forward_with_cache(
                prep_local, tok[:, None], cache, t + i, cfg=cfg,
                compute_dtype=compute_dtype, ffn=step_ffn,
                attn_kernel=False)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=sample_top_k)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    @jax.jit
    def generate(params, ids, rng):
        b, t = ids.shape
        if b % n:
            raise ValueError(
                f"batch {b} not divisible by expert-axis size {n}")
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        prepared = _as_prepared(params, cfg)
        param_specs = jax.tree_util.tree_map_with_path(
            lambda p, leaf: _ep_param_spec(p, leaf, axis=axis), prepared)
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(param_specs, P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )(prepared, ids, rng)

    return generate


def make_pipeline_generate_ep(cfg: MixtralConfig, mesh, *,
                              max_new_tokens: int,
                              temperature: float = 0.0,
                              sample_top_k: Optional[int] = None,
                              compute_dtype=None, kv_dtype=None,
                              stage_axis: Optional[str] = None,
                              expert_axis: Optional[str] = None):
    """EP x PP 2D Mixtral decode over a {stage, expert} mesh — the llama
    -family mirror of generate_moe.make_pipeline_generate_moe_ep: layers
    shard over the STAGE axis (the ppermute decode ring, KV-head-width
    stage cache shards), each stage's expert stacks shard over the EXPERT
    axis, tokens reach their experts via all_to_all WITHIN the stage row
    while the hidden state rides the stage ring — both collectives per
    decode step, each on its own mesh axis.

    generate(stage_blocks, aux, ids, rng): `stage_blocks` from
    runtime.generate.prepare_pipeline_stacked (expert leaves are
    re-placed over the expert axis here); ids (B, T), B divisible by the
    expert-axis size. Greedy output equals the solo decoder with
    `make_ffn(cfg, groups=n_exp)` token-for-token.

    Same deliberate schedule duplication as the GPT EP x PP decoder (see
    generate_moe.py's NOTE): the capacity-dependent ffn (one compiled
    program for the prefill chunk, another for decode steps) cannot ride
    the one-block-function family-adapter protocol."""
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.parallel.mesh import EXPERT_AXIS, STAGE_AXIS
    from dnn_tpu.parallel.moe import moe_capacity, moe_ffn_local
    from dnn_tpu.runtime.generate import _sample
    from dnn_tpu.runtime.kvcache import codec_for_cache

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if cfg.alt_window:
        raise ValueError(
            "alternating-window configs are not supported on the pipeline "
            "decode path (no per-layer window channel in the stage scan)")
    s_axis = stage_axis or STAGE_AXIS
    e_axis = expert_axis or EXPERT_AXIS
    num_stages = mesh.shape[s_axis]
    n_exp = mesh.shape[e_axis]
    if cfg.n_layer % num_stages:
        raise ValueError(
            f"n_layer {cfg.n_layer} not divisible by {num_stages} stages")
    if cfg.n_expert % n_exp:
        raise ValueError(
            f"n_expert {cfg.n_expert} not divisible by expert axis {n_exp}")
    per_stage = cfg.n_layer // num_stages
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def _place(stage_blocks):
        specs = jax.tree_util.tree_map_with_path(
            lambda p, leaf: _ep_param_spec(p, leaf, axis=e_axis,
                                           stage_axis=s_axis), stage_blocks)
        return jax.device_put(
            stage_blocks,
            jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                         is_leaf=lambda x: isinstance(x, P)),
        ), specs

    def per_device(stage_blocks, aux, ids_local, rng):
        local = jax.tree.map(lambda p: p[0], stage_blocks)  # (per, ...)
        d = lax.axis_index(s_axis)
        b, t = ids_local.shape  # local batch = this expert column's group
        s_max = t + max_new_tokens
        cache_dtype = kv_dtype if kv_dtype is not None else (
            compute_dtype or jnp.float32)
        stage_cfg = dataclasses.replace(cfg, n_layer=per_stage)
        cache = llama.init_cache(stage_cfg, b, s_max, cache_dtype)
        codec = codec_for_cache(cache, window=cfg.sliding_window,
                                softcap=cfg.attn_softcap)

        def ffn_for(tokens_per_group):
            capacity = moe_capacity(tokens_per_group, cfg.n_expert,
                                    cfg.router_top_k, cfg.capacity_factor)
            return _local_ep_ffn(cfg, axis=e_axis, capacity=capacity,
                                 compute_dtype=compute_dtype)

        def ring_pass(x, cache, start_pos, ffn):
            def sub(carry, s):
                h, cache = carry

                def layer(carry2, layer_in):
                    bp, layer_cache = layer_in
                    return llama._block_with_cache(
                        bp, carry2, layer_cache, start_pos, cfg=cfg,
                        compute_dtype=compute_dtype, codec=codec, ffn=ffn)

                h2, cache2 = lax.scan(layer, h, (local, cache))
                active = d == s
                cache = jax.tree.map(
                    lambda new, old: jnp.where(active, new, old),
                    cache2, cache)
                h = lax.ppermute(h2, s_axis, perm)
                return (h, cache), None

            (h, cache), _ = lax.scan(sub, (x, cache), jnp.arange(num_stages))
            return h, cache

        def sample_last(h, sub_rng):
            logits = llama.head(aux, h[:, -1:].astype(jnp.float32), cfg=cfg,
                                compute_dtype=compute_dtype)
            tok = _sample(logits[:, -1], sub_rng, temperature=temperature,
                          top_k=sample_top_k)
            return lax.psum(
                jnp.where(d == 0, tok, jnp.zeros_like(tok)), s_axis)

        rng = jax.random.fold_in(rng, lax.axis_index(e_axis))
        x = llama._scaled_embed(aux, ids_local, cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        h, cache = ring_pass(x, cache, 0, ffn_for(b * t))
        rng, sub = jax.random.split(rng)
        tok = sample_last(h, sub)
        step_ffn = ffn_for(b)

        def step(carry, i):
            cache, tok, rng = carry
            x = llama._scaled_embed(aux, tok[:, None], cfg)
            if compute_dtype is not None:
                x = x.astype(compute_dtype)
            h, cache = ring_pass(x, cache, t + i, step_ffn)
            rng, sub = jax.random.split(rng)
            nxt = sample_last(h, sub)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    compiled = {}  # one jitted program per param-tree structure

    def generate(stage_blocks, aux, ids, rng):
        b, t = ids.shape
        if b % n_exp:
            raise ValueError(
                f"batch {b} not divisible by expert-axis size {n_exp}")
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        placed, specs = _place(stage_blocks)
        key = jax.tree_util.tree_structure(stage_blocks)
        if key not in compiled:
            compiled[key] = jax.jit(jax.shard_map(
                per_device, mesh=mesh,
                in_specs=(specs, P(), P(e_axis), P()),
                out_specs=P(e_axis),
                check_vma=False,
            ))
        return compiled[key](placed, aux, ids, rng)

    return generate


# --------------------------------------------------------------------------
# HF conversion
# --------------------------------------------------------------------------

def params_from_state_dict(sd, *, n_layer: Optional[int] = None):
    """HF MixtralForCausalLM OR Qwen2MoeForCausalLM state dict -> this
    pytree (layout auto-detected from the keys). Attention/norm/embed
    leaves ride checkpoint.llama_params_from_state_dict's mapping; each
    layer's MoE converts here: the router weight (E, D) -> kernel
    (D, E); per-expert SwiGLU triples stack expert-major to wg/wu/wd
    (Mixtral: block_sparse_moe.experts.i.{w1,w3,w2}; Qwen2-MoE:
    mlp.experts.i.{gate,up,down}_proj, plus mlp.shared_expert.* and the
    sigmoid shared_expert_gate)."""
    import numpy as np

    sd = {(k[len("model."):] if k.startswith("model.") else k): v
          for k, v in sd.items()}
    if any(".mlp.experts." in k for k in sd):
        return _qwen2_moe_from_sd(sd, n_layer=n_layer)
    if n_layer is None:
        n_layer = 1 + max(
            int(k.split(".")[1]) for k in sd
            if k.startswith("layers.") and k.split(".")[1].isdigit())

    # attention/norms/embed via the llama converter on a filtered dict
    # (it requires mlp.* keys, which Mixtral does not have — feed it
    # per-layer aliases pointing at one expert, then overwrite)
    base_keys = {k: v for k, v in sd.items() if "block_sparse_moe" not in k}
    for i in range(n_layer):
        p = f"layers.{i}."
        e0 = p + "block_sparse_moe.experts.0."
        base_keys[p + "mlp.gate_proj.weight"] = sd[e0 + "w1.weight"]
        base_keys[p + "mlp.up_proj.weight"] = sd[e0 + "w3.weight"]
        base_keys[p + "mlp.down_proj.weight"] = sd[e0 + "w2.weight"]
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(base_keys, n_layer=n_layer)

    def _t(w):  # torch Linear (out, in) -> (in, out)
        return np.ascontiguousarray(np.asarray(w).T)

    for i in range(n_layer):
        p = f"layers.{i}.block_sparse_moe."
        n_expert = 1 + max(
            int(k[len(p + "experts."):].split(".")[0]) for k in sd
            if k.startswith(p + "experts."))
        blk = dict(params[f"h_{i}"])
        del blk["mlp"]
        blk["moe"] = {
            "router": {"kernel": _t(sd[p + "gate.weight"])},
            "wg": np.stack([_t(sd[f"{p}experts.{e}.w1.weight"])
                            for e in range(n_expert)]),
            "wu": np.stack([_t(sd[f"{p}experts.{e}.w3.weight"])
                            for e in range(n_expert)]),
            "wd": np.stack([_t(sd[f"{p}experts.{e}.w2.weight"])
                            for e in range(n_expert)]),
        }
        params[f"h_{i}"] = blk
    return params


def _qwen2_moe_from_sd(sd, *, n_layer: Optional[int] = None):
    """Qwen2MoeForCausalLM layout (already model.-stripped): routed
    experts under mlp.experts.i.{gate,up,down}_proj, router under
    mlp.gate, shared expert + its scalar gate alongside."""
    import numpy as np

    if n_layer is None:
        n_layer = 1 + max(
            int(k.split(".")[1]) for k in sd
            if k.startswith("layers.") and k.split(".")[1].isdigit())

    # attention/norms/embed via the llama converter on a filtered dict
    # (it requires mlp.* keys; feed it per-layer aliases pointing at one
    # expert, then overwrite — the Mixtral converter's trick)
    base_keys = {k: v for k, v in sd.items() if ".mlp." not in k}
    for i in range(n_layer):
        p = f"layers.{i}."
        e0 = p + "mlp.experts.0."
        base_keys[p + "mlp.gate_proj.weight"] = sd[e0 + "gate_proj.weight"]
        base_keys[p + "mlp.up_proj.weight"] = sd[e0 + "up_proj.weight"]
        base_keys[p + "mlp.down_proj.weight"] = sd[e0 + "down_proj.weight"]
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(base_keys, n_layer=n_layer)

    def _t(w):  # torch Linear (out, in) -> (in, out)
        return np.ascontiguousarray(np.asarray(w).T)

    for i in range(n_layer):
        p = f"layers.{i}.mlp."
        n_expert = 1 + max(
            int(k[len(p + "experts."):].split(".")[0]) for k in sd
            if k.startswith(p + "experts."))
        blk = dict(params[f"h_{i}"])
        del blk["mlp"]
        blk["moe"] = {
            "router": {"kernel": _t(sd[p + "gate.weight"])},
            "wg": np.stack([_t(sd[f"{p}experts.{e}.gate_proj.weight"])
                            for e in range(n_expert)]),
            "wu": np.stack([_t(sd[f"{p}experts.{e}.up_proj.weight"])
                            for e in range(n_expert)]),
            "wd": np.stack([_t(sd[f"{p}experts.{e}.down_proj.weight"])
                            for e in range(n_expert)]),
            "shared": {
                "gate": {"kernel": _t(sd[p + "shared_expert.gate_proj"
                                         ".weight"])},
                "up": {"kernel": _t(sd[p + "shared_expert.up_proj"
                                       ".weight"])},
                "down": {"kernel": _t(sd[p + "shared_expert.down_proj"
                                         ".weight"])},
            },
            "shared_gate": {
                "kernel": _t(sd[p + "shared_expert_gate.weight"])},
        }
        params[f"h_{i}"] = blk
    return params


def to_hf_config(cfg: MixtralConfig, **overrides):
    """transformers.MixtralConfig (or Qwen2MoeConfig for shared-expert
    configs) for parity tests."""
    import transformers

    if cfg.d_shared:
        return transformers.Qwen2MoeConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
            intermediate_size=cfg.d_ff,
            moe_intermediate_size=cfg.d_ff,
            shared_expert_intermediate_size=cfg.d_shared,
            num_hidden_layers=cfg.n_layer,
            num_attention_heads=cfg.n_head,
            num_key_value_heads=cfg.n_kv_head,
            max_position_embeddings=cfg.block_size,
            rope_theta=cfg.rope_theta, rms_norm_eps=cfg.rms_eps,
            num_experts=cfg.n_expert,
            num_experts_per_tok=cfg.router_top_k,
            norm_topk_prob=cfg.router_norm_topk,
            decoder_sparse_step=1,  # every layer sparse (this pytree)
            tie_word_embeddings=cfg.tie_word_embeddings,
            **overrides)

    kw = dict(
        vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
        intermediate_size=cfg.d_ff, num_hidden_layers=cfg.n_layer,
        num_attention_heads=cfg.n_head, num_key_value_heads=cfg.n_kv_head,
        max_position_embeddings=cfg.block_size, rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps, num_local_experts=cfg.n_expert,
        num_experts_per_tok=cfg.router_top_k,
        # HF Mixtral defaults a 4096 sliding window; the released models
        # attend dense and so do we
        sliding_window=None,
    )
    kw.update(overrides)
    return transformers.MixtralConfig(**kw)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def _register(name: str, cfg: MixtralConfig):
    def convert(sd, _cfg=cfg):
        return params_from_state_dict(sd, n_layer=_cfg.n_layer)

    register_model(ModelSpec(
        name=name,
        init=lambda rng, dtype=jnp.float32, _cfg=cfg: init(rng, _cfg, dtype),
        apply=make_apply(cfg),
        # llama.make_partition resolves the expert hook per stage scan —
        # multi-stage relay partitioning works like any llama family
        partition=llama.make_partition(cfg),
        example_input=gpt.make_example_input(cfg),
        supported_parts=tuple(range(1, cfg.n_layer + 1)),
        convert_state_dict=convert,
        config=cfg,
        extras={
            "make_apply": lambda compute_dtype=None, **_kw: make_apply(
                cfg, compute_dtype=compute_dtype),
            "family_rows": lambda compute_dtype=None, **_kw: family_rows(
                cfg, compute_dtype=compute_dtype),
        },
    ))


for _name, _cfg in PRESETS.items():
    _register(_name, _cfg)
