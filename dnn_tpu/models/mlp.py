"""Tiny MLP model family — the worked "add your own family" example.

The reference tells users to adapt it to a new model by hand-editing the
node script: write ModelPart* classes, swap the import, and re-key the
`MODEL_PARTS_CLASSES` dict (/root/reference/readme.md:100-108,
node.py:29-32). Here the same job is one self-contained module that
registers a `ModelSpec`; README's "Adding a model family" section walks
through this file line by line. Keep it boring on purpose — it is
documentation that happens to run.

Architecture: fc stack over flattened inputs, relu between layers,
softmax head — an MNIST-shaped (784 -> 512 -> 256 -> 10) classifier by
default. Partitioning is at layer boundaries, like the reference's CIFAR
split (cifar_model_parts.py:29-58) but for any 1 <= num_parts <= depth.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.ops.nn import linear, relu, softmax
from dnn_tpu.registry import ModelSpec, StageSpec, register_model

# (in, hidden..., out). Chosen so the flagship config is MNIST-shaped; the
# family supports any widths via make_spec().
DEFAULT_WIDTHS = (784, 512, 256, 10)


def _torch_linear(key, cin, cout, dtype):
    # torch nn.Linear default init (kaiming_uniform a=sqrt(5) + bias bound),
    # same convention as the other families so converted checkpoints and
    # native inits share a scale.
    bound = 1.0 / math.sqrt(cin)
    kkey, bkey = jax.random.split(key)
    kernel = jax.random.uniform(
        kkey, (cin, cout), dtype, minval=-math.sqrt(3.0) * bound, maxval=math.sqrt(3.0) * bound
    )
    bias = jax.random.uniform(bkey, (cout,), dtype, minval=-bound, maxval=bound)
    return {"kernel": kernel, "bias": bias}


def make_spec(name="mlp", widths=DEFAULT_WIDTHS):
    """Build and register an MLP ModelSpec.

    The five ingredients every family provides (see README "Adding a model
    family"): init, apply, partition, example_input, convert_state_dict.
    """
    widths = tuple(int(w) for w in widths)
    if len(widths) < 2:
        raise ValueError("widths needs at least (in, out)")
    depth = len(widths) - 1
    layer_names = tuple(f"fc{i}" for i in range(depth))

    # 1. init: rng -> param pytree. Keys are the partitionable unit.
    def init(rng, dtype=jnp.float32):
        keys = jax.random.split(rng, depth)
        return {
            layer_names[i]: _torch_linear(keys[i], widths[i], widths[i + 1], dtype)
            for i in range(depth)
        }

    # Layer-granular segments: relu between layers, softmax after the last.
    def _seg(i):
        last = i == depth - 1

        def fn(params, x, _name=layer_names[i], _last=last):
            h = linear(params[_name], x)
            return softmax(h, axis=-1) if _last else relu(h)

        return fn

    _segments = tuple(_seg(i) for i in range(depth))

    # 2. apply: full-model forward, (B, widths[0]) -> (B, widths[-1]) probs.
    def apply(params, x):
        for fn in _segments:
            x = fn(params, x)
        return x

    # 3. partition: contiguous layer ranges via gpt.layer_ranges — reuse
    #    the framework's split rule instead of re-deriving one (earlier
    #    stages take the remainder).
    def partition(num_parts):
        from dnn_tpu.models.gpt import layer_ranges

        if not 1 <= num_parts <= depth:
            raise ValueError(
                f"{name} has {depth} layers; num_parts must be in [1, {depth}], got {num_parts}"
            )
        stages = []
        for lo, hi in layer_ranges(depth, num_parts):

            def stage_fn(params, x, _lo=lo, _hi=hi):
                for i in range(_lo, _hi):
                    x = _segments[i](params, x)
                return x

            stages.append(
                StageSpec(
                    name="+".join(layer_names[lo:hi]),
                    apply=stage_fn,
                    param_keys=layer_names[lo:hi],
                )
            )
        return stages

    # 4. example_input: dummy batch for dryruns and the CLI's no-image
    #    fallback (the reference's torch.randn analog, node.py:149-154).
    def example_input(batch_size=1, rng=None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        return jax.random.normal(rng, (batch_size, widths[0]), jnp.float32)

    # 5. convert_state_dict: torch nn.Linear stores weight as (out, in);
    #    ours is (in, out) so the matmul hits the MXU untransposed.
    def convert_state_dict(sd):
        params = {}
        for i, lname in enumerate(layer_names):
            w = np.asarray(sd[f"{lname}.weight"])
            b = np.asarray(sd[f"{lname}.bias"])
            if w.shape != (widths[i + 1], widths[i]):
                raise ValueError(
                    f"{lname}.weight shape {w.shape} != {(widths[i + 1], widths[i])}"
                )
            params[lname] = {"kernel": jnp.asarray(w.T), "bias": jnp.asarray(b)}
        return params

    return register_model(
        ModelSpec(
            name=name,
            init=init,
            apply=apply,
            partition=partition,
            example_input=example_input,
            supported_parts=tuple(range(1, depth + 1)),
            convert_state_dict=convert_state_dict,
        )
    )


# The registered flagship instance (config: {"model": "mlp"}).
make_spec()
