# Importing the model modules registers them with the model registry.
from dnn_tpu.models import cifar  # noqa: F401
from dnn_tpu.models import gpt  # noqa: F401
from dnn_tpu.models import mlp  # noqa: F401
from dnn_tpu.models import gpt_moe  # noqa: F401
from dnn_tpu.models import llama  # noqa: F401
from dnn_tpu.models import llama_moe  # noqa: F401
