"""GPT with Mixture-of-Experts FFNs — the expert-parallel model family.

No counterpart exists in the reference (SURVEY.md §2: "no MoE modules
exist"); this family extends the GPT-2 re-authoring (models/gpt.py, built
because the reference's `model.py` is absent — gpt_model_parts.py:4) with
sparse FFNs:

  * every block's dense MLP is replaced by a top-k routed MoE FFN
    (dnn_tpu/parallel/moe.py) — attention, embeddings, and the LM head are
    exactly GPT-2's;
  * dense path routes in `groups` so it equals the expert-parallel path
    bit-for-bit at groups == n_devices;
  * `make_apply_ep(cfg, mesh)` runs the whole forward under `shard_map`
    with the batch sharded over the "expert" mesh axis (dp and ep share
    the axis): attention/embed/head compute on local batches, expert
    weights live sharded P("expert"), and tokens reach their experts via
    `jax.lax.all_to_all` — the EP row of the parallelism table;
  * pipeline partitioning reuses gpt.layer_ranges, so the family also
    stages across the "stage" axis like its dense sibling.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dnn_tpu.models import gpt
from dnn_tpu.ops.attention import causal_self_attention
from dnn_tpu.ops.nn import layer_norm
from dnn_tpu.parallel.mesh import EXPERT_AXIS
from dnn_tpu.parallel.moe import (
    init_moe,
    moe_capacity,
    moe_ffn,
    moe_ffn_local,
)
from dnn_tpu.registry import ModelSpec, StageSpec, register_model


@dataclasses.dataclass(frozen=True)
class GPTMoEConfig(gpt.GPTConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_ff: int = 0  # 0 = 4 * n_embd (per expert)

    @property
    def ff_dim(self):
        return self.d_ff or 4 * self.n_embd


PRESETS = {
    # 8-expert small model: ~2x the active FLOPs of gpt2-small's MLP budget
    # spread over 8x the MLP params — the classic sparse-scaling shape
    "gpt2-moe": GPTMoEConfig(n_layer=12, n_head=12, n_embd=768, n_experts=8),
    # tiny config for tests / CPU-mesh CI (experts divisible by 2 and 4)
    "gpt2-moe-test": GPTMoEConfig(block_size=64, vocab_size=256, n_layer=2,
                                  n_head=4, n_embd=32, n_experts=4, d_ff=64),
}


def init_block(key, cfg: GPTMoEConfig, dtype=jnp.float32):
    c = cfg.n_embd
    ks = jax.random.split(key, 3)
    proj_std = 0.02 / (2 * cfg.n_layer) ** 0.5
    return {
        "ln_1": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
        "attn": {
            "qkv": {"kernel": (jax.random.normal(ks[0], (c, 3 * c)) * 0.02).astype(dtype),
                    "bias": jnp.zeros((3 * c,), dtype)},
            "proj": {"kernel": (jax.random.normal(ks[1], (c, c)) * proj_std).astype(dtype),
                     "bias": jnp.zeros((c,), dtype)},
        },
        "ln_2": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
        "moe": init_moe(ks[2], c, cfg.n_experts, cfg.ff_dim, dtype),
    }


def init(rng, cfg: GPTMoEConfig = PRESETS["gpt2-moe"], dtype=jnp.float32):
    keys = jax.random.split(rng, cfg.n_layer + 3)
    c = cfg.n_embd
    params = {
        "wte": {"embedding": (jax.random.normal(keys[0], (cfg.vocab_size, c)) * 0.02).astype(dtype)},
        "wpe": {"embedding": (jax.random.normal(keys[1], (cfg.block_size, c)) * 0.01).astype(dtype)},
        "ln_f": {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)},
    }
    for i in range(cfg.n_layer):
        params[f"h_{i}"] = init_block(keys[2 + i], cfg, dtype)
    params["lm_head"] = {"kernel": params["wte"]["embedding"].T}
    return params


def _block_core(block_params, x, ffn_fn, *, cfg: GPTMoEConfig, compute_dtype=None):
    """Pre-LN block: causal MHA + a pluggable FFN (dense-routed or
    expert-parallel), both residual. ONE definition for both execution
    paths — the dense==EP parity invariant depends on them never
    diverging."""
    h = layer_norm(block_params["ln_1"], x, eps=cfg.ln_eps)
    x = x + causal_self_attention(
        block_params["attn"], h, n_head=cfg.n_head, compute_dtype=compute_dtype
    )
    h = layer_norm(block_params["ln_2"], x, eps=cfg.ln_eps)
    m = ffn_fn(block_params["moe"], h)
    return x + m.astype(x.dtype)


def block_apply(block_params, x, *, cfg: GPTMoEConfig, groups: int = 1,
                compute_dtype=None):
    """Dense-path block: the FFN routes locally in `groups` groups."""
    return _block_core(
        block_params, x,
        lambda mp, h: moe_ffn(
            mp, h, top_k=cfg.top_k, capacity_factor=cfg.capacity_factor,
            groups=groups, compute_dtype=compute_dtype,
        ),
        cfg=cfg, compute_dtype=compute_dtype,
    )


def _blocks_scan(stacked, x, *, cfg, groups, compute_dtype):
    def body(carry, layer_params):
        return block_apply(layer_params, carry, cfg=cfg, groups=groups,
                           compute_dtype=compute_dtype), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


def make_apply(cfg: GPTMoEConfig, *, groups: int = 1, compute_dtype=None):
    """Dense (single-program) forward. `groups` sets the routing-group
    count; groups == n matches an n-device EP run exactly."""

    def apply(params, idx):
        x = gpt.embed(params, idx, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        stacked = gpt.stack_blocks(params, range(cfg.n_layer))
        x = _blocks_scan(stacked, x, cfg=cfg, groups=groups,
                         compute_dtype=compute_dtype)
        return gpt.head(params, x.astype(jnp.float32), cfg=cfg,
                        compute_dtype=compute_dtype)

    return apply


def make_apply_ep(cfg: GPTMoEConfig, mesh, *, axis_name: str = EXPERT_AXIS,
                  compute_dtype=None):
    """Expert-parallel forward over `mesh`'s expert axis.

    apply(params, ids): ids (B, T), B divisible by the axis size. The batch
    shards over the expert axis (each device's local batch = its routing
    group); per-block expert weights shard on their E axis; everything else
    replicates. Logits come back sharded over the batch.

    `params` may be the raw per-layer pytree ({"h_0"...}) or the stacked
    form from `gpt.prepare_stacked(params, cfg)` (a {"blocks": ...} key).
    Long-lived callers should prepare ONCE at load time — restacking
    inside a jitted step is an O(params) copy per call (the same contract
    as the dense family's prepare_stacked)."""
    n = mesh.shape[axis_name]
    if cfg.n_experts % n:
        raise ValueError(f"n_experts={cfg.n_experts} not divisible by axis size {n}")

    def _spec_for(path, leaf):
        # derived from the ACTUAL pytree (same approach as
        # llama_moe.make_apply_ep), so int8-quantized trees — expert
        # *_scale leaves, {q, scale} attention linears — shard correctly
        # instead of tripping a hardcoded-structure mismatch. Only the
        # expert stacks shard (stacked blocks carry a leading L, so E is
        # axis 1); the router and everything else replicate.
        keys = [p.key for p in path if hasattr(p, "key")]
        if "moe" in keys and keys and keys[-1] in (
                "wi", "wo", "bi", "bo", "wi_scale", "wo_scale"):
            return P(None, axis_name)
        return P()

    def local_fn(prep_local, ids_local):
        x = gpt.embed(prep_local, ids_local, cfg=cfg)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)

        b_local, t = ids_local.shape
        s = b_local * t  # this device's tokens = one routing group
        capacity = moe_capacity(s, cfg.n_experts, cfg.top_k, cfg.capacity_factor)

        def ep_ffn(mp, h):
            d = h.shape[-1]
            return moe_ffn_local(
                mp, h.reshape(-1, d), top_k=cfg.top_k, capacity=capacity,
                axis_name=axis_name, compute_dtype=compute_dtype,
            ).reshape(h.shape)

        def body(carry, layer_params):
            return _block_core(layer_params, carry, ep_ffn, cfg=cfg,
                               compute_dtype=compute_dtype), None

        x, _ = jax.lax.scan(body, x, prep_local["blocks"])
        return gpt.head(prep_local, x.astype(jnp.float32), cfg=cfg,
                        compute_dtype=compute_dtype)

    def apply(params, ids):
        b = ids.shape[0]
        if b % n:
            raise ValueError(f"batch {b} not divisible by expert-axis size {n}")
        if "blocks" in params:
            prepared = params
        else:
            prepared = {k: v for k, v in params.items() if not k.startswith("h_")}
            prepared["blocks"] = gpt.stack_blocks(params, range(cfg.n_layer))
        param_specs = jax.tree_util.tree_map_with_path(_spec_for, prepared)
        return jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(param_specs, P(axis_name)),
            out_specs=P(axis_name),
            check_vma=False,
        )(prepared, ids)

    return apply


# --------------------------------------------------------------------------
# registration (pipeline partitioning reuses gpt.layer_ranges)
# --------------------------------------------------------------------------

def make_partition(cfg: GPTMoEConfig, *, compute_dtype=None):
    """Pipeline stages over layer ranges (the dense family's layout).

    NOTE: under a MICROBATCHED pipeline each microbatch is its own routing
    group (the MoE FFN routes over whatever batch it sees), so outputs
    differ from the whole-batch forward — not an error, the standard
    batch-dependence of capacity-based MoE. Exact parity with the dense
    forward needs microbatches=1 (or dense groups == microbatches)."""
    def partition(num_parts):
        ranges = gpt.layer_ranges(cfg.n_layer, num_parts)
        stages = []
        for p, (lo, hi) in enumerate(ranges):
            is_first, is_last = p == 0, p == num_parts - 1
            param_keys = tuple(f"h_{i}" for i in range(lo, hi))
            if is_first:
                param_keys = ("wte", "wpe") + param_keys
            if is_last:
                param_keys = param_keys + ("ln_f", "lm_head")

            def stage_fn(params, x, _lo=lo, _hi=hi, _first=is_first, _last=is_last):
                if _first:
                    x = gpt.embed(params, x, cfg=cfg)
                if compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(compute_dtype)
                if _hi > _lo:
                    stacked = gpt.stack_blocks(params, range(_lo, _hi))
                    x = _blocks_scan(stacked, x, cfg=cfg, groups=1,
                                     compute_dtype=compute_dtype)
                if _last:
                    x = gpt.head(params, x.astype(jnp.float32), cfg=cfg,
                                 compute_dtype=compute_dtype)
                return x

            stages.append(StageSpec(
                name=f"moe_blocks[{lo}:{hi}]"
                + ("+embed" if is_first else "") + ("+head" if is_last else ""),
                apply=stage_fn,
                param_keys=param_keys,
            ))
        return stages

    return partition


def _register(name: str, cfg: GPTMoEConfig):
    register_model(ModelSpec(
        name=name,
        init=lambda rng, dtype=jnp.float32, _cfg=cfg: init(rng, _cfg, dtype),
        apply=make_apply(cfg),
        partition=make_partition(cfg),
        example_input=gpt.make_example_input(cfg),
        supported_parts=tuple(range(1, cfg.n_layer + 1)),
        config=cfg,
        extras={
            "make_apply": lambda compute_dtype=None, **_kw: make_apply(
                cfg, compute_dtype=compute_dtype
            ),
            "make_partition": lambda compute_dtype=None, **_kw: make_partition(
                cfg, compute_dtype=compute_dtype
            ),
            "make_apply_ep": lambda mesh, compute_dtype=None: make_apply_ep(
                cfg, mesh, compute_dtype=compute_dtype
            ),
        },
    ))


for _name, _cfg in PRESETS.items():
    _register(_name, _cfg)
