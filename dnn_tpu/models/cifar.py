"""CIFAR-10 CNN model family, TPU-native.

Re-authors the reference's `NeuralNetwork`
(/root/reference/cifar_model_parts.py:6-25):

    conv1 3->32 k3 s1 p1, relu, maxpool 2x2
    conv2 32->64 k3 s1 p1, relu, maxpool 2x2
    flatten -> fc1 4096->512, relu -> fc2 512->10 -> softmax(dim=1)

and its 2-way split (`ModelPart0_2Node` = convs + flatten,
`ModelPart1_2Node` = fcs + softmax — cifar_model_parts.py:29-58), but:

  * NHWC activations / HWIO kernels (TPU MXU layout) instead of NCHW;
  * pure functions over a param pytree instead of nn.Module aliasing;
  * partitioning generalized to any 1 <= num_parts <= 4 at layer
    boundaries (the reference hard-codes exactly 2 — node.py:246-248);
  * the flatten at the conv/fc boundary emits the reference's (C, H, W)
    order (see _seg_conv2), so the 2-way split's wire activation and the
    fc1 weight layout are interchangeable with a reference node's. NOTE:
    this fixes the native fc1 layout too — a native .npz saved by the
    earlier (H, W, C)-flatten revision would load without error but
    mispredict; no such artifact was ever shipped.

Param pytree layout (keys are the stage-sliceable unit, mirroring the
reference's per-layer state-dict keys conv1/conv2/fc1/fc2):

  {"conv1": {kernel, bias}, "conv2": {kernel, bias},
   "fc1": {kernel, bias}, "fc2": {kernel, bias}}
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from dnn_tpu.ops.nn import conv2d, linear, max_pool2d, relu, softmax
from dnn_tpu.registry import ModelSpec, StageSpec, register_model

NUM_CLASSES = 10
IMAGE_SHAPE = (32, 32, 3)  # HWC
FLAT_FEATURES = 8 * 8 * 64  # after two 2x2 pools: 32->16->8 spatial, 64 ch


def _kaiming_conv(key, kh, kw, cin, cout, dtype):
    # Matches torch's default Conv2d init scale (kaiming_uniform a=sqrt(5)).
    fan_in = kh * kw * cin
    bound = 1.0 / math.sqrt(fan_in)
    kkey, bkey = jax.random.split(key)
    kernel = jax.random.uniform(
        kkey, (kh, kw, cin, cout), dtype, minval=-math.sqrt(3.0) * bound, maxval=math.sqrt(3.0) * bound
    )
    bias = jax.random.uniform(bkey, (cout,), dtype, minval=-bound, maxval=bound)
    return {"kernel": kernel, "bias": bias}


def _torch_linear(key, cin, cout, dtype):
    bound = 1.0 / math.sqrt(cin)
    kkey, bkey = jax.random.split(key)
    kernel = jax.random.uniform(
        kkey, (cin, cout), dtype, minval=-math.sqrt(3.0) * bound, maxval=math.sqrt(3.0) * bound
    )
    bias = jax.random.uniform(bkey, (cout,), dtype, minval=-bound, maxval=bound)
    return {"kernel": kernel, "bias": bias}


def init(rng, dtype=jnp.float32):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "conv1": _kaiming_conv(k1, 3, 3, 3, 32, dtype),
        "conv2": _kaiming_conv(k2, 3, 3, 32, 64, dtype),
        "fc1": _torch_linear(k3, FLAT_FEATURES, 512, dtype),
        "fc2": _torch_linear(k4, 512, NUM_CLASSES, dtype),
    }


# --- layer-granular segments: the partitionable unit ----------------------
# Reference forward order: pool(relu(conv1)) -> pool(relu(conv2)) -> flatten
# -> relu(fc1) -> softmax(fc2)  (cifar_model_parts.py:18-25).


def _seg_conv1(params, x, compute_dtype=None):
    # Input channels padded 3 -> 8 before the conv: XLA's TPU conv emitter
    # handles the degenerate cin=3 contraction poorly — the zero-pad
    # measures ~2x forward throughput on a v5e (19.7% -> 39.1% MFU at
    # B=1024, benchmarks/cifar_mfu_probe.py). Zero kernel rows contribute
    # exact zeros to the accumulation, so outputs are bit-identical in
    # every dtype; params keep the reference's (3, 32) kernel shape
    # (cifar_model_parts.py:9) so checkpoints are unaffected.
    kernel = params["conv1"]["kernel"]
    # TPU-only: other backends' conv emitters don't share the degenerate-
    # cin penalty, so they'd pay the extra MACs for nothing. Resolved at
    # trace time (jit traces per backend), so each backend compiles its
    # own consistent branch.
    pad = max(0, 8 - kernel.shape[2]) if jax.default_backend() == "tpu" else 0
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, 0), (0, pad), (0, 0)))
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad)))
    padded = {"kernel": kernel, "bias": params["conv1"]["bias"]}
    return max_pool2d(relu(conv2d(padded, x, compute_dtype=compute_dtype)))


def _seg_conv2(params, x, compute_dtype=None):
    h = max_pool2d(relu(conv2d(params["conv2"], x, compute_dtype=compute_dtype)))
    # Flatten in the REFERENCE'S (C, H, W) order (`x.view(-1, 64*8*8)` on
    # NCHW, cifar_model_parts.py:41), not our activation-native (H, W, C):
    # this is the 2-way split's wire boundary, so matching the order makes
    # our stage-0 output byte-compatible with a reference part-1 node (and
    # vice versa) and lets fc1 weights carry over with no permutation. The
    # transpose is 4096 elements — noise next to the convs.
    return h.transpose(0, 3, 1, 2).reshape(h.shape[0], -1)


def _seg_fc1(params, x, compute_dtype=None):
    return relu(linear(params["fc1"], x, compute_dtype=compute_dtype))


def _seg_fc2(params, x, compute_dtype=None):
    # bf16 operands still accumulate + softmax in f32: probs stay f32 in
    # both modes (only matmul/conv operand traffic changes).
    h = linear(params["fc2"], x, compute_dtype=compute_dtype,
               accum_dtype=jnp.float32 if compute_dtype is not None else None)
    return softmax(h, axis=1)


_SEGMENTS = (
    ("conv1", _seg_conv1, ("conv1",)),
    ("conv2", _seg_conv2, ("conv2",)),
    ("fc1", _seg_fc1, ("fc1",)),
    ("fc2", _seg_fc2, ("fc2",)),
)

# Split points chosen so num_parts=2 reproduces the reference split exactly:
# part0 = convs + flatten, part1 = fcs + softmax (cifar_model_parts.py:29-58).
_PARTITIONS = {
    1: ((0, 1, 2, 3),),
    2: ((0, 1), (2, 3)),
    3: ((0,), (1,), (2, 3)),
    4: ((0,), (1,), (2,), (3,)),
}


def apply(params, x):
    """Full-model forward: (B, 32, 32, 3) NHWC -> (B, 10) class probs."""
    for _, fn, _ in _SEGMENTS:
        x = fn(params, x)
    return x


def make_apply(compute_dtype=None):
    """Forward with an explicit matmul/conv operand dtype (e.g. bf16 for
    the MXU); probs are always f32 (see _seg_fc2). `None` returns the
    default f32 `apply` used by the parity tests."""
    if compute_dtype is None:
        return apply

    def apply_cd(params, x):
        x = x.astype(compute_dtype)
        for _, fn, _ in _SEGMENTS:
            x = fn(params, x, compute_dtype=compute_dtype)
        return x

    return apply_cd


def partition(num_parts):
    if num_parts not in _PARTITIONS:
        raise ValueError(
            f"cifar_cnn supports num_parts in {sorted(_PARTITIONS)}, got {num_parts}"
        )
    stages = []
    for seg_ids in _PARTITIONS[num_parts]:
        segs = [_SEGMENTS[i] for i in seg_ids]
        param_keys = tuple(k for _, _, keys in segs for k in keys)

        def stage_fn(params, x, _segs=tuple(segs)):
            for _, fn, _ in _segs:
                x = fn(params, x)
            return x

        stages.append(
            StageSpec(
                name="+".join(s[0] for s in segs),
                apply=stage_fn,
                param_keys=param_keys,
            )
        )
    return stages


def example_input(batch_size=1, rng=None):
    """Dummy input mirroring the reference's torch.randn(1, 3, 32, 32)
    fallback (node.py:149-154), in NHWC."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return jax.random.normal(rng, (batch_size, *IMAGE_SHAPE), jnp.float32)


def _convert_state_dict(sd):
    from dnn_tpu.io.checkpoint import cifar_params_from_torch_state_dict

    return cifar_params_from_torch_state_dict(sd)


register_model(
    ModelSpec(
        name="cifar_cnn",
        init=init,
        apply=apply,
        partition=partition,
        example_input=example_input,
        supported_parts=tuple(sorted(_PARTITIONS)),
        convert_state_dict=_convert_state_dict,
    )
)
