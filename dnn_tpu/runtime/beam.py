"""Beam-search decoding over the KV cache.

The reference has no decoding at all (its GPT partitions emit one
stateless forward's logits, /root/reference/partitions/gpt_model_parts.py:36-50);
this framework's sampling surfaces (greedy/temperature/top-k/top-p,
runtime/generate.py) cover the stochastic side. Beam search is the
deterministic search-side complement — the standard method when the goal
is the highest-likelihood sequence rather than a sample.

TPU-first shape of the implementation:
  * beams are BATCH ROWS: the (B, K) beam grid runs as B*K cache rows
    through the same `forward_with_cache` program the samplers use — the
    MXU sees one (B*K, 1) decode matmul per step, not K small ones;
  * one `lax.scan` drives all steps; every shape is static (beam
    reordering is a gather on the batch axis, token history is a
    preallocated (B, K, T) buffer updated in place);
  * hypothesis scoring is f32 log-softmax; finished beams (optional
    `eos_id`) are frozen by masking their continuation row to
    "EOS carries 0 logprob, everything else -inf" — scores stay exact
    with no dynamic beam retirement;
  * final selection applies the GNMT length penalty
    ((5 + len) / 6) ** alpha (alpha = 0 disables it).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dnn_tpu.models.gpt import GPTConfig
from dnn_tpu.runtime.generate import forward_with_cache, init_cache


def _family_fns(cfg):
    """(forward_with_cache, init_cache) for the config's family — the
    beam loop itself is family-agnostic (cache leaves reorder by their
    shared (L, B, H, S[, D]) batch axis), so LLaMA-family configs
    (Gemma's per-layer windows included — handled inside
    llama.forward_with_cache) ride the same search."""
    from dnn_tpu.models import llama

    if isinstance(cfg, llama.LlamaConfig):
        return llama.forward_with_cache, llama.init_cache
    return forward_with_cache, init_cache

_NEG_BIG = -1e30


def _length_penalty(lengths, alpha: float):
    if alpha == 0.0:
        return jnp.ones_like(lengths, jnp.float32)
    return ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** alpha


def make_beam_generate(cfg: GPTConfig, *, max_new_tokens: int, beam_size: int,
                       eos_id: Optional[int] = None,
                       length_penalty: float = 0.0,
                       compute_dtype=None, kv_dtype=None,
                       return_all: bool = False):
    """Build a jitted beam_generate(prepared, ids) for the GPT family.

    Returns the best hypothesis per batch row, (B, max_new_tokens) int32
    (positions after an EOS are filled with `eos_id`), or with
    `return_all=True` the full grid ((B, K, max_new_tokens) tokens,
    (B, K) length-penalized scores) sorted best-first. Deterministic —
    no rng argument. `beam_size=1` reproduces greedy `make_generate`
    token-for-token (same argmax over the same logits)."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    k = beam_size
    fwd, mk_cache = _family_fns(cfg)

    @functools.partial(jax.jit, static_argnames=())
    def beam_generate(prepared, ids):
        b, t = ids.shape
        s_max = t + max_new_tokens
        if s_max > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        v = cfg.vocab_size
        cache_dtype = kv_dtype if kv_dtype is not None else (
            compute_dtype or jnp.float32)

        # prefill once per batch row, then tile the written cache K ways —
        # beams share the prompt's K/V, so prompt compute is paid once,
        # not beam_size times
        cache = mk_cache(cfg, b, s_max, cache_dtype)
        logits, cache = fwd(
            prepared, ids, cache, 0, cfg=cfg, compute_dtype=compute_dtype)
        cache = jax.tree.map(lambda c: jnp.repeat(c, k, axis=1), cache)
        logp0 = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1)  # (B, V)

        # first expansion: top-k over the vocab seeds the beams
        scores, tok = lax.top_k(logp0, k)  # (B, K), (B, K)
        tok = tok.astype(jnp.int32)
        if eos_id is not None:
            finished = tok == eos_id
        else:
            finished = jnp.zeros((b, k), bool)
        lengths = jnp.ones((b, k), jnp.int32)
        hist = jnp.zeros((b, k, max_new_tokens), jnp.int32)
        hist = hist.at[:, :, 0].set(tok)

        def step(carry, i):
            cache, scores, tok, hist, finished, lengths = carry
            logits, cache = fwd(
                prepared, tok.reshape(b * k, 1), cache, t + i, cfg=cfg,
                compute_dtype=compute_dtype)
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1).reshape(b, k, v)
            if eos_id is not None:
                # frozen beams: only the EOS continuation, at zero cost —
                # their total score is exact and never re-penalized
                frozen = jnp.full((v,), _NEG_BIG).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], frozen[None, None, :],
                                 logp)
            total = scores[:, :, None] + logp  # (B, K, V)
            scores, flat_idx = lax.top_k(total.reshape(b, k * v), k)
            parent = (flat_idx // v).astype(jnp.int32)   # (B, K)
            tok = (flat_idx % v).astype(jnp.int32)

            # reorder everything beam-indexed by its parent
            rows = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
            cache = jax.tree.map(lambda c: jnp.take(c, rows, axis=1), cache)
            gather = lambda x: jnp.take_along_axis(  # noqa: E731
                x, parent if x.ndim == 2 else parent[:, :, None], axis=1)
            hist = jnp.take_along_axis(
                hist, parent[:, :, None], axis=1)
            finished = gather(finished)
            lengths = gather(lengths)

            if eos_id is not None:
                lengths = jnp.where(finished, lengths, lengths + 1)
                finished = finished | (tok == eos_id)
            else:
                lengths = lengths + 1
            hist = hist.at[:, :, i + 1].set(tok)
            return (cache, scores, tok, hist, finished, lengths), None

        if max_new_tokens > 1:
            (cache, scores, tok, hist, finished, lengths), _ = lax.scan(
                step, (cache, scores, tok, hist, finished, lengths),
                jnp.arange(max_new_tokens - 1))

        # positions past a beam's EOS already hold eos_id (the frozen
        # expansion can only emit it), so no post-hoc padding is needed
        final = scores / _length_penalty(lengths, length_penalty)
        order = jnp.argsort(-final, axis=1)  # best-first
        hist = jnp.take_along_axis(hist, order[:, :, None], axis=1)
        final = jnp.take_along_axis(final, order, axis=1)
        if return_all:
            return hist, final
        return hist[:, 0]

    return beam_generate
