from dnn_tpu.runtime.engine import PipelineEngine

__all__ = ["PipelineEngine"]
