"""Autoregressive generation with a KV cache for the GPT family.

The reference's GPT path is a single stateless full-sequence forward per
request — no KV cache, no sampling, no incremental decode (SURVEY §5
'Long-context': "each forward is full-sequence, stateless",
/root/reference/partitions/gpt_model_parts.py:13-50). A GPT user needs
generation, so the rebuild supplies it TPU-first:

  * prefill is one full-sequence forward that also writes K/V into a
    preallocated static-shape cache (XLA-friendly: no growing arrays);
  * decode is a `lax.scan` over steps — one compiled step regardless of
    token count — each step a (B, 1) forward against the cache with
    position masking instead of dynamic shapes;
  * the cache is laid out (L, B, H, S, D) so layers scan over the leading
    axis with the same stacked block params the pipeline runtime shards.

Greedy (temperature=0), temperature/top-k, and nucleus (top-p) sampling
are supported, composably (top-k filter first, nucleus over the rest).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnn_tpu.models.gpt import GPTConfig, head
from dnn_tpu.ops.attention import merge_heads, split_heads
from dnn_tpu.ops.nn import gelu, layer_norm, linear
from dnn_tpu.runtime.kvcache import (
    FloatKV,
    Int4KV,
    Int8KV,
    codec_for_cache,
)

_NEG_BIG = -1e30

# nucleus sampling ranks only this many candidates per step (see _sample):
# top-256 probability mass on a trained LM exceeds 0.999, so any practical
# p's nucleus fits inside the prefilter and the result is bit-identical to
# ranking the full vocabulary.
TOP_P_PREFILTER_K = 256


def init_cache(cfg: GPTConfig, batch: int, max_len: int, dtype=jnp.float32):
    """Preallocated K/V cache, one leading layer axis: (L, B, H, S, D).
    dtype="int8" / "int4" build the quantized caches (per-row scales
    ride along — dnn_tpu/runtime/kvcache.Int8KV / Int4KV; int4 stores
    native jnp.int4, two values per byte)."""
    if dtype == "int8":
        return Int8KV().init(cfg, batch, max_len)
    if dtype == "int4":
        return Int4KV().init(cfg, batch, max_len)
    return FloatKV(dtype).init(cfg, batch, max_len)


def _qkv_heads(bp, h, *, cfg: GPTConfig, compute_dtype):
    qkv = linear(bp["attn"]["qkv"], h, compute_dtype=compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return tuple(split_heads(t, cfg.n_head) for t in (q, k, v))  # (B,H,T,D)


def _block_with_cache(bp, x, layer_cache, start_pos, *, cfg: GPTConfig,
                      compute_dtype, ffn=None, codec=None):
    """One transformer block over x (B, T, C) whose tokens sit at positions
    [start_pos, start_pos+T); writes this block's K/V into the per-layer
    cache (a codec pytree — float or int8+scales) and attends against
    everything cached so far. T=prompt_len for prefill, T=1 for decode —
    same code path. `ffn(bp, h)` overrides the dense MLP (the MoE family
    plugs its routed FFN in here, dnn_tpu/runtime/generate_moe.py)."""
    codec = codec or codec_for_cache(layer_cache)
    t = x.shape[1]
    h = layer_norm(bp["ln_1"], x, eps=cfg.ln_eps)
    q, k, v = _qkv_heads(bp, h, cfg=cfg, compute_dtype=compute_dtype)
    layer_cache = codec.write(layer_cache, k, v, start_pos)
    pos_limit = start_pos + jnp.arange(t)  # causal within the new tokens
    # base= asserts the contiguous-limit contract the Pallas kernel needs
    # (kvcache.FloatKV.attend) — einsum codecs ignore it
    y = codec.attend(q, layer_cache, pos_limit, base=start_pos)
    x = x + linear(bp["attn"]["proj"], merge_heads(y.astype(x.dtype)),
                   compute_dtype=compute_dtype)
    h = layer_norm(bp["ln_2"], x, eps=cfg.ln_eps)
    if ffn is None:
        m = linear(bp["mlp"]["proj"], gelu(linear(bp["mlp"]["fc"], h, compute_dtype=compute_dtype)),
                   compute_dtype=compute_dtype)
    else:
        m = ffn(bp, h).astype(x.dtype)
    return x + m, layer_cache


def forward_with_cache(prepared, ids, cache, start_pos, *, cfg: GPTConfig,
                       compute_dtype=None, ffn=None, attn_kernel="auto"):
    """Forward ids (B, T) at positions [start_pos, start_pos+T) through all
    layers (scan over the stacked blocks), updating the cache. Returns
    (logits (B, T, V), cache). The cache format picks the storage codec:
    {"k","v"} float (init_cache default) or the int8+scales form
    (init_cache(..., dtype="int8")). `attn_kernel=True` runs cache
    attention through the Pallas streaming kernel
    (dnn_tpu/ops/pallas/cached_attention.py) — decode steps AND prefill
    chunks alike, one compiled program regardless of position; the
    default "auto" engages that kernel only on TPU against caches of
    >= kvcache.AUTO_KERNEL_MIN_S positions (length-aware dispatch: the
    long-context regime where clamped streaming beats reading the full
    allocation) and is the plain einsum everywhere else."""
    codec = codec_for_cache(cache, use_kernel=attn_kernel)
    x = _embed_at(prepared, ids, start_pos, compute_dtype=compute_dtype)

    def layer(carry, layer_in):
        bp, layer_cache = layer_in
        x, layer_cache = _block_with_cache(
            bp, carry, layer_cache, start_pos, cfg=cfg,
            compute_dtype=compute_dtype, ffn=ffn, codec=codec,
        )
        return x, layer_cache

    x, new_cache = lax.scan(layer, x, (prepared["blocks"], cache))
    logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                  compute_dtype=compute_dtype)
    return logits, new_cache


def logit_bias_row(logit_bias, vocab_size: int):
    """{token_id: additive bias} -> a dense (V,) f32 row (None -> None).
    The OpenAI-style knob: +big forces a token, -big (e.g. -100) bans it
    — applied to logits AFTER the repetition penalty, BEFORE
    temperature/filters, so bans bind for greedy rows too. Validates ids
    against the vocab (a silently-clipped id would bias the wrong
    token)."""
    if not logit_bias:
        return None
    row = np.zeros((vocab_size,), np.float32)
    for tok, val in logit_bias.items():
        t = int(tok)
        if not 0 <= t < vocab_size:
            raise ValueError(
                f"logit_bias token id {t} outside [0, {vocab_size})")
        v = float(val)
        if not np.isfinite(v):
            raise ValueError(f"logit_bias value for {t} not finite: {v}")
        row[t] = v
    return jnp.asarray(row)


def apply_repetition_penalty(logits, seen, penalty):
    """CTRL-style repetition penalty on RAW logits (HF semantics, applied
    before temperature): for tokens already in the sequence (`seen`,
    (..., V) bool), a positive logit is divided by `penalty` and a
    negative one multiplied — both push repeated tokens down when
    penalty > 1. Pure elementwise select: O(V), static shapes."""
    pen = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, pen, logits)


def _sample(logits, rng, *, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None, min_p: Optional[float] = None):
    """logits (B, V) -> token ids (B,). temperature=0 is greedy; top_k
    truncates to the k highest logits; min_p drops tokens whose
    probability is below min_p x the top token's (a sort-free relative
    cutoff — one max + one compare); top_p (nucleus) keeps the smallest
    set of tokens whose probability mass reaches p. All static-shape
    (threshold masks, no dynamic vocab slicing) and composable, applied
    in that order."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_BIG, logits)
    if min_p is not None:
        # prob_i >= min_p * prob_max  <=>  logit_i >= logit_max + log(min_p)
        mx = jnp.max(logits, axis=-1, keepdims=True)
        logits = jnp.where(logits < mx + jnp.log(min_p), _NEG_BIG, logits)
    if top_p is not None:
        # The nucleus threshold can only fall inside the highest-probability
        # tokens, so rank just TOP_P_PREFILTER_K candidates (lax.top_k,
        # O(V log k)) instead of sorting the full vocab (O(V log V)) inside
        # every decode step. Probabilities use the FULL softmax denominator
        # (logsumexp — O(V), sort-free), so the kept set and the sampled
        # token are bit-identical to the full-vocab filter whenever the
        # nucleus fits inside k; if it ever overflows (p greater than the
        # top-k's total mass), the cut truncates to the k best — strictly
        # tighter, never looser.
        k = min(TOP_P_PREFILTER_K, logits.shape[-1])
        vals = lax.top_k(logits, k)[0]  # (..., k) descending
        lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        probs = jnp.exp(vals - lse)
        cum = jnp.cumsum(probs, axis=-1)
        # keep a token while the mass BEFORE it is < p (top-1 always kept);
        # the cutoff logit is the smallest kept one
        keep = (cum - probs) < top_p
        n_keep = jnp.maximum(keep.sum(axis=-1), 1)
        thresh = jnp.take_along_axis(vals, (n_keep - 1)[..., None], axis=-1)
        logits = jnp.where(logits < thresh, _NEG_BIG, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _sample_rows(logits, keys, *, temperature, top_k, top_p, min_p=None):
    """Per-ROW sampling for the slot pool: every row carries its own
    request's parameters. logits (B, V); keys (B, 2) uint32; temperature
    (B,) f32 (0 = greedy); top_k (B,) int32 (0 = off, clamped to
    TOP_P_PREFILTER_K); top_p (B,) f32 (outside (0, 1) = off); min_p
    (B,) f32 (outside (0, 1] = off; None skips the filter entirely).

    Row i with uniform parameters reproduces `_sample`'s draw for the same
    key bit-for-bit — same thresholds (the k-th-largest value and the
    nucleus cutoff are computed by the same ops) and the same categorical
    call shape — so a request in a mixed pool samples exactly what it
    would in a single-request server (tests/test_serving_options.py).
    An all-greedy pool skips the filter math at runtime (real lax.cond at
    the top level of the step program, not a select)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def do_sample(_):
        k_cap = min(TOP_P_PREFILTER_K, logits.shape[-1])
        safe_t = jnp.where(temperature > 0, temperature, 1.0)
        lg = logits / safe_t[:, None]
        # per-row top-k: threshold at the row's k-th largest value
        vals = lax.top_k(lg, k_cap)[0]  # (B, k_cap) descending
        k_idx = jnp.clip(top_k, 1, k_cap) - 1
        kth = jnp.take_along_axis(vals, k_idx[:, None], axis=-1)
        lg = jnp.where((top_k[:, None] > 0) & (lg < kth), _NEG_BIG, lg)
        if min_p is not None:
            # per-row relative cutoff (see _sample): rows with min_p
            # outside (0, 1] pass through untouched (1.0 = keep only
            # tokens tied with the max, matching _sample's threshold)
            m_on = (min_p > 0) & (min_p <= 1.0)
            safe_mp = jnp.where(m_on, min_p, 0.5)
            mx = jnp.max(lg, axis=-1, keepdims=True)
            lg = jnp.where(
                m_on[:, None] & (lg < mx + jnp.log(safe_mp)[:, None]),
                _NEG_BIG, lg)
        # per-row nucleus: the _sample prefilter with a row-wise p
        pvals = lax.top_k(lg, k_cap)[0]
        lse = jax.scipy.special.logsumexp(lg, axis=-1, keepdims=True)
        probs = jnp.exp(pvals - lse)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p[:, None]
        n_keep = jnp.maximum(keep.sum(axis=-1), 1)
        thresh = jnp.take_along_axis(pvals, (n_keep - 1)[:, None], axis=-1)
        p_on = (top_p > 0) & (top_p < 1.0)
        lg = jnp.where(p_on[:, None] & (lg < thresh), _NEG_BIG, lg)
        # mirror the pool's per-row call shape (categorical over (1, V))
        # so draws match the uniform-parameter _sample vmap exactly
        return jax.vmap(
            lambda l, k: jax.random.categorical(k, l[None, :], axis=-1)[0]
        )(lg, keys).astype(jnp.int32)

    sampled = lax.cond(jnp.any(temperature > 0.0), do_sample,
                       lambda _: greedy, operand=None)
    return jnp.where(temperature > 0.0, sampled, greedy)


def _embed_at(aux, ids, start_pos, *, compute_dtype):
    """Token+position embedding for ids (B, T) at absolute positions
    [start_pos, start_pos+T) — the incremental-decode counterpart of
    gpt.embed (same gathers as forward_with_cache, so pipeline and
    single-device generation match bit for bit)."""
    pos = start_pos + jnp.arange(ids.shape[1])
    x = jnp.take(aux["wte"]["embedding"], ids, axis=0) + \
        jnp.take(aux["wpe"]["embedding"], pos, axis=0)
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
    return x


def prepare_pipeline_stacked(prepared, cfg: GPTConfig, mesh, *, axis_name=None):
    """One-time load-side transform for pipeline-parallel generation:
    reshape the (L, ...) block stack stage-major to (S, L/S, ...) and place
    it sharded over the stage axis (each device holds only its own stage's
    blocks — HBM-resident per-stage weights, same layout the inference
    engine's stacked pipeline uses). Returns (stage_blocks, aux)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dnn_tpu.parallel.mesh import STAGE_AXIS

    axis = axis_name or STAGE_AXIS
    num_stages = mesh.shape[axis]
    if cfg.n_layer % num_stages != 0:
        raise ValueError(
            f"n_layer {cfg.n_layer} not divisible by {num_stages} stages"
        )
    per_stage = cfg.n_layer // num_stages
    stage_blocks = jax.tree.map(
        lambda p: p.reshape(num_stages, per_stage, *p.shape[1:]),
        prepared["blocks"],
    )
    stage_blocks = jax.device_put(
        stage_blocks, NamedSharding(mesh, P(axis))
    )
    aux = {k: v for k, v in prepared.items() if k != "blocks"}
    return stage_blocks, aux


class GPTPipelineFamily:
    """Per-stage decode hooks for the pipeline-parallel generator — the
    family-adapter pattern the batcher uses (serving.GPTFamilyRows),
    applied to the ppermute ring: a family supplies its stage-local cache
    layout, cached block, embed, and head; the ring schedule, cache-shard
    bookkeeping, and sampling broadcast stay family-agnostic. LLaMA's
    adapter is models/llama.LlamaPipelineFamily (RoPE positions,
    KV-head-width cache shards)."""

    def __init__(self, cfg, *, compute_dtype=None, ffn=None, kv_dtype=None):
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        self.ffn = ffn  # block-MLP override (MoE: generate_moe.moe_cache_ffn)
        self.kv_dtype = kv_dtype  # None follows compute_dtype; "int8" quantizes

    def stage_cache(self, per_stage: int, batch: int, s_max: int):
        import dataclasses

        cfg = self.cfg
        dt = self.kv_dtype if self.kv_dtype is not None else (
            self.compute_dtype or jnp.float32)
        # a per-stage cache is just a cache whose "layer count" is the
        # stage's slice — reuse init_cache (and its codec dispatch)
        stage_cfg = dataclasses.replace(cfg, n_layer=per_stage)
        return init_cache(stage_cfg, batch, s_max, dt)

    def block_with_cache(self, bp, x, layer_cache, start_pos):
        return _block_with_cache(
            bp, x, layer_cache, start_pos, cfg=self.cfg,
            compute_dtype=self.compute_dtype, ffn=self.ffn)

    def embed(self, aux, ids, start_pos):
        return _embed_at(aux, ids, start_pos, compute_dtype=self.compute_dtype)

    def head(self, aux, h):
        return head(aux, h.astype(jnp.float32), cfg=self.cfg,
                    compute_dtype=self.compute_dtype)


def make_pipeline_generate(cfg: GPTConfig, mesh, *, max_new_tokens: int,
                           temperature: float = 0.0, top_k: Optional[int] = None,
                           top_p: Optional[float] = None,
                           compute_dtype=None, axis_name=None, family=None,
                           kv_dtype=None):
    """Pipeline-parallel KV-cache generation across a stage-sharded mesh.

    The serving capability the reference's 8-stage GPT pipeline stops short
    of: its partitions can emit one stateless forward's logits
    (/root/reference/partitions/gpt_model_parts.py:36-50) but cannot
    decode. Here the whole decode loop runs as ONE SPMD program:

      * each device holds its stage's blocks AND that stage's slice of the
        KV cache — cache shards live with the weights they serve, nothing
        cache-shaped ever crosses a device boundary;
      * per token, the (B, 1, C) hidden state makes one full circuit of the
        `ppermute` ring: at sub-step s the real value sits on stage s, which
        applies its blocks against its local cache; every device computes
        each sub-step (SPMD — one program), but only the active stage's
        cache update is kept (`where` on the stage coordinate). Since
        single-stream decode is inherently sequential through the stages,
        wall-clock equals the sequential stage latency — the idle devices'
        discarded compute costs energy, not time;
      * embed runs where the ring starts and head/sampling where it ends
        (stage 0 after the wraparound hop), and the sampled token is
        psum-broadcast so every stage enters the next step agreed.

    Token-for-token identical to single-device `make_generate` (same gather,
    block, head, and rng-split sequence). Returns
    generate(stage_blocks, aux, ids, rng) over `prepare_pipeline_stacked`
    outputs.
    """
    from jax.sharding import PartitionSpec as P

    from dnn_tpu.parallel.mesh import STAGE_AXIS

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    axis = axis_name or STAGE_AXIS
    num_stages = mesh.shape[axis]
    if cfg.n_layer % num_stages != 0:
        raise ValueError(
            f"n_layer {cfg.n_layer} not divisible by {num_stages} stages"
        )
    per_stage = cfg.n_layer // num_stages
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    if family is not None:
        # same contract as ContinuousBatcher: with an explicit family the
        # model math runs at the FAMILY's compute_dtype; a diverging
        # top-level knob would silently lose
        fam_dtype = getattr(family, "compute_dtype", None)
        if compute_dtype is not None and fam_dtype != compute_dtype:
            raise ValueError(
                f"compute_dtype mismatch: make_pipeline_generate="
                f"{compute_dtype} vs family adapter={fam_dtype} — set it "
                f"on the adapter")
        if kv_dtype is not None:
            raise ValueError("pass kv_dtype on the family adapter, not "
                             "alongside family=")
    fam = family or GPTPipelineFamily(cfg, compute_dtype=compute_dtype,
                                      kv_dtype=kv_dtype)

    def per_device(stage_blocks, aux, ids, rng):
        local = jax.tree.map(lambda p: p[0], stage_blocks)  # (per_stage, ...)
        d = lax.axis_index(axis)
        b, t = ids.shape
        s_max = t + max_new_tokens
        cache = fam.stage_cache(per_stage, b, s_max)

        def my_blocks(x, cache, start_pos):
            def layer(carry, layer_in):
                bp, layer_cache = layer_in
                return fam.block_with_cache(bp, carry, layer_cache, start_pos)

            return lax.scan(layer, x, (local, cache))

        def ring_pass(x, cache, start_pos):
            """x real on stage 0 -> through all stages in order -> real
            result back on stage 0 (wraparound hop)."""
            def sub(carry, s):
                h, cache = carry
                h2, cache2 = my_blocks(h, cache, start_pos)
                active = d == s
                cache = jax.tree.map(
                    lambda new, old: jnp.where(active, new, old), cache2, cache)
                h = lax.ppermute(h2, axis, perm)
                return (h, cache), None

            (h, cache), _ = lax.scan(sub, (x, cache), jnp.arange(num_stages))
            return h, cache

        def sample_last(h, sub_rng):
            logits = fam.head(aux, h[:, -1:])
            tok = _sample(logits[:, -1], sub_rng,
                          temperature=temperature, top_k=top_k, top_p=top_p)
            # only stage 0 holds the real hidden state; broadcast its token
            return lax.psum(jnp.where(d == 0, tok, jnp.zeros_like(tok)), axis)

        # prefill: full prompt, one ring circuit
        x = fam.embed(aux, ids, 0)
        h, cache = ring_pass(x, cache, 0)
        rng, sub = jax.random.split(rng)
        tok = sample_last(h, sub)

        def step(carry, i):
            cache, tok, rng = carry
            x = fam.embed(aux, tok[:, None], t + i)
            h, cache = ring_pass(x, cache, t + i)
            rng, sub = jax.random.split(rng)
            nxt = sample_last(h, sub)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1)
        )
        toks = jnp.moveaxis(toks, 0, 1)  # (B, max_new_tokens-1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    @jax.jit
    def generate(stage_blocks, aux, ids, rng):
        b, t = ids.shape
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}"
            )
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )(stage_blocks, aux, ids, rng)

    return generate


def make_generate(cfg: GPTConfig, *, max_new_tokens: int, temperature: float = 0.0,
                  top_k: Optional[int] = None, top_p: Optional[float] = None,
                  min_p: Optional[float] = None,
                  repetition_penalty: Optional[float] = None,
                  logit_bias=None,
                  compute_dtype=None, ffn=None, kv_dtype=None,
                  attn_kernel="auto"):
    """Build a jitted generate(prepared, ids, rng) -> (B, max_new_tokens).

    `prepared` is the stacked layout from `gpt.prepare_stacked`. The prompt
    length is static per compilation (usual JAX contract); decode runs as a
    single lax.scan. `ffn(bp, h)` overrides the dense block MLP (the MoE
    family's entry point, dnn_tpu/runtime/generate_moe.py). `kv_dtype`
    picks the cache storage: None follows compute_dtype (f32 default),
    jnp.bfloat16 halves cache bandwidth, "int8" quarters it
    (dnn_tpu/runtime/kvcache.py). `attn_kernel=True` streams the cache
    through the Pallas attention kernel on TPU (fused int8 dequant; einsum
    fallback elsewhere); the default "auto" engages it only for
    long-context caches on TPU (kvcache.AUTO_KERNEL_MIN_S), False forces
    the einsum. `min_p` drops tokens below min_p x the top
    probability; `repetition_penalty` (HF/CTRL semantics) penalizes every
    token already in the sequence — when set, a (B, V) seen-mask rides
    the decode carry (scatter per step; only materialized when the
    penalty is on, so the default program is unchanged). `logit_bias`
    ({token_id: additive bias}) forces or bans specific tokens — applied
    after the penalty, before the filters, binding for greedy too.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if repetition_penalty is not None and repetition_penalty <= 0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}")
    if min_p is not None and not 0.0 <= min_p <= 1.0:
        # min_p > 1 would mask EVERY token (threshold above the max
        # logit) and categorical would then draw uniformly — reject loud
        raise ValueError(f"min_p must be in [0, 1], got {min_p}")
    bias_row = logit_bias_row(logit_bias, cfg.vocab_size)
    pen_on = repetition_penalty is not None and repetition_penalty != 1.0

    @functools.partial(jax.jit, static_argnames=())
    def generate(prepared, ids, rng):
        b, t = ids.shape
        s_max = t + max_new_tokens
        if s_max > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}"
            )
        cache_dtype = kv_dtype if kv_dtype is not None else (compute_dtype or jnp.float32)
        cache = init_cache(cfg, b, s_max, cache_dtype)

        # prefill: full prompt in one forward
        logits, cache = forward_with_cache(
            prepared, ids, cache, 0, cfg=cfg, compute_dtype=compute_dtype,
            ffn=ffn, attn_kernel=attn_kernel,
        )
        rng, sub = jax.random.split(rng)

        seen = None
        if pen_on:
            seen = jnp.zeros((b, cfg.vocab_size), bool)
            seen = seen.at[jnp.arange(b)[:, None], ids].set(True)

        def pick(lg, seen, sub):
            if pen_on:
                lg = apply_repetition_penalty(lg, seen, repetition_penalty)
            if bias_row is not None:
                lg = lg + bias_row
            tok = _sample(lg, sub, temperature=temperature, top_k=top_k,
                          top_p=top_p, min_p=min_p)
            if pen_on:
                seen = seen.at[jnp.arange(b), tok].set(True)
            return tok, seen

        tok, seen = pick(logits[:, -1], seen, sub)

        def step(carry, i):
            # carry token tok_i sits at sequence position t + i
            cache, tok, rng, seen = carry
            logits, cache = forward_with_cache(
                prepared, tok[:, None], cache, t + i, cfg=cfg,
                compute_dtype=compute_dtype, ffn=ffn,
                attn_kernel=attn_kernel,
            )
            rng, sub = jax.random.split(rng)
            nxt, seen = pick(logits[:, -1], seen, sub)
            return (cache, nxt, rng, seen), tok

        (_, last, _, _), toks = lax.scan(
            step, (cache, tok, rng, seen), jnp.arange(max_new_tokens - 1)
        )
        toks = jnp.moveaxis(toks, 0, 1)  # (B, max_new_tokens-1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    return generate
