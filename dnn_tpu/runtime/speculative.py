"""Speculative decoding: a small draft model proposes, the target verifies.

Autoregressive decode runs one serial target forward per token — latency is
L_target x n_tokens regardless of FLOPs. Speculative decoding breaks the
serial chain: a cheap draft model proposes `k` tokens autoregressively,
then the target scores all k (+1 bonus) positions in ONE parallel forward
(the MXU-friendly shape), accepting the longest prefix the target agrees
with. Greedy output is token-for-token IDENTICAL to target-only greedy
decode — acceptance only changes speed, never content; sampled output
follows the standard rejection-sampling construction (Leviathan et al.,
2023; Chen et al., 2023 — see PAPERS.md), which preserves the target
distribution exactly.

The reference framework has no decode loop at all (one stateless forward
per request, /root/reference/node.py:137-200); this module is part of the
serving stack the rebuild adds on top of KV-cache decode
(dnn_tpu/runtime/generate.py).

TPU-shaped mechanics — the whole loop is ONE jitted program:

  * Static shapes everywhere: proposals are always (k,), the target always
    scores (k+1,) positions, token output rides a fixed-size buffer with a
    dynamic write offset. The variable-length "accepted prefix" exists
    only as an integer `m`, never as a shape.
  * `lax.while_loop` over verify iterations (each commits >= 1 token, so
    it terminates); KV caches are preallocated (dnn_tpu/runtime/generate.py
    `init_cache`) and written at dynamic offsets — a rejected proposal is
    "rolled back" by simply not advancing the position pointer; its stale
    cache entries sit beyond the attention position limit and are
    overwritten when the sequence grows through them.
  * Draft-cache sync by idempotent re-feed: after a verify step the draft
    cache can lag the committed context (when every proposal was
    accepted, the draft never saw its own last proposal). Each iteration
    therefore starts by re-feeding the PREVIOUS (k+1)-token verify chunk
    to the draft at its old positions — recomputing identical K/V for
    already-correct entries (harmless) and filling exactly the entries
    that could be missing. This keeps every shape static instead of
    feeding a variable-length "tokens the draft hasn't seen" slice.

Batch is 1 by design: speculative decoding is a latency optimization for
a single stream (each row would accept a different prefix length; batched
throughput is the continuous batcher's job, dnn_tpu/runtime/serving.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dnn_tpu.models.gpt import GPTConfig
from dnn_tpu.runtime.generate import _NEG_BIG, forward_with_cache, init_cache

__all__ = ["make_speculative_generate"]


def _cached_lm(cfg, compute_dtype):
    """(init_cache_fn(batch, max_len), forward_fn(prepared, ids, cache,
    pos)) for whichever family `cfg` belongs to. Target and draft dispatch
    independently, so a LLaMA target can verify a GPT draft (and vice
    versa) — the construction only needs matching vocabularies."""
    from dnn_tpu.models.gpt_moe import GPTMoEConfig
    from dnn_tpu.models.llama import LlamaConfig

    if isinstance(cfg, LlamaConfig):
        from dnn_tpu.models import llama

        # attn_kernel pinned off: the speculative rewind/verify loop
        # has always run (and is only tested) on the einsum path —
        # mirrors SpeculativeBatcher's explicit pin (serving_spec.py)
        return (lambda b, n: llama.init_cache(cfg, b, n),
                lambda prepared, ids, cache, pos: llama.forward_with_cache(
                    prepared, ids, cache, pos, cfg=cfg,
                    compute_dtype=compute_dtype, attn_kernel=False))
    ffn = None
    if isinstance(cfg, GPTMoEConfig):
        # MoE subclasses GPTConfig, so it MUST be caught before the dense
        # fallback (whose blocks index 'mlp', not 'moe'); its cached
        # forward is the dense block with the routed FFN plugged in
        from dnn_tpu.runtime.generate_moe import moe_cache_ffn

        ffn = moe_cache_ffn(cfg, compute_dtype=compute_dtype)
    return (lambda b, n: init_cache(cfg, b, n),
            lambda prepared, ids, cache, pos, _ffn=ffn: forward_with_cache(
                prepared, ids, cache, pos, cfg=cfg,
                compute_dtype=compute_dtype, ffn=_ffn,
                attn_kernel=False))


def _probs(logits, *, temperature: float, top_k: Optional[int]):
    """Rows of logits (..., V) -> the ACTUAL sampling distribution
    (temperature + top-k filtered), f32. Both draft proposal probs and
    target accept probs must use this same transform — rejection sampling
    is only exact against the distributions really sampled from."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_BIG, logits)
    return jax.nn.softmax(logits, axis=-1)


def make_speculative_generate(
    target_cfg: GPTConfig,
    draft_cfg: GPTConfig,
    *,
    max_new_tokens: int,
    k: int = 4,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    compute_dtype=None,
    return_stats: bool = False,
):
    """Build `generate(target_prepared, draft_prepared, ids, rng)`.

    ids is (1, P) with P >= k+2 (the draft-sync chunk must fit inside the
    prompt on the first iteration). Returns (1, max_new_tokens) tokens;
    with `return_stats`, also {"iterations", "proposed", "accepted"} —
    accepted/proposed is the draft's acceptance rate, the number that
    decides whether the draft pays for itself."""
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}"
        )
    greedy = temperature == 0.0

    def generate(target_prepared, draft_prepared, ids, rng):
        b, p = ids.shape
        if b != 1:
            raise ValueError("speculative decode is single-stream (batch 1); "
                             "use ContinuousBatcher for batched throughput")
        if p < k + 2:
            raise ValueError(f"prompt length {p} < k+2 ({k + 2})")
        need = p + max_new_tokens + k
        for name, cfg in (("target", target_cfg), ("draft", draft_cfg)):
            if need > cfg.block_size:
                raise ValueError(
                    f"prompt+max_new+k = {need} exceeds {name} block_size "
                    f"{cfg.block_size}"
                )

        t_init, t_fwd = _cached_lm(target_cfg, compute_dtype)
        d_init, d_fwd = _cached_lm(draft_cfg, compute_dtype)
        t_cache = t_init(1, need)
        d_cache = d_init(1, need)
        # prefill both caches on everything but the last prompt token (it
        # is the first decode input, same as make_generate)
        _, t_cache = t_fwd(target_prepared, ids[:, :-1], t_cache, 0)
        _, d_cache = d_fwd(draft_prepared, ids[:, :-1], d_cache, 0)

        buf = jnp.zeros((1, max_new_tokens + k + 1), jnp.int32)
        state = {
            "t_cache": t_cache, "d_cache": d_cache, "buf": buf,
            "n": jnp.int32(0), "last": ids[:, -1].astype(jnp.int32),
            "pos": jnp.int32(p - 1),
            # first sync chunk: the prompt's own tail, at its own
            # positions — an exact no-op recompute (see module docstring)
            "prev_chunk": ids[0, p - 2 - k:p - 1].astype(jnp.int32),
            "prev_pos": jnp.int32(p - 2 - k),
            "rng": rng, "iters": jnp.int32(0), "accepted": jnp.int32(0),
        }

        def propose(d_cache, last, rng, pos):
            """k draft steps from `last` (which sits at position `pos`);
            returns proposals (k,), the draft's full sampling distribution
            per step (k, V) (needed for the residual resample at a
            rejection), and the updated cache. Greedy carries a scalar 1.0
            placeholder instead of the (k, V) rows — it never resamples."""

            def step(carry, i):
                cache, tok, r = carry
                logits, cache = d_fwd(draft_prepared, tok[:, None], cache,
                                      pos + i)
                row = logits[0, -1]
                if greedy:
                    nxt = jnp.argmax(row).astype(jnp.int32)[None]
                    out = jnp.float32(1.0)
                else:
                    r, sub = jax.random.split(r)
                    dist = _probs(row, temperature=temperature, top_k=top_k)
                    nxt = jax.random.categorical(sub, jnp.log(dist))[None].astype(jnp.int32)
                    out = dist
                return (cache, nxt, r), (nxt[0], out)

            (d_cache, _, rng), (props, d_rows) = lax.scan(
                step, (d_cache, last, rng), jnp.arange(k))
            return d_cache, props, d_rows, rng

        def body(s):
            pos = s["pos"]
            # 1. draft sync: idempotent re-feed of last verify chunk
            _, d_cache = d_fwd(draft_prepared, s["prev_chunk"][None, :],
                               s["d_cache"], s["prev_pos"])
            # 2. draft proposes k tokens
            d_cache, props, d_rows, rng = propose(
                d_cache, s["last"], s["rng"], pos)
            # 3. target scores [last, p1..pk] in one forward
            chunk = jnp.concatenate([s["last"], props])[None, :]  # (1, k+1)
            t_logits, t_cache = t_fwd(target_prepared, chunk, s["t_cache"],
                                      pos)
            rows = t_logits[0]  # (k+1, V); row i predicts position pos+i+1

            if greedy:
                t_toks = jnp.argmax(rows, axis=-1).astype(jnp.int32)  # (k+1,)
                match = props == t_toks[:k]
                m = jnp.where(match.all(), k, jnp.argmax(~match)).astype(jnp.int32)
                w = t_toks  # committed tokens ARE the target's greedy picks
            else:
                rng, r_acc, r_rep = jax.random.split(rng, 3)
                t_dist = _probs(rows, temperature=temperature, top_k=top_k)
                t_probs = t_dist[jnp.arange(k), props]  # target prob of each proposal
                d_probs = d_rows[jnp.arange(k), props]  # draft prob of each proposal
                ratio = t_probs / jnp.maximum(d_probs, 1e-30)
                accept = jax.random.uniform(r_acc, (k,)) < jnp.minimum(ratio, 1.0)
                m = jnp.where(accept.all(), k, jnp.argmax(~accept)).astype(jnp.int32)
                # Token at row m: on a rejection (m < k), resample from the
                # residual norm(max(p_t − p_d, 0)) — together with the
                # accept rule this reproduces p_t exactly (Leviathan et al.
                # 2023, Thm 1). When all k accepted (m == k) the draft has
                # no row there; d_row degrades to zeros so the "residual"
                # is exactly p_t — the standard bonus sample.
                d_row_m = jnp.where(
                    m < k, d_rows[jnp.minimum(m, k - 1)], jnp.zeros_like(d_rows[0]))
                t_row_m = t_dist[m]
                resid = jnp.maximum(t_row_m - d_row_m, 0.0)
                z = resid.sum()
                # z == 0 only if p_t <= p_d pointwise, i.e. p_t == p_d: any
                # draw from p_t is then distribution-correct.
                resid = jnp.where(z > 0, resid / z, t_row_m)
                rep = jax.random.categorical(r_rep, jnp.log(resid)).astype(jnp.int32)
                props_ext = jnp.concatenate(
                    [props, jnp.zeros((1,), jnp.int32)])  # (k+1,)
                w = jnp.where(jnp.arange(k + 1) == m, rep, props_ext)
            buf2 = lax.dynamic_update_slice(s["buf"], w[None, :], (0, s["n"]))
            committed = m + 1
            return {
                "t_cache": t_cache, "d_cache": d_cache, "buf": buf2,
                "n": s["n"] + committed, "last": w[m][None],
                "pos": pos + committed,
                "prev_chunk": chunk[0], "prev_pos": pos,
                "rng": rng, "iters": s["iters"] + 1,
                "accepted": s["accepted"] + m,
            }

        out = lax.while_loop(lambda s: s["n"] < max_new_tokens, body, state)
        tokens = out["buf"][:, :max_new_tokens]
        if return_stats:
            stats = {"iterations": out["iters"],
                     "proposed": out["iters"] * k,
                     "accepted": out["accepted"]}
            return tokens, stats
        return tokens

    return generate
