"""LM serving daemon: the ContinuousBatcher behind the gRPC edge.

The reference's defining trait is a long-lived serving *process*
(/root/reference/node.py:114-133 hosts a gRPC server until termination);
its only workload is one CNN forward per request. The rebuild's LM analog
is this daemon: a `NodeService` server whose SendTensor accepts a PROMPT
(1-D int32 token ids) and answers with the GENERATED TOKENS, decoding all
in-flight requests together through one continuous-batching pool
(dnn_tpu/runtime/serving.py) — requests enter and leave slots
independently, so concurrent callers share full batch width.

Wire-compatible by construction: every reference RPC is byte-identical
(dnn_tpu/comm/wire.proto keeps node_service.proto's methods, messages and
field numbering untouched) — a reference-built client drives this server
unmodified. Generation options ride the existing `request_id` field as
"gen[:max_new[:seed]]" (anything unparseable falls back to server
defaults). One ADDITIVE method exists beyond the reference protocol:
`GenerateStream`, the per-token streaming front (new method name;
reference peers never call it, so compatibility is preserved).

Threading model: gRPC handlers are async, device compute is blocking, so
ONE worker thread owns the batcher — it admits queued prompts whenever
slots free up, steps the pool while anything is active, and resolves a
`concurrent.futures.Future` per request that the async handlers await via
`asyncio.wrap_future`. Handlers never touch the device; the pool never
blocks the event loop (the reference blocks its loop on every hop,
node.py:181 — SURVEY §3.3).
"""

from __future__ import annotations

import asyncio
import json
import logging
import queue
import threading
import time
from typing import Any, NamedTuple, Optional

import grpc
import numpy as np

from dnn_tpu import obs
from dnn_tpu.chaos import inject as _chaos_inject
from dnn_tpu.comm import transport as _tx
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.comm.service import (
    PayloadCorruptError,
    _handlers,
    _tensor_arr,
    _tensor_msg,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

log = logging.getLogger("dnn_tpu.lm_server")

__all__ = ["LMServer", "serve_lm", "start_lm_server_in_background",
           "parse_gen_options", "DrainingError", "EXIT_RESTART"]

#: exit code serve_lm returns when a wedged-policy escalation asked the
#: SUPERVISOR (node --supervise / chaos.supervisor) to restart this
#: process — distinct from crash (nonzero) and clean shutdown (0) so an
#: operator reading the supervisor log can tell policy from accident
EXIT_RESTART = 43


class DrainingError(RuntimeError):
    """A request rejected because the server is DRAINING: admission is
    closed, in-flight decodes are finishing, and this request should be
    retried against another replica. Maps to gRPC UNAVAILABLE — which
    the edge client's existing retry ladder already treats as
    retriable — so queued work is handed BACK, never lost."""


def parse_gen_options(request_id: str, default_max_new: int):
    """'gen[:max_new[:seed]][:t=TEMP][:k=TOPK][:p=TOPP][:m=MINP]
    [:r=REPPEN][:b=ID~VAL,ID~VAL][:a=ADAPTER][:j=JSONDEPTH]'
    -> (max_new, seed, opts).
    Only the literal 'gen' prefix carries options —
    any other request_id (e.g. a reference client's tracing id like
    'req:1234') gets the server defaults instead of being reinterpreted as
    a token budget. Positional segments are max_new then seed; named
    `key=value` segments (per-request sampling overrides, forwarded to
    ContinuousBatcher.submit) may appear anywhere after the prefix.
    Unparseable segments fall back to defaults (seed None = derive from
    the request id, the batcher's own convention). Unknown named
    segments are skipped — in particular `tr=...`, the obs layer's trace
    tag (dnn_tpu/obs.tag_request_id), rides through here untouched."""
    max_new, seed, opts = default_max_new, None, {}
    parts = (request_id or "").split(":")
    if parts[0] != "gen":
        return max_new, seed, opts
    def _parse_bias(val: str) -> dict:
        # "ID~VAL,ID~VAL" — ":" is the segment separator, so pairs ride
        # "~" within one segment
        out = {}
        for pair in val.split(","):
            tok, _, v = pair.partition("~")
            out[int(tok)] = float(v)
        return out

    named = {"t": ("temperature", float), "k": ("top_k", int),
             "p": ("top_p", float), "a": ("adapter", int),
             "m": ("min_p", float), "r": ("repetition_penalty", float),
             "b": ("logit_bias", _parse_bias),
             # exactly-once guard: admission dedups on this opaque key
             # (LMServer._dedup) so a client retry after a drain or a
             # worker-death requeue can never run the generation twice
             "d": ("dedup", str),
             # disaggregated prefill/decode (dnn_tpu/control): consume
             # the kvput:<key> payload a prefill replica handed off —
             # admission then ADOPTS the KV instead of prefilling
             # (LMServer._resolve_kv_handle -> submit(prefilled=...))
             "h": ("kv_handle", str),
             # JSON mode: constrain the completion to a JSON value nested
             # up to DEPTH levels (runtime/constrain.json_regex); resolved
             # to a compiled TokenConstraint in LMServer._preflight
             "j": ("json_depth", int)}
    pos = 0
    for seg in parts[1:]:
        if "=" in seg:
            key, _, val = seg.partition("=")
            if key in named:
                name, conv = named[key]
                try:
                    opts[name] = conv(val)
                except ValueError:
                    pass
            continue
        pos += 1
        try:
            if pos == 1:
                max_new = max(1, int(seg))
            elif pos == 2:
                seed = int(seg)
        except ValueError:
            pass
    return max_new, seed, opts


def _fail_future(fut, exc):
    """set_exception tolerant of a future the caller already abandoned
    (cancelled via asyncio.wait_for on its deadline) — InvalidStateError
    out of a cleanup path must never kill the worker."""
    try:
        if not fut.done():
            fut.set_exception(exc)
    except Exception:  # noqa: BLE001 — done()/set race with a cancel
        pass


class _QueuedRequest(NamedTuple):
    """One request waiting for the batcher worker — named fields so the
    submit/admit/hold/drain sites stay self-describing (the tuple form
    needed every unpack edited in lockstep per added field)."""

    prompt: Any
    max_new: int
    seed: Any
    opts: Optional[dict]
    on_token: Any
    cancel_evt: Any
    trace: Any
    t_q: float  # perf_counter at enqueue — the queue-wait clock
    fut: Any
    attempts: int = 0  # worker-death requeues consumed (retry budget)


class _BatcherWorker(threading.Thread):
    """The one thread that talks to the device. Owns the ContinuousBatcher;
    everyone else submits (prompt, max_new, seed, future) through a queue."""

    def __init__(self, batcher: ContinuousBatcher,
                 compile_cache_budget: int = 512):
        super().__init__(daemon=True, name="lm-batcher")
        self.batcher = batcher
        # guard against unbounded XLA compile-cache growth (the suite's
        # segfault pathology — utils/xla_cache.py): counts the batcher's
        # compiled programs and clears ALL caches at the idle boundary
        # when the budget trips. A steady server (three programs) never
        # reaches 512; shape-churning workloads (many prompt buckets,
        # adapters, pooling variants) do, and recompile after the clear.
        from dnn_tpu.utils.xla_cache import CompileCacheGuard

        self.cache_guard = CompileCacheGuard(compile_cache_budget)
        for fn in batcher.jit_programs():  # spec variants add their own
            self.cache_guard.register(fn)
        self.q: "queue.Queue" = queue.Queue()
        self._stop_evt = threading.Event()
        self._abandon = False
        self._draining = False
        # worker-death hook (LMServer._on_worker_death): when set, a
        # step crash hands the surviving work (in-flight + queued
        # items) to the owner for requeue-or-fail instead of failing
        # everything — the recovery half of the `worker_died` event
        self.on_death = None
        # watchdog heartbeat (obs/watchdog.py): LMServer points this at
        # Watchdog.beat — one None check per loop iteration when off.
        # step_done -> Watchdog.step_done: until the first completed
        # step, a stale heartbeat is first-compile warmup, not a wedge
        self.heartbeat = None
        self.step_done = None
        # auto-profile arm (obs/profile.py, POST /profilez?auto=1): when
        # set, the loop times each step and captures the one AFTER the
        # first that exceeds the threshold; one None check per step when
        # disarmed
        self.auto_profile = None
        self._profile_hit = False
        # goodput/SLO tracker (obs/goodput.py): LMServer points this at
        # its GoodputTracker — _admit feeds the TTFT objective; one
        # None check when off
        self.goodput = None
        self._held_logged = None  # last item whose hold hit the flight
        # ring — identity-gates the per-retry held_back event
        # _lock serializes submit against the dead-marking in _fail_all /
        # abandon: without it a future enqueued between the worker's final
        # queue drain and thread exit would never resolve (the caller
        # would hang for request_timeout instead of failing fast)
        self._lock = threading.Lock()
        self._dead: "Exception | None" = None
        # rid -> {"fut", "on_token", "cancel_evt"}
        self._futures = {}
        # paged back-pressure: a request the batcher could not admit for
        # TRANSIENT lack of pool blocks (paged_kvcache.InsufficientBlocks)
        # waits here — retried ahead of the queue once decodes retire —
        # instead of failing its caller
        self._held = None
        # control ops (dnn_tpu/kvtier): batcher mutations that are NOT
        # request admissions — stage_prefix / kvtier_export /
        # kvtier_adopt all reassign pool leaves, so they MUST run on
        # this thread between steps (the single-producer contract the
        # donation invariant rests on). Drained at the top of every
        # loop iteration: a busy pool still applies a pull within one
        # step, not only at idle.
        self._cq: list = []
        # periodic housekeeping hook (LMServer wires lease/handoff TTL
        # sweeps): called once per loop iteration, rate-limited inside
        self.tick = None

    def submit(self, prompt: np.ndarray, max_new: int, seed, *,
               opts=None, on_token=None, cancel_evt=None, trace=None):
        """Queue a request. `opts` (optional dict) forwards per-request
        sampling overrides to ContinuousBatcher.submit (temperature /
        top_k / top_p). `on_token(tok)` (optional) fires from the worker
        thread for every token as it commits — the streaming hook.
        `cancel_evt` (optional threading.Event) set by the caller retires
        the request's slot at the next step boundary; its future resolves
        cancelled. `trace` (optional obs span) parents this request's
        span tree: the worker records queue_wait at admission and the
        batcher hangs admit/prefill/decode spans under it."""
        import concurrent.futures

        fut = concurrent.futures.Future()
        with self._lock:
            if self._draining and self._dead is None:
                # admission is CLOSED but the pool is still finishing:
                # hand the request straight back with the retriable
                # draining status (never enqueue work the drain exit
                # would have to fail later anyway). Through the guarded
                # settle (CON002): the future is fresh here, but every
                # settle in this module goes through one guarded path —
                # the unguarded form is exactly the PR 4 worker-killer.
                _fail_future(fut, DrainingError(
                    "LM server draining: admission closed; retry "
                    "against another replica"))
                return fut
            if self._dead is not None:
                _fail_future(fut, self._dead)
                if (g := self.goodput) is not None:
                    g.on_outcome(False)  # fast-fails burn availability
                return fut
            self.q.put(_QueuedRequest(prompt, max_new, seed, opts,
                                      on_token, cancel_evt, trace,
                                      time.perf_counter(), fut))
            m = obs.metrics()
            if m is not None:
                # CALLABLE gauge: the shutdown/failure paths drain the
                # queue with bare get_nowait(), so a stored depth would
                # freeze at its pre-drain value — qsize reads fresh at
                # every scrape instead
                m.set_fn("serving.queue_depth", self.q.qsize)
        return fut

    def submit_control(self, fn):
        """Queue `fn()` to run on the worker thread between steps (the
        KV-tier seam: stage/export/adopt mutate pool state the step
        loop owns). Returns a concurrent.futures.Future resolving to
        fn()'s result; fails fast when the worker is dead."""
        import concurrent.futures

        fut = concurrent.futures.Future()
        with self._lock:
            if self._dead is not None:
                _fail_future(fut, self._dead)
                return fut
            self._cq.append((fn, fut))
        return fut

    def _run_control_ops(self):
        """Drain queued control ops — top of every loop iteration, so
        a pull lands within one step even on a busy pool. Settles are
        guarded (CON002): the caller may have deadline-cancelled."""
        while True:
            with self._lock:
                if not self._cq:
                    return
                fn, fut = self._cq.pop(0)
            try:
                res = fn()
            except BaseException as e:  # noqa: BLE001 — the op's error
                # belongs to its caller, never to the serving loop
                _fail_future(fut, e)
            else:
                try:
                    fut.set_result(res)
                except Exception:  # noqa: BLE001 — abandoned future
                    pass

    def _fail_control(self, exc):
        """Fail every queued control op (worker death / shutdown)."""
        with self._lock:
            ops, self._cq = self._cq, []
        for _fn, fut in ops:
            _fail_future(fut, exc)

    def _resubmit(self, item: _QueuedRequest) -> bool:
        """Requeue a surviving item from a DEAD predecessor worker,
        preserving its future / queue clock / attempt count. False when
        this worker is itself already dead (the caller then fails the
        item's future)."""
        with self._lock:
            if self._dead is not None or self._draining:
                return False
            self.q.put(item)
        return True

    def begin_drain(self):
        """Connection-draining entry: stop admission NOW, finish
        in-flight decodes, hand queued-but-unadmitted work back with
        the retriable draining status, then exit the thread. The run
        loop notices `_draining` at its next iteration; submit() starts
        rejecting immediately."""
        with self._lock:
            self._draining = True
        self._stop_evt.set()  # wake a worker parked in q.get(timeout)
        obs.flight.record("drain_begin", queued=self.q.qsize(),
                          active=self.batcher.n_active)

    def _drain_handback(self):
        """Fail every queued (never-admitted) item with the RETRIABLE
        draining error — the hand-back half of draining. Held-back
        items never prefilled, so they hand back too."""
        exc = DrainingError(
            "LM server draining: request was queued but not admitted; "
            "retry against another replica")
        n = 0
        with self._lock:
            if self._held is not None:
                held, self._held = self._held, None
                _fail_future(held.fut, exc)
                n += 1
            while True:
                try:
                    _fail_future(self.q.get_nowait().fut, exc)
                    n += 1
                except queue.Empty:
                    break
        if n:
            obs.flight.record("drain_handback", requests=n)

    def stop(self, *, drain: bool = True):
        """Signal shutdown. drain=True: the loop exits once the pool and
        queue are empty. drain=False: abandon in-flight decodes too —
        queued futures are cancelled here, admitted ones by the loop on
        its next iteration (the worker must not keep stepping the device
        after close())."""
        with self._lock:
            if not drain:
                self._abandon = True
                if self._dead is None:
                    self._dead = RuntimeError("LM server shut down")
                while True:
                    try:
                        self.q.get_nowait().fut.cancel()
                    except queue.Empty:
                        break
            elif self._dead is None:
                # drain path: mark dead BEFORE signaling stop so a submit
                # racing the loop's final pool-empty/queue-empty check fails
                # fast instead of enqueueing a future after the thread
                # exits (which would hang its caller for request_timeout).
                # Items already queued under the lock are still drained.
                self._dead = RuntimeError("LM server shutting down")
        self._stop_evt.set()

    # ------------------------------------------------------------------

    def _admit(self, item: _QueuedRequest) -> bool:
        """Admit one queued request. Returns False when the request was
        HELD BACK (paged pool transiently full) — the admission loop must
        then stop pulling more work until blocks free (`t_q` is preserved
        through holds, so the recorded queue wait spans until the attempt
        that actually admits)."""
        from dnn_tpu.runtime.paged_kvcache import InsufficientBlocks

        if item.cancel_evt is not None and item.cancel_evt.is_set():
            item.fut.cancel()  # cancelled while still queued: never admit
            return True
        wait = time.perf_counter() - item.t_q
        try:
            if _chaos_inject.kv_exhaust():
                # injected pool exhaustion (dnn_tpu/chaos): exercises
                # the held-back path below exactly as a real full pool
                raise InsufficientBlocks(
                    "chaos: injected KV pool exhaustion")
            rid = self.batcher.submit(item.prompt, item.max_new,
                                      seed=item.seed, trace=item.trace,
                                      **(item.opts or {}))
        except InsufficientBlocks:
            # flight: submit() already recorded pool_exhausted (once per
            # episode); this is the queueing front's held-back decision —
            # recorded once per ITEM, not once per retry (the run loop
            # re-submits the held item every decode step, which at ms
            # cadence would flood the ring during a long shortage)
            if item is not self._held_logged:
                obs.flight.record("held_back", queue_depth=self.q.qsize())
                self._held_logged = item
            self._held = item
            return False
        except Exception as e:  # noqa: BLE001 — validation errors belong to
            obs.flight.record("admit_rejected", error=str(e)[:200])
            # the submitting request, not the loop — and guarded: the
            # caller may have deadline-cancelled this future while it
            # queued, and an InvalidStateError here would kill the worker
            _fail_future(item.fut, e)
            return True
        obs.flight.record(
            "admit", rid=rid, queue_wait_ms=round(wait * 1e3, 3),
            prompt_len=int(np.asarray(item.prompt).size),
            max_new=item.max_new,
            trace_id=item.trace.trace_id if item.trace else None)
        # first token: the convoy path samples it during submit()'s
        # inline prefill; interleaved admission (prefill_chunk_tokens)
        # defers it to a later mixed step's commit — first_token then
        # reads None and TTFT is recorded when the rid first appears in
        # the step loop's output instead
        first = self.batcher.first_token(rid)
        m = obs.metrics()
        if m is not None:
            m.observe("serving.queue_wait_seconds", wait)
            m.set_fn("serving.queue_depth", self.q.qsize)
            if first is not None:
                # end-to-end TTFT: enqueue -> first token (sampled
                # during the batcher's prefill, which submit() just ran)
                ttft = time.perf_counter() - item.t_q
                m.observe("serving.ttft_seconds", ttft)
                if (g := self.goodput) is not None:
                    g.on_ttft(ttft)  # SLO burn-rate window (obs/goodput)
        if item.trace:
            obs.record_span("queue_wait", item.t_q, wait,
                            parent=item.trace)
        rec = {"fut": item.fut, "on_token": item.on_token,
               "cancel_evt": item.cancel_evt,
               # the original submission, kept so a worker death can
               # requeue it (attempts bounds the retries; lm_server
               # _on_worker_death)
               "item": item}
        if first is None:
            rec["ttft_t0"] = item.t_q  # deferred: the run loop records
            # TTFT at the first committed token
        self._futures[rid] = rec
        if item.on_token is not None and first is not None:
            self._emit_token(rid, first)
        return True

    def _emit_token(self, rid, tok):
        rec = self._futures.get(rid)
        if rec is None or rec["on_token"] is None:
            return
        try:
            rec["on_token"](int(tok))
        except Exception:  # noqa: BLE001 — a dead stream consumer must not
            log.debug("on_token callback failed for rid %d", rid,
                      exc_info=True)  # kill the device loop

    def _process_cancels(self):
        """Retire cancelled requests at the step boundary: the slot
        re-enters the free pool (batcher.cancel) and the future resolves
        cancelled — the caller's disconnect must not decode on to its
        token budget."""
        for rid, rec in list(self._futures.items()):
            evt = rec["cancel_evt"]
            if evt is not None and evt.is_set():
                if self.batcher.cancel(rid):
                    try:  # drop the cancelled record — nobody claims it
                        self.batcher.claim(rid)
                    except KeyError:
                        pass
                del self._futures[rid]
                rec["fut"].cancel()

    def _publish_done(self):
        b = self.batcher
        for rid in [r for r in self._futures if r in b.results]:
            # claim (not read) releases the batcher's per-request
            # bookkeeping — results, finish reason, logprobs — so a
            # long-lived daemon's dicts don't grow without bound
            tokens, _reason, _lps = b.claim(rid)
            fut = self._futures.pop(rid)["fut"]
            try:
                fut.set_result(tokens)
            except Exception:  # noqa: BLE001 — the caller abandoned the
                # future (a unary deadline abort cancels it through
                # asyncio.wait_for -> wrap_future); publishing to a
                # cancelled future raises InvalidStateError and used to
                # KILL the worker thread — the result is simply dropped
                pass

    def _shutdown_drain_queue(self):
        """Final drain-path exit step, under _lock: mark dead and fail any
        future that slipped into the queue between the loop's last
        queue-empty check and its stop-event check (the TOCTOU window —
        submit saw _dead=None and enqueued just before stop() marked dead).
        Failing fast here bounds that racer to an immediate shutdown error
        instead of a request_timeout hang."""
        with self._lock:
            if self._dead is None:
                self._dead = RuntimeError("LM server shutting down")
            if self._held is not None:
                held, self._held = self._held, None
                _fail_future(held.fut, self._dead)
        self._fail_control(self._dead)
        with self._lock:
            while True:
                try:
                    _fail_future(self.q.get_nowait().fut, self._dead)
                except queue.Empty:
                    return

    def _collect_for_requeue(self):
        """Death-path collection for the requeue hook: mark this worker
        dead (so racing submits fail fast) and hand over the surviving
        work — [(rid, item)] for admitted-but-unfinished requests,
        [item] for queued/held ones. The futures stay UNRESOLVED; the
        hook owns their fate (requeue into a successor worker, or
        fail)."""
        with self._lock:
            if self._dead is None:
                self._dead = RuntimeError("LM batcher worker died")
        # control ops are replica-local pool mutations: never requeued
        # onto a successor (its pool is fresh — a stale pull would
        # ingest against different block ids); their callers retry
        self._fail_control(self._dead)
        with self._lock:
            inflight = [(rid, rec["item"])
                        for rid, rec in self._futures.items()
                        if rec.get("item") is not None]
            self._futures.clear()
            queued = []
            if self._held is not None:
                queued.append(self._held)
                self._held = None
            while True:
                try:
                    queued.append(self.q.get_nowait())
                except queue.Empty:
                    break
        return inflight, queued

    def _fail_all(self, exc):
        self._fail_control(exc)
        with self._lock:
            self._dead = exc  # submits from here on fail immediately
            failed = len(self._futures)
            for rec in self._futures.values():
                _fail_future(rec["fut"], exc)
            self._futures.clear()
            if self._held is not None:
                held, self._held = self._held, None
                _fail_future(held.fut, exc)
                failed += 1
            while True:
                try:
                    _fail_future(self.q.get_nowait().fut, exc)
                    failed += 1
                except queue.Empty:
                    break
        # error-path failures must burn the availability budget too — a
        # worker death that fails every in-flight request is exactly the
        # outage the objective exists to page on (retirement-path
        # outcomes feed from _obs_retire; this path never retires)
        if (g := self.goodput) is not None:
            for _ in range(failed):
                g.on_outcome(False)

    def _step_pool(self, b):
        """One pool step, with the auto-profile arm folded in: disarmed
        (the steady state) this is one None check around b.step().
        Armed, each step is timed; the step AFTER the first breach runs
        inside a jax.profiler capture (obs/profile.py) and disarms."""
        _chaos_inject.step_fault()  # injected device fault: raises at
        # the scheduled step counter -> the ordinary worker-death path
        ap = self.auto_profile
        if ap is None:
            self._profile_hit = False
            return b.step()
        if self._profile_hit:
            from dnn_tpu.obs.profile import ProfilerBusy, capture_step

            self.auto_profile = None
            self._profile_hit = False
            try:
                path, stepped = capture_step(
                    b.step, capture_root=ap.get("capture_root"),
                    keep=ap.get("keep", 8), extra_s=ap.get("extra_s", 0.0))
                log.info("auto-profile captured slow-step follow-up to %s",
                         path)
                return stepped
            except ProfilerBusy:
                return b.step()
        t0 = time.perf_counter()
        stepped = b.step()
        if time.perf_counter() - t0 > ap["threshold_s"]:
            self._profile_hit = True
        return stepped

    def run(self):
        b = self.batcher
        while True:
            hb = self.heartbeat
            if hb is not None:
                hb()
            # KV-tier control ops + housekeeping tick: between steps,
            # on the one thread that owns the pool (one len-check /
            # None-check each when idle)
            if self._cq:
                self._run_control_ops()
            tk = self.tick
            if tk is not None:
                tk()
            if self._abandon:
                self._fail_control(RuntimeError("LM server shut down"))
                with self._lock:
                    for rec in self._futures.values():
                        rec["fut"].cancel()
                    self._futures.clear()
                    if self._held is not None:
                        held, self._held = self._held, None
                        held.fut.cancel()
                return
            self._process_cancels()  # step boundary: free cancelled slots
            if self._draining:
                # connection draining: queued work handed back
                # retriable, in-flight decodes stepped to completion
                # below, then a clean exit (submit already rejects)
                self._drain_handback()
                if b.n_active == 0:
                    with self._lock:
                        if self._dead is None:
                            self._dead = DrainingError(
                                "LM server drained and exited")
                    self._fail_control(self._dead)
                    obs.flight.record("drain_done")
                    return
            elif b.n_active == 0 and self.q.empty() and self._held is None:
                # overlap mode: the pool emptied with one dispatched
                # step still uncommitted (its tokens are all past
                # retirement) — commit it so its bookkeeping (StepClock
                # record, discarded tokens) never dangles across idle
                fo = getattr(b, "flush_overlap", None)
                if fo is not None:
                    fo()
                if self._stop_evt.is_set():
                    self._shutdown_drain_queue()
                    return
                # SAFE BOUNDARY: nothing in flight, nothing queued — the
                # only place the worker may drop compiled executables.
                # Bounds the week-long daemon against the compile-cache
                # growth pathology that segfaults XLA's CPU compiler in
                # the test suite (utils/xla_cache.py has the story);
                # cleared programs recompile transparently on next use.
                # A guard failure must never kill the worker (callers
                # would hang to request_timeout) — serving correctness
                # does not depend on the clear happening.
                try:
                    self.cache_guard.maybe_clear()
                except Exception:  # noqa: BLE001
                    log.exception("compile-cache guard failed; continuing")
                try:
                    self._admit(self.q.get(timeout=0.1))
                except queue.Empty:
                    continue
            while not self._draining and b.free_slots():
                if self._held is not None:
                    # retry the held-back request before new work; still
                    # short on blocks -> keep holding, stop admitting
                    item, self._held = self._held, None
                    if not self._admit(item):
                        break
                    continue
                try:
                    if not self._admit(self.q.get_nowait()):
                        break
                except queue.Empty:
                    break
            had_active = bool(b.n_active)
            try:
                stepped = self._step_pool(b) if had_active else {}
            except Exception as e:  # noqa: BLE001 — one device-side error
                # must not leave callers hanging for request_timeout:
                # either hand the surviving work to the owner's
                # requeue-or-fail hook (LMServer._on_worker_death spawns
                # a successor worker), or fail every pending future fast
                # and die visibly (HealthCheck reports not-alive;
                # SendTensor aborts UNAVAILABLE)
                handler = self.on_death
                obs.flight.record("worker_died", error=str(e)[:500],
                                  pending=len(self._futures),
                                  requeue=handler is not None)
                if handler is not None:
                    log.exception("batcher worker died; handing %d "
                                  "in-flight + queued requests to the "
                                  "requeue hook", len(self._futures))
                    inflight, queued = self._collect_for_requeue()
                    try:
                        handler(e, inflight, queued)
                        return
                    except Exception:  # noqa: BLE001 — a broken hook
                        # must not strand the collected futures
                        log.exception("worker-death requeue hook failed;"
                                      " failing survivors")
                        exc = RuntimeError(
                            f"LM batcher worker died: {e}")
                        for _rid, it in inflight:
                            _fail_future(it.fut, exc)
                        for it in queued:
                            _fail_future(it.fut, exc)
                        return
                log.exception("batcher worker died; failing %d pending "
                              "requests", len(self._futures))
                self._fail_all(RuntimeError(f"LM batcher worker died: {e}"))
                return
            if had_active and (sd := self.step_done) is not None:
                sd()  # a real step completed: the watchdog is warmed
            for rid, tok in stepped.items():  # streaming: tokens as they
                # commit, before done-publish; the speculative batcher
                # (and an interleaved deferred-first commit) deliver a
                # LIST of tokens per step
                rec = self._futures.get(rid)
                if rec is not None and "ttft_t0" in rec:
                    # interleaved admission: this is the request's FIRST
                    # committed token — record the real TTFT now
                    t0 = rec.pop("ttft_t0")
                    m = obs.metrics()
                    if m is not None:
                        ttft = time.perf_counter() - t0
                        m.observe("serving.ttft_seconds", ttft)
                        if (g := self.goodput) is not None:
                            g.on_ttft(ttft)
                if isinstance(tok, (list, tuple)):
                    for t in tok:
                        self._emit_token(rid, t)
                else:
                    self._emit_token(rid, tok)
            self._publish_done()  # submit alone can retire (budget == 1)


class LMServer:
    """NodeService servicer mapping SendTensor(prompt) -> generated tokens.

    Build with the same (cfg, prepared) pair the batcher takes; batcher
    kwargs pass through (slots, max_len, prompt_pad, temperature, top_k,
    top_p, compute_dtype, eos_id, seed, ffn, kv_dtype, family — `ffn` is
    how the MoE family serves,
    dnn_tpu/runtime/generate_moe.moe_cache_ffn). Two of them shape the
    daemon's decode-bandwidth story (both length-aware, both default-on
    or opt-in as noted): `attn_kernel` defaults to "auto" — long-context
    cache attention streams through the position-clamped Pallas kernel
    on TPU, the einsum elsewhere (runtime/kvcache.AUTO_KERNEL_MIN_S) —
    and `decode_buckets=True` grows the dense pool bucket-by-bucket so
    decode bytes/step track the pool's LIVE context instead of max_len
    (runtime/decode_buckets.py; dense pools only — paged pools are
    already length-proportional).

    Observability (dnn_tpu/obs): every request gets a span tree (queue
    wait, admit, prefill, per-bucket decode; trace id continued from a
    client's `tr=` request_id tag), the pool exports TTFT / inter-token
    / occupancy / queue-depth / memory-watermark metrics, a
    jax.monitoring listener counts XLA compiles, and serving events
    (admissions, deadline misses, worker death) feed the flight
    recorder — dumped automatically on unhandled crash. `metrics_port`
    (None = no endpoint; 0 = ephemeral) serves it all over stdlib HTTP:
    GET /metrics (Prometheus text), /trace (Chrome-trace JSON, ?id= for
    one request), /debugz (flight ring), /statusz (watchdog detail),
    /healthz, POST /profilez (on-demand jax.profiler capture, ?auto=1
    arms capture-the-next-slow-step). `watchdog` (None/False = off;
    True or a period in seconds, or a prebuilt obs.watchdog.Watchdog)
    runs the hung-device watchdog: subprocess-bounded device probes plus
    this worker's loop heartbeat decide ok|degraded|wedged."""

    def __init__(self, cfg, prepared, *, default_max_new: int = 32,
                 request_timeout: float = 120.0, tokenizer=None,
                 draft_cfg=None, draft_prepared=None, spec_k: int = 4,
                 compile_cache_budget: int = 512,
                 metrics_port: Optional[int] = None,
                 watchdog=None,
                 goodput=None, slo=None,
                 on_wedged: str = "503",
                 worker_restarts: int = 2,
                 max_request_retries: int = 1,
                 drain_grace_s: float = 30.0,
                 weights: str = "f32",
                 role: str = "both",
                 kv_handoff_cap: int = 64,
                 kv_handoff_ttl_s: float = 120.0,
                 kv_lease_ttl_s: float = 30.0,
                 **batcher_kwargs):
        # weight-only quantized serving (ISSUE 12 satellite — the first
        # rung of ROADMAP item 4's weight-quant ladder): weights="int8"
        # quantizes the served tree ONCE at construction (quant.py's
        # symmetric per-output-channel scheme; every matmul funnels
        # through ops.nn.linear, which dispatches on the q dtype), so
        # decode streams ~4x fewer weight bytes per step. The goodput
        # MBU denominator prices the quantized tree exactly
        # (utils/flops.tree_weight_bytes) because model_cost below sums
        # the REAL leaves of the tree the batcher actually serves.
        if weights not in ("f32", "int8"):
            raise ValueError(
                f"weights must be 'f32' or 'int8', got {weights!r}")
        if weights == "int8":
            if batcher_kwargs.get("lora_adapters"):
                raise ValueError(
                    "weights='int8' does not compose with LoRA serving: "
                    "lora_view applies low-rank deltas to float kernels, "
                    "not quantized {q, scale} pairs")
            from dnn_tpu.quant import quantize_gpt

            prepared = quantize_gpt(prepared, bits=8)
        self.weights = weights
        # resilience state (ISSUE 8) before anything that can serve a
        # request or a scrape: drain flag, wedged-policy escalation
        # latch, admission dedup, worker-restart bookkeeping
        if on_wedged not in ("503", "restart", "drain"):
            raise ValueError(
                f"on_wedged must be 503|restart|drain, got {on_wedged!r}")
        # fleet role (dnn_tpu/control, disaggregated prefill/decode):
        # ADVISORY — the router routes prefill exports to `prefill`
        # replicas and generation to `decode`/`both`; the server itself
        # serves every endpoint whatever its role (a mis-routed request
        # still answers correctly, just on the wrong replica's FLOPs).
        # Advertised on /statusz (the FleetCollector's per-replica role
        # column) and as the dnn_tpu_replica_role gauge.
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be prefill|decode|both, got {role!r}")
        self.role = role
        # prefill->decode KV handoff inbox (kvput:<key> ingests, the
        # h=<key> gen option consumes exactly once): bounded LRU — an
        # orphaned handoff (router died between kvput and gen) must not
        # hold row-cache-sized payloads forever. Entries are ALSO
        # time-bounded: staged handoffs carry an ingest timestamp and
        # the worker's housekeeping tick sweeps entries older than
        # `kv_handoff_ttl_s` with a `kvput_expired` flight event — a
        # cap alone let one abandoned prefill pin a row-sized payload
        # until 63 siblings arrived to push it out (ttl <= 0 disables)
        self._kv_handoff: "dict" = {}
        self._kv_lock = threading.Lock()
        self._kv_handoff_cap = int(kv_handoff_cap)
        self._kv_handoff_ttl_s = float(kv_handoff_ttl_s)
        self._kv_lease_ttl_s = float(kv_lease_ttl_s)
        self._kvtier_leases = None  # built after the batcher (kvtier
        # endpoints exist only when the radix store is on)
        self._hk_last = 0.0
        self.on_wedged = on_wedged
        self.worker_restarts = int(worker_restarts)
        self.max_request_retries = int(max_request_retries)
        self.drain_grace_s = float(drain_grace_s)
        self._draining = False
        self._drain_thread = None
        self._drain_lock = threading.Lock()
        self._escalated = threading.Event()
        self._escalate_reason: Optional[str] = None
        self._restart_lock = threading.Lock()
        self._restart_times: list = []
        self._restart_window_s = 300.0
        self._dedup_lock = threading.Lock()
        self._dedup: "dict" = {}   # key -> worker future (insertion-ordered)
        self._DEDUP_CAP = 512
        # observability first: the compile listener must be live before
        # the batcher's first program compiles, so jax_compilations_total
        # counts the daemon's own warmup too (dnn_tpu/obs)
        obs.install_compile_telemetry()
        if (m := obs.metrics()) is not None:
            from dnn_tpu.utils.metrics import labeled

            m.set(labeled("dnn_tpu_replica_role", role=self.role), 1.0)
        if obs.enabled():
            # black box: an unhandled crash anywhere in this process
            # dumps the flight ring (obs/flight.py) — the daemon is the
            # thing whose post-mortems matter
            obs.flight.install_crash_dump()
            from dnn_tpu.obs.mem import install_memory_gauges

            install_memory_gauges()
        self.metrics_server = None
        self._watchdog = None
        # step-timeline attribution (obs/timeline.py): the daemon's
        # decode steps feed a StepClock — /stepz serves the per-phase
        # decomposition, /statusz gains a `step` component, and the
        # profiler's sidecar meta records this clock's step-counter
        # range so a capture aligns to the step axis. Auto-built like
        # the goodput tracker; off with the obs gate.
        self.step_clock = None
        if obs.enabled():
            from dnn_tpu.obs.timeline import StepClock

            self.step_clock = StepClock().install()
        if metrics_port is not None:
            from dnn_tpu.obs.profile import Profiler

            # /metrics /trace /debugz /statusz /stepz /profilez
            # endpoint; /healthz mirrors HealthCheck, then degrades
            # through the watchdog's ok|degraded|wedged when attached
            self.metrics_server = obs.serve_metrics(
                metrics_port,
                healthy=lambda: (w := getattr(self, "worker", None))
                is not None and w.is_alive() and not self._draining,
                status=self._statusz,
                profiler=Profiler(arm_target=self),
                drain=self._drainz,
                stepclock=self.step_clock)
        try:
            self._init_rest(
                cfg, prepared, default_max_new=default_max_new,
                request_timeout=request_timeout, tokenizer=tokenizer,
                draft_cfg=draft_cfg, draft_prepared=draft_prepared,
                spec_k=spec_k, compile_cache_budget=compile_cache_budget,
                **batcher_kwargs)
            if getattr(self.batcher, "_prefix_store", None) is not None:
                # fleet KV tier live on this replica: donor-side lease
                # staging (kvlease/kvfetch/kvack — kvtier/migrate.py)
                from dnn_tpu.kvtier.migrate import LeaseTable

                self._kvtier_leases = LeaseTable(ttl_s=kv_lease_ttl_s)
            if self.metrics_server is not None:
                # /kvz comes alive once the batcher (and its lens)
                # exists — the endpoint was bound before the batcher,
                # so the lens is attached late (http.py reads it per
                # request). None when the obs gate or the KV tier is
                # off: /kvz then 404s honestly.
                self.metrics_server._kvlens = getattr(
                    self.batcher, "_kvlens", None)
            # housekeeping rides the worker loop (lease TTL + kvput
            # inbox TTL), rate-limited inside the tick
            self.worker.tick = self._housekeeping_tick
        except BaseException:
            # a failed construction (bad batcher kwargs) must release the
            # already-bound endpoint, or a retry hits EADDRINUSE forever
            if self.metrics_server is not None:
                self.metrics_server.close()
                self.metrics_server = None
            raise
        if watchdog:
            # hung-device watchdog (obs/watchdog.py): `watchdog` is True
            # (defaults), a float (period seconds), or a prebuilt
            # Watchdog (tests inject stubbed probes). Wired to the
            # worker's loop heartbeat + thread liveness, started here —
            # after _init_rest, so the worker exists to monitor.
            from dnn_tpu.obs.watchdog import Watchdog

            if isinstance(watchdog, Watchdog):
                self._watchdog = watchdog
            else:
                import functools

                import jax

                from dnn_tpu.obs.watchdog import subprocess_device_probe

                period = 30.0 if watchdog is True else float(watchdog)
                self._watchdog = Watchdog(
                    period_s=period,
                    # floor 6 s: the probe child pays ~4 s of import
                    # before its first device op — a shorter deadline
                    # reads a healthy backend as wedged
                    probe_deadline_s=min(10.0, max(6.0, period / 3)),
                    # pin the probe to THIS server's backend: a
                    # cpu-substrate daemon must not answer "is the TPU
                    # alive" (nor queue behind a chip it never uses)
                    device_probe=functools.partial(
                        subprocess_device_probe,
                        platform=jax.default_backend()))
            if self._watchdog.alive_check is None:
                # a LAMBDA over self.worker, not a bound method: the
                # worker-death requeue path swaps in a successor worker,
                # and a stale bound is_alive would read the corpse
                self._watchdog.alive_check = \
                    lambda: self.worker.is_alive()
            self.worker.heartbeat = self._watchdog.beat
            self.worker.step_done = self._watchdog.step_done
            if self.on_wedged != "503":
                # wedged is a POLICY now, not just a 503: the watchdog's
                # once-per-episode escalation hook fires the restart /
                # drain path (warm-up grace preserved — the watchdog
                # never reports wedged before the first completed step)
                self._watchdog.on_wedged = self._wedged_escalate
            if not self._watchdog._thread.is_alive():
                self._watchdog.start()
        # live goodput accounting (obs/goodput.py): dnn_tpu_mfu /
        # dnn_tpu_mbu / dnn_tpu_goodput_tokens_per_sec scrape-time
        # gauges + optional SLO burn rates. `goodput` is None (auto:
        # build from the model config when obs is enabled), False (off),
        # or a prebuilt GoodputTracker. `slo` is an obs.goodput.
        # SLOConfig (implies auto-build when goodput is None).
        self.goodput = None
        if goodput is None and obs.enabled():
            from dnn_tpu.obs.goodput import GoodputTracker, model_cost

            # same fallback chain as the batcher's cache allocation
            # (serving.py: kv_dtype, else the family's resolved
            # compute_dtype, else f32) — a bf16 server must not have its
            # MBU KV term priced at f32 width, and the QUANTIZED specs
            # ("int8"/"int4") price their packed payload + f32 scale
            # rows exactly (utils/flops.kv_bytes_per_pos kv_dtype=;
            # int4 at jnp.dtype itemsize would overstate 2x and miss
            # the scales)
            import jax.numpy as jnp

            kv_spec = (batcher_kwargs.get("kv_dtype")
                       or getattr(self.batcher.family, "compute_dtype",
                                  None)
                       or jnp.float32)
            try:
                cost = model_cost(cfg, prepared, kv_dtype=kv_spec)
            except Exception:  # noqa: BLE001 — exotic kv_dtype spec
                cost = model_cost(cfg, prepared, kv_bytes=2)
            self.goodput = GoodputTracker(cost, slo=slo).install()
        elif goodput:
            self.goodput = goodput.install()
        if self.goodput is not None:
            self.batcher.goodput = self.goodput
            self.worker.goodput = self.goodput
        if self.step_clock is not None:
            self.batcher.step_clock = self.step_clock

    @property
    def auto_profile(self):
        """POST /profilez?auto=1 arm state — delegates to the batcher
        worker (the thread that times and captures steps)."""
        return self.worker.auto_profile

    @auto_profile.setter
    def auto_profile(self, value):
        self.worker.auto_profile = value

    def _statusz(self):
        """The /statusz payload: watchdog state when one runs, else None
        — the HTTP handler then falls back to its worker-liveness shape
        (one fallback, not two drifting copies; obs/http.py). A DRAINING
        server overlays the `draining` state (unless already wedged) so
        routers/fleet collectors stop sending it work while in-flight
        decodes finish. Once the pool has stepped, a `step` component
        overlays the step clock's summary (last step duration, host
        fraction, steps/sec) so an operator can tell slow-but-healthy
        from wedged without pulling a profile — it reads the SAME
        worker loop the watchdog's decode heartbeat beats from, so
        their recency agrees; the component is informational (state
        "ok"), escalation stays the watchdog's."""
        s = self._watchdog.status() if self._watchdog is not None \
            else None
        sc = self.step_clock
        if sc is not None and sc.steps_total:
            if s is None:
                # no watchdog: synthesize the handler's worker-liveness
                # shape here so the step component still has a home
                alive = (w := getattr(self, "worker", None)) is not None \
                    and w.is_alive()
                s = {"state": "ok" if alive else "wedged",
                     "components": {"worker": {
                         "state": "ok" if alive else "wedged",
                         "detail": "serving worker thread liveness"}}}
            else:
                s = dict(s)
            comps = dict(s.get("components") or {})
            comps["step"] = sc.status_component()
            s["components"] = comps
        if s is None:
            # no watchdog and no step record yet: synthesize the
            # handler's worker-liveness shape so the payload still
            # carries the fleet-facing fields below (role — the
            # FleetCollector's per-replica role column reads /statusz)
            alive = (w := getattr(self, "worker", None)) is not None \
                and w.is_alive()
            s = {"state": "ok" if alive else "wedged",
                 "components": {"worker": {
                     "state": "ok" if alive else "wedged",
                     "detail": "serving worker thread liveness"}}}
        else:
            s = dict(s)
        s["role"] = self.role
        if self._kvtier_on():
            # KV-tier residency rides /statusz (informational): the
            # FleetCollector's per-replica rows read it next to role
            st = self.batcher._prefix_store
            comps = dict(s.get("components") or {})
            comps["kvtier"] = {
                "state": "ok",
                "detail": (f"resident_blocks={st.n_blocks} "
                           f"block_hits={st.block_hits} "
                           f"remote_hits={st.remote_block_hits} "
                           f"leases={self._kvtier_leases.n_leases}"),
                "kvtier_blocks": st.n_blocks,
            }
            s["components"] = comps
        if not self._draining:
            return s
        comps = dict(s.get("components") or {})
        comps["drain"] = {"state": "draining",
                          "detail": "admission closed; finishing "
                                    "in-flight decodes"}
        s["components"] = comps
        if s.get("state") != "wedged":
            s["state"] = "draining"
        return s

    # -- resilience: drain / requeue / wedged policy (ISSUE 8) ----------

    def _wedged_escalate(self, detail: str):
        """Watchdog wedged-episode hook (once per episode; obs/
        watchdog.py): turn the passive 503 into the configured policy.
        `restart` exits fast so the process supervisor relaunches from
        the latest checkpoint; `drain` finishes in-flight work first
        (on a wedged DEVICE that usually can't finish — the drain grace
        bounds the wait)."""
        obs.flight.record("wedged_policy", policy=self.on_wedged,
                          detail=str(detail)[:300])
        if self.on_wedged == "drain":
            self._drainz()
            # the drain thread sets _escalated when done (or grace out)
        else:
            self._escalate(f"wedged: {detail}")

    def _escalate(self, reason: str):
        self._escalate_reason = reason
        self._escalated.set()

    def drain(self, grace_s: Optional[float] = None) -> dict:
        """Connection draining, blocking: stop admission (preflight
        rejects with UNAVAILABLE "draining" — retriable by the existing
        client ladder), let in-flight decodes finish, hand queued work
        back, then the worker exits. Returns a status dict; bounded by
        `grace_s` (default drain_grace_s) — in-flight work still
        running at the deadline is abandoned (futures cancel) so a
        wedged decode cannot hold the drain open forever."""
        grace = self.drain_grace_s if grace_s is None else float(grace_s)
        self._draining = True
        self.worker.begin_drain()
        self.worker.join(timeout=grace)
        clean = not self.worker.is_alive()
        if not clean:
            # grace expired with decodes still in flight: abandon them
            # (the supervisor is about to restart us anyway)
            self.worker.stop(drain=False)
            self.worker.join(timeout=5)
        obs.flight.record("drain_exit", clean=clean,
                          grace_s=round(grace, 3))
        return {"drained": True, "clean": clean}

    def _drainz(self) -> dict:
        """POST /drainz handler (and the wedged drain policy's entry):
        kick a background drain once; report current drain state.
        Idempotent — repeated POSTs watch the same drain."""
        with self._drain_lock:
            if self._drain_thread is None:
                def _run():
                    self.drain()
                    self._escalate("drained")

                obs.flight.record("drainz", source="http_or_policy")
                self._drain_thread = threading.Thread(
                    target=_run, daemon=True, name="lm-drain")
                self._draining = True  # reject admissions immediately
                self._drain_thread.start()
        return {"draining": True,
                "active": self.batcher.n_active,
                "queued": self.worker.q.qsize(),
                "worker_alive": self.worker.is_alive()}

    def _on_worker_death(self, exc, inflight, queued):
        """The batcher worker died mid-step (device fault, injected or
        real). Instead of failing every in-flight request permanently
        (the pre-ISSUE-8 behavior), spawn a successor worker and
        REQUEUE the idempotent survivors: unary requests with retry
        budget left (`attempts` < max_request_retries) and deadline
        remaining. Streaming requests (tokens already delivered) and
        budget-exhausted ones fail fast. Restarts are bounded —
        `worker_restarts` within a 5-minute window — so a hard-broken
        device degrades to the old fail-fast shape instead of a
        requeue loop."""
        now = time.perf_counter()
        with self._restart_lock:
            self._restart_times = [
                t for t in self._restart_times
                if now - t <= self._restart_window_s]
            can_restart = (len(self._restart_times) < self.worker_restarts
                           and not self._draining)
            if can_restart:
                self._restart_times.append(now)
        items = [(rid, it) for rid, it in inflight] \
            + [(None, it) for it in queued]
        fail_exc = RuntimeError(f"LM batcher worker died: {exc}")
        if not can_restart:
            obs.flight.record("worker_restart_exhausted",
                              window_s=self._restart_window_s,
                              budget=self.worker_restarts,
                              failed=len(items))
            for _rid, it in items:
                _fail_future(it.fut, fail_exc)
            if (g := self.goodput) is not None:
                for _ in items:
                    g.on_outcome(False)
            return
        # retire the dead requests' slots host-side: prefill overwrites
        # device state, so the successor serves from a clean pool
        for rid, _it in inflight:
            try:
                if self.batcher.cancel(rid):
                    self.batcher.claim(rid)
            except Exception:  # noqa: BLE001 — slot already retired
                pass
        new_worker = self._spawn_worker()
        if self.goodput is not None:
            new_worker.goodput = self.goodput
        old = self.worker
        new_worker.heartbeat = old.heartbeat
        new_worker.step_done = old.step_done
        self.worker = new_worker
        new_worker.start()
        requeued = failed = 0
        for _rid, it in items:
            ok = (it.on_token is None
                  and (it.cancel_evt is None or not it.cancel_evt.is_set())
                  and it.attempts < self.max_request_retries
                  and now - it.t_q < self.request_timeout)
            if ok:
                ok = new_worker._resubmit(
                    it._replace(attempts=it.attempts + 1))
            if ok:
                requeued += 1
            else:
                failed += 1
                _fail_future(it.fut, fail_exc)
                if (g := self.goodput) is not None:
                    g.on_outcome(False)
        obs.flight.record("worker_restart",
                          restarts=len(self._restart_times),
                          requeued=requeued, failed=failed,
                          error=str(exc)[:300])
        log.warning("batcher worker restarted after death (%s): "
                    "%d requests requeued, %d failed", exc, requeued,
                    failed)

    def _init_rest(self, cfg, prepared, *, default_max_new,
                   request_timeout, tokenizer, draft_cfg, draft_prepared,
                   spec_k, compile_cache_budget, **batcher_kwargs):
        # the daemon's DEFAULT cache layout is the paged pool ("auto"
        # resolves to paged whenever this configuration can page, with a
        # visible dense fallback — serving.ContinuousBatcher kv=): the
        # serving path admits by ACTUAL request length instead of
        # slots x max_len. Callers opt out with kv="dense" (the
        # --kv=dense CLI fallback) or pin kv="paged" to fail loud when
        # paging is impossible.
        batcher_kwargs.setdefault("kv", "auto")
        if (batcher_kwargs.get("allow_constraints")
                and "constraint_rows" not in batcher_kwargs):
            # the daemon's JSON mode goes up to depth _MAX_JSON_DEPTH=3,
            # whose byte DFA has 3519 states — the batcher's device mask
            # pool must hold it (serving.ContinuousBatcher constraint_
            # rows; bool bytes = rows x vocab, ~181 MB at GPT-2 vocab).
            # Operators who never serve deep JSON can pass a smaller
            # constraint_rows explicitly.
            batcher_kwargs["constraint_rows"] = 3600
        if draft_cfg is not None:
            # speculative serving: the slot pool advances up to spec_k+1
            # tokens per device step (runtime/serving_spec.py)
            from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

            self.batcher = SpeculativeBatcher(
                cfg, prepared, draft_cfg, draft_prepared, spec_k=spec_k,
                **batcher_kwargs)
        else:
            self.batcher = ContinuousBatcher(cfg, prepared,
                                             **batcher_kwargs)
        self.default_max_new = default_max_new
        self.request_timeout = request_timeout
        # optional text front (dnn_tpu/io/tokenizer.py): with it,
        # SendMessage serves prompt text -> generated text
        self.tokenizer = tokenizer
        # JSON-mode constraints are per-(depth) compile-once artifacts —
        # the token table is vocab-sized work shared by every request
        self._constraint_cache: dict = {}
        # embedding endpoint: one make_embed per pooling (jit caches per
        # padded-length shape underneath)
        self._embed_fns: dict = {}
        # embed calls run device work OUTSIDE the worker thread
        # (asyncio.to_thread) — the cache guard must not clear while one
        # is in flight, and must never iterate _embed_fns mid-insert.
        # The inflight transitions happen under guard.lock (the guard's
        # check+clear is atomic under it), closing the race where an
        # embed enters its program between the check and the clear.
        self._embed_inflight = 0
        self._compile_cache_budget = compile_cache_budget
        self.worker = self._spawn_worker()
        self.worker.start()

    def _spawn_worker(self) -> _BatcherWorker:
        """Build a batcher worker wired to this server — used at
        construction AND by the worker-death restart path, so a
        successor worker can never drift behind the original's hooks
        (cache-guard registrations, requeue hook)."""
        worker = _BatcherWorker(
            self.batcher,
            compile_cache_budget=self._compile_cache_budget)
        # lazily-created program families count toward the compile budget
        # (snapshot copy: the guard runs on the worker thread)
        worker.cache_guard.register(
            lambda: list(self._embed_fns.values()))
        worker.cache_guard.add_busy_check(
            lambda: self._embed_inflight > 0)
        if self.worker_restarts > 0:
            worker.on_death = self._on_worker_death
        return worker

    _MAX_JSON_DEPTH = 3  # regex expansion grows with depth; bound it

    def json_constraint(self, depth: int):
        """Compile-once TokenConstraint for a depth-bounded JSON value
        (the gen option ':j=DEPTH'). Returns None when the server's
        tokenizer exposes no token->bytes map (constraints need one).
        Raises ValueError for an out-of-range depth."""
        depth = int(depth)
        if not 0 <= depth <= self._MAX_JSON_DEPTH:
            raise ValueError(
                f"json depth must be in [0, {self._MAX_JSON_DEPTH}], "
                f"got {depth}")
        vb = getattr(self.tokenizer, "vocab_bytes", None)
        if vb is None:
            return None
        c = self._constraint_cache.get(depth)
        if c is None:
            from dnn_tpu.runtime.constrain import TokenConstraint, json_regex

            # compile over the MODEL's vocab size: padded embedding
            # tables (model vocab > tokenizer vocab) must still match
            # the batcher's vocab check, with padding ids banned.
            # Tolerate zero-arg vocab_bytes() adapters (the protocol
            # predates the size parameter) by padding/trimming here.
            model_v = self.batcher.cfg.vocab_size
            try:
                vocab = vb(model_v)
            except TypeError:
                vocab = list(vb())
            if len(vocab) < model_v:
                vocab = list(vocab) + [b""] * (model_v - len(vocab))
            elif len(vocab) > model_v:
                vocab = list(vocab)[:model_v]
            c = TokenConstraint.from_regex(json_regex(depth), vocab)
            self._constraint_cache[depth] = c
        return c

    def _request_span(self, request_id: str, **attrs):
        """Root span for one served request: a client that tagged its
        request_id (obs.tag_request_id — the `tr=` segment rides the
        existing wire field) gets its trace CONTINUED across the process
        boundary; untagged requests start fresh. NULL_SPAN when off."""
        return obs.continue_or_start("lm.request", request_id, **attrs)

    # --- RPC implementations (names/signatures fixed by the protocol) ---

    async def _preflight(self, request_id: str, context):
        """Shared request preflight for both RPC fronts: drain gate,
        worker liveness, option parsing — one place, one status
        mapping. A draining server rejects with UNAVAILABLE — the
        retriable status the edge client's ladder honors — so admission
        stops without losing anything."""
        if self._draining:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "draining: admission closed; retry against another "
                "replica")
        if not self.worker.is_alive():
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "LM batcher worker is not running (died or shut down)")
        max_new, seed, opts = parse_gen_options(request_id,
                                                self.default_max_new)
        if "json_depth" in opts:
            try:
                # first use per depth compiles an (S, V) token table —
                # vocab-sized host work that must not block the event
                # loop (every concurrent RPC stalls behind _preflight)
                c = await asyncio.to_thread(self.json_constraint,
                                            opts.pop("json_depth"))
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    str(e))
            if c is None:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "JSON mode (j=) needs a server tokenizer with a "
                    "token->bytes map (io/tokenizer.ByteTokenizer)")
            opts["constraint"] = c
        return max_new, seed, opts

    async def _result_or_abort(self, fut, context):
        """Map a COMPLETED worker future to the shared status ladder
        (both fronts route every terminal outcome through here, so a
        streaming caller and a unary caller always see the same gRPC code
        for the same server condition): cancelled -> UNAVAILABLE
        (server-side abandon), ValueError -> INVALID_ARGUMENT (caller
        error), other exceptions -> UNAVAILABLE (worker death/shutdown).
        Returns the result on success."""
        if fut.cancelled():
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                "LM server shut down")
        exc = fut.exception()
        if isinstance(exc, ValueError):
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        if exc is not None:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
        return fut.result()

    async def _submit_and_await(self, ids, request_id: str, context,
                                root=None):
        """Unary submit/await: preflight, wait with the request deadline
        (-> DEADLINE_EXCEEDED), client RPC cancellation re-raised for
        grpc.aio, all terminal outcomes mapped by _result_or_abort.
        `root` — an already-created request span whose ending the CALLER
        owns (SendMessage appends a detokenize child after the tokens
        come back); None creates and ends one here."""
        own_root = root is None
        if own_root:
            root = self._request_span(request_id, method="SendTensor")
        fut = None
        try:
            max_new, seed, opts = await self._preflight(request_id,
                                                        context)
            await self._resolve_kv_handle(opts, context)
            # propagated deadline (dl= segment, comm/transport.py): the
            # caller's REMAINING budget caps the server-side wait, so a
            # nearly-dead request can't hold a slot for the full local
            # request_timeout after its client already gave up
            inbound_dl = _tx.extract_deadline(request_id)
            timeout_s = self.request_timeout if inbound_dl is None \
                else max(min(self.request_timeout, inbound_dl), 0.001)
            dkey = opts.pop("dedup", None)
            root.set(max_new=max_new,
                     prompt_len=int(np.asarray(ids).size))
            # cancel_evt: a deadline abort must also retire the slot at
            # the next step boundary — without it the pool decodes on to
            # the abandoned request's full token budget
            cancel_evt = threading.Event()
            if dkey is not None:
                # exactly-once admission: a retried dedup key JOINS the
                # original request's future instead of generating twice
                # (failed/cancelled entries are replaced — retrying
                # after a real failure is the point of retrying)
                with self._dedup_lock:
                    cached = self._dedup.get(dkey)
                    if cached is not None and not cached.cancelled() \
                            and not (cached.done()
                                     and cached.exception() is not None):
                        fut = cached
            joined = fut is not None
            if joined:
                obs.flight.record(
                    "dedup_join", key=str(dkey)[:80],
                    trace_id=root.trace_id if root else None)
                root.set(dedup="join")
            else:
                fut = self.worker.submit(
                    np.asarray(ids, np.int32).reshape(-1), max_new, seed,
                    opts=opts, trace=root, cancel_evt=cancel_evt)
                if dkey is not None:
                    with self._dedup_lock:
                        self._dedup[dkey] = fut
                        while len(self._dedup) > self._DEDUP_CAP:
                            self._dedup.pop(next(iter(self._dedup)))
            try:
                # a JOINED wait is shielded: this caller timing out must
                # abandon only its own wait, never cancel the original
                # submitter's future out from under it
                wrapped = asyncio.wrap_future(fut)
                await asyncio.wait_for(
                    asyncio.shield(wrapped) if joined else wrapped,
                    timeout=timeout_s)
            except asyncio.TimeoutError:
                cancel_evt.set()
                m = obs.metrics()
                if m is not None:
                    m.inc("serving.deadline_exceeded_total")
                # availability SLO: no direct feed here — the eviction
                # retires through batcher.cancel -> _obs_retire
                # ("cancelled"), which counts it against the budget once
                # the post-mortem record: the dump (/debugz) carries this
                # event plus whatever surrounded it (admissions, compiles,
                # watchdog state flips) — the window a stall hides in
                obs.flight.record(
                    "deadline_miss", method="SendTensor",
                    timeout_s=timeout_s,
                    trace_id=root.trace_id if root else None)
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"generation exceeded {timeout_s}s")
            except asyncio.CancelledError:
                if not fut.cancelled():
                    raise  # client cancelled the RPC: grpc.aio handles it
            except Exception:  # noqa: BLE001 — the future itself holds
                pass           # the outcome; _result_or_abort maps it
            return await self._result_or_abort(fut, context)
        finally:
            # end-of-span in ALL outcomes — a preflight abort's trace
            # (the failed request an operator most wants to see) must
            # still reach the collector, which stores ended spans only
            if own_root:
                done = fut is not None and fut.done() \
                    and not fut.cancelled() and fut.exception() is None
                root.end(tokens=len(fut.result()) if done else None)

    async def _validated_prompt(self, request: pb.TensorRequest, context):
        """Decode + validate the raw-id prompt (shared by the unary and
        streaming fronts): integrity, integer dtype, vocab range — JAX's
        clip-mode gather would otherwise silently substitute edge-of-table
        embeddings and generate plausible output from a corrupt prompt."""
        try:
            prompt = _tensor_arr(request.tensor)
        except PayloadCorruptError as e:
            await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
        if not np.issubdtype(prompt.dtype, np.integer):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"prompt must be integer token ids, got dtype {prompt.dtype}")
        vocab = self.batcher.cfg.vocab_size
        if prompt.size and (prompt.min() < 0 or prompt.max() >= vocab):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"prompt token ids must be in [0, {vocab}), got range "
                f"[{prompt.min()}, {prompt.max()}]")
        return prompt

    def _embed_prompt(self, prompt: np.ndarray, pooling: str) -> np.ndarray:
        """Pooled hidden-state embedding of one prompt
        (runtime/embeddings.make_embed). Prompts pad up to a prompt_pad
        multiple — pad content is free under causal attention, so ONE
        jitted program per (pooling, padded length) serves every request
        of that bucket. Runs concurrently with the decode worker (JAX
        serializes device execution); called via asyncio.to_thread so
        the event loop never blocks on device time."""
        cfg = self.batcher.cfg
        if (getattr(self.batcher.family, "ffn", None) is not None
                and getattr(cfg, "default_ffn", lambda **_: None)()
                is None):
            # the extractor resolves CONFIG-carried MLP overrides
            # (Mixtral's default_ffn) itself; an ffn set only on the
            # family adapter (the GPT-MoE daemon) has no hook in the
            # extractor's block forward — reject cleanly instead of
            # KeyError-ing inside the trace
            raise ValueError(
                "the embedding endpoint does not support ffn-overridden "
                "families whose config carries no default_ffn (the "
                "GPT-MoE daemon)")
        t = int(prompt.size)
        if t < 1:
            raise ValueError("embedding needs at least one token")
        if t > cfg.block_size:
            raise ValueError(
                f"prompt length {t} > block_size {cfg.block_size}")
        fn = self._embed_fns.get(pooling)
        if fn is None:
            from dnn_tpu.runtime.embeddings import make_embed

            fn = make_embed(cfg, pooling=pooling,
                            compute_dtype=self.batcher.family.compute_dtype)
            self._embed_fns[pooling] = fn
        p_pad = self.batcher.prompt_pad
        padded_len = min(-(-t // p_pad) * p_pad, cfg.block_size)
        ids = np.zeros((1, max(padded_len, t)), np.int32)
        ids[0, :t] = prompt.reshape(-1)
        # in-flight marker: the worker's cache guard must not
        # jax.clear_caches() while this thread is inside the program —
        # transitions under guard.lock make the guard's check+clear
        # atomic against them (utils/xla_cache.py)
        guard = self.worker.cache_guard
        with guard.lock:
            self._embed_inflight += 1
        try:
            out = fn(self.batcher.prepared, ids,
                     np.asarray([t], np.int32))
            return np.asarray(out[0], np.float32)
        finally:
            with guard.lock:
                self._embed_inflight -= 1

    # -- disaggregated prefill/decode (dnn_tpu/control) -----------------

    def _prefill_export(self, prompt: np.ndarray) -> np.ndarray:
        """Run the chunk loop only (no slot, no sampling) and pack the
        handoff payload. Same off-worker device-work discipline as the
        embed endpoint: the _embed_inflight counter (really "aux device
        work in flight") fences the worker's cache guard so a clear
        can never land mid-program."""
        from dnn_tpu.control import handoff as _handoff

        guard = self.worker.cache_guard
        with guard.lock:
            self._embed_inflight += 1
        try:
            return np.asarray(
                _handoff.pack(self.batcher.export_prefill(prompt)))
        finally:
            with guard.lock:
                self._embed_inflight -= 1

    async def _kvput(self, key: str, request: pb.TensorRequest,
                     context) -> pb.TensorResponse:
        """Ingest a prefill replica's packed KV payload under `key`.
        Unpacked and geometry-checked NOW — a mismatched handoff fails
        at ingest with a readable diff, not at admission; handles are
        single-use (the h= gen option consumes them) and the inbox is
        a bounded LRU."""
        key = key.strip()
        if not key:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "kvput needs a nonempty handle key (kvput:<key>)")
        if getattr(self.batcher, "spec_k", None) is not None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "speculative servers cannot adopt handed-off KV (the "
                "draft cache needs its own prompt prefill)")
        if getattr(self.batcher, "_ilv", 0):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "interleaved-admission servers (prefill_chunk_tokens) "
                "cannot adopt handed-off KV — adoption rides the "
                "convoy install path")
        try:
            raw = _tensor_arr(request.tensor)
        except PayloadCorruptError as e:
            await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
        from dnn_tpu.control import handoff as _handoff

        try:
            # full-payload byte parse: host-only, but row-cache-sized —
            # off the event loop like every other non-trivial handler leg
            payload = await asyncio.to_thread(_handoff.unpack, raw)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        mine = self.batcher.handoff_fingerprint()
        theirs = payload.get("fingerprint") or {}
        if theirs and theirs != mine:
            diff = {k: (theirs.get(k), mine.get(k))
                    for k in set(theirs) | set(mine)
                    if theirs.get(k) != mine.get(k)}
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"handoff geometry mismatch (theirs, mine): {diff} — "
                "prefill and decode replicas must share model config, "
                "max_len, prompt_pad and kv dtype")
        self._sweep_kv_handoffs()
        with self._kv_lock:
            self._kv_handoff[key] = (payload, time.monotonic())
            while len(self._kv_handoff) > self._kv_handoff_cap:
                self._kv_handoff.pop(next(iter(self._kv_handoff)))
        obs.flight.record("kv_staged", key=key[:80],
                          prompt_len=payload["prompt_len"])
        return wc.TensorResponse(
            status=f"[lm] ok: kv handle {key!r} staged "
                   f"({payload['prompt_len']} prompt positions)")

    def _sweep_kv_handoffs(self, now: Optional[float] = None):
        """TTL sweep over the kvput inbox: staged handoffs are single-
        use and were previously unbounded-LIFETIME until collected — an
        abandoned prefill (router death between kvput and generate)
        pinned its row-sized payload until cap pressure pushed it out.
        Swept from the worker's housekeeping tick AND on every ingest;
        each expiry is a `kvput_expired` flight event."""
        ttl = self._kv_handoff_ttl_s
        if ttl <= 0:
            return
        now = time.monotonic() if now is None else now
        expired = []
        with self._kv_lock:
            for k in list(self._kv_handoff):
                payload, t0 = self._kv_handoff[k]
                if now - t0 > ttl:
                    expired.append((k, payload.get("prompt_len")))
                    del self._kv_handoff[k]
        if expired:
            m = obs.metrics()
            for k, plen in expired:
                if m is not None:
                    m.inc("serving.kvput_expired_total")
                obs.flight.record("kvput_expired", key=str(k)[:80],
                                  prompt_len=plen, ttl_s=ttl,
                                  cause="kvput_ttl")

    def _housekeeping_tick(self):
        """Worker-loop housekeeping (rate-limited to ~1 Hz so the hot
        loop pays one float compare): kvput inbox TTL + kvtier lease
        TTL sweeps."""
        now = time.monotonic()
        if now - self._hk_last < 1.0:
            return
        self._hk_last = now
        self._sweep_kv_handoffs(now)
        if self._kvtier_leases is not None:
            self._kvtier_leases.sweep()

    # -- fleet KV tier endpoints (dnn_tpu/kvtier) -----------------------

    def _kvtier_on(self) -> bool:
        return getattr(self.batcher, "_prefix_store", None) is not None

    async def _kvtier_require(self, context):
        if not self._kvtier_on():
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "the KV tier is off on this replica: serve with "
                "kv=paged (or paged_blocks>0) and prefix_cache>0")

    async def _kvtier_stage(self, request, context):
        """kvstage: prefill these tokens' full blocks straight into
        the radix store (no slot, no sampling) — the prefill-replica
        half of disaggregated block migration."""
        await self._kvtier_require(context)
        prompt = await self._validated_prompt(request, context)
        fut = self.worker.submit_control(
            lambda: self.batcher.stage_prefix(np.asarray(prompt)))
        try:
            stats = await asyncio.wrap_future(fut)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Exception as e:  # noqa: BLE001 — InsufficientBlocks etc:
            # transient, the caller treats staging as advisory
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                f"{type(e).__name__}: {e}")
        return wc.TensorResponse(
            status="[lm] ok: kvstage " + json.dumps(stats))

    async def _kvtier_lease(self, request, context):
        """kvlease: export the longest resident block run for these
        tokens, stage it under a TTL'd lease (kvtier/migrate.py), and
        answer the offer meta — lease id, byte count, and the shm
        segment + nonce when this host can publish one. The adopter
        pulls via shm attach or kvfetch and acks via kvack."""
        await self._kvtier_require(context)
        prompt = await self._validated_prompt(request, context)
        fut = self.worker.submit_control(
            lambda: self.batcher.kvtier_export(np.asarray(prompt)))
        try:
            payload = await asyncio.wrap_future(fut)
        except Exception as e:  # noqa: BLE001 — export failures are the
            # donor's problem, reported readable
            await context.abort(grpc.StatusCode.INTERNAL,
                                f"{type(e).__name__}: {e}")
        if payload is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                "no resident prefix blocks for these tokens")
        from dnn_tpu.kvtier import migrate as _mig

        # host-side pack is row-cache-sized — off the event loop
        wire = await asyncio.to_thread(_mig.pack_blocks, payload)
        meta = self._kvtier_leases.offer(wire.tobytes())
        meta["n_tokens"] = int(np.asarray(payload["tokens"]).size)
        meta["blocks"] = int(
            np.asarray(payload["tokens"]).size // payload["block_len"])
        return wc.TensorResponse(
            status=f"[lm] ok: lease {meta['lease']} offered "
                   f"({meta['bytes']} bytes)",
            result_tensor=_tensor_msg(np.frombuffer(
                json.dumps(meta).encode(), np.uint8)))

    async def _kvtier_fetch(self, lease_id: str, context):
        """kvfetch:<lease>: the grpc rung — staged bytes back to the
        adopter. An expired/unknown lease is NOT_FOUND: the adopter
        records kvtier_fallback and re-prefills."""
        await self._kvtier_require(context)
        try:
            data = self._kvtier_leases.fetch(lease_id)
        except KeyError:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"unknown or expired kvtier lease {lease_id!r}")
        return wc.TensorResponse(
            status=f"[lm] ok: lease {lease_id} ({len(data)} bytes)",
            result_tensor=_tensor_msg(np.frombuffer(data, np.uint8)))

    async def _kvtier_ack(self, lease_id: str, context):
        await self._kvtier_require(context)
        ok = self._kvtier_leases.ack(lease_id)
        return wc.TensorResponse(
            status=f"[lm] ok: lease {lease_id} "
                   + ("released" if ok else "already gone"))

    async def _kvtier_pull(self, request, context):
        """kvpull: {donor, tokens} — pull the prefix's blocks FROM the
        donor replica and adopt them locally. ADVISORY by design: any
        failure (donor dead, lease expired, geometry mismatch, pool
        full) answers a `kvtier_fallback` status instead of an error —
        the follow-up generate simply re-prefills, loud in the flight
        ring, never wrong."""
        await self._kvtier_require(context)
        try:
            raw = _tensor_arr(request.tensor)
            spec = json.loads(np.asarray(raw, np.uint8).tobytes())
            donor = str(spec["donor"])
            tokens = np.asarray(spec["tokens"], np.int32).reshape(-1)
        except (PayloadCorruptError, ValueError, KeyError, TypeError):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                'kvpull expects a uint8 JSON tensor '
                '{"donor": "host:port", "tokens": [...]}')

        def _pull():
            from dnn_tpu.comm.client import NodeClient
            from dnn_tpu.kvtier import migrate as _mig

            cl = NodeClient(donor, transport="grpc", breaker=False)
            try:
                return _mig.pull_blocks(cl, tokens,
                                        timeout=self._kv_lease_ttl_s)
            finally:
                cl.close()

        m = obs.metrics()
        try:
            _chaos_inject.kv_migrate()  # donor-death-mid-migration seam
            payload = await asyncio.to_thread(_pull)
            fut = self.worker.submit_control(
                lambda: self.batcher.kvtier_adopt(payload))
            n = await asyncio.wrap_future(fut)
        except Exception as e:  # noqa: BLE001 — the whole point: a
            # dying donor (or an expired lease) must never fail the
            # request, only the OPTIMIZATION — loud, then re-prefill
            if m is not None:
                m.inc("dnn_tpu_kvtier_fallback_total")
            obs.flight.record("kvtier_fallback", donor=donor,
                              error=f"{type(e).__name__}: {e}"[:200])
            return wc.TensorResponse(
                status="[lm] kvtier_fallback: "
                       f"{type(e).__name__}: {e}"[:240])
        nbytes = int(payload.get("_wire_bytes", 0))
        if m is not None and n:
            m.inc("dnn_tpu_kvtier_migrated_blocks_total", n)
            if nbytes:
                m.inc("dnn_tpu_kvtier_migrated_bytes_total", nbytes)
        obs.flight.record("kvtier_adopted", donor=donor, blocks=n,
                          bytes=nbytes)
        return wc.TensorResponse(
            status=f"[lm] ok: kvpull adopted {n} blocks "
                   f"({nbytes} bytes) from {donor}")

    async def _resolve_kv_handle(self, opts: dict, context):
        """Swap a parsed h=<key> option for its staged payload
        (single-use). Unknown handle = INVALID_ARGUMENT — generating
        WITHOUT the adopted KV would silently re-prefill, hiding a
        broken handoff path."""
        h = opts.pop("kv_handle", None)
        if h is None:
            return
        with self._kv_lock:
            entry = self._kv_handoff.pop(h, None)
        if entry is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"unknown or already-consumed kv handle {h!r} "
                "(kvput: it first; handles are single-use — an expired "
                "handle was TTL-swept, re-stage it)")
        opts["prefilled"] = entry[0]

    async def SendTensor(self, request: pb.TensorRequest, context) -> pb.TensorResponse:
        rid = request.request_id or ""
        # client-side transport metadata may ride any request_id — the
        # trace tag (tr=...) and the propagated deadline (dl=...); both
        # are stripped before endpoint parse (the deadline is honored
        # inside _submit_and_await, which reads the RAW rid)
        rid_clean = _tx.strip_deadline(obs.strip_wire_tag(rid))
        if rid_clean.startswith("kvput:"):
            # KV-handoff ingest (disaggregated serving): the tensor is
            # a packed export_prefill payload, NOT token ids — decoded
            # raw, before the vocab-range prompt validation below
            return await self._kvput(rid_clean.split(":", 1)[1],
                                     request, context)
        # fleet KV tier (dnn_tpu/kvtier): block-granular stage / lease /
        # fetch / ack / pull — kvpull and kvfetch/kvack carry non-token
        # tensors, so they too dispatch before prompt validation
        if rid_clean == "kvstage":
            return await self._kvtier_stage(request, context)
        if rid_clean == "kvlease":
            return await self._kvtier_lease(request, context)
        if rid_clean.startswith("kvfetch:"):
            return await self._kvtier_fetch(
                rid_clean.split(":", 1)[1], context)
        if rid_clean.startswith("kvack:"):
            return await self._kvtier_ack(
                rid_clean.split(":", 1)[1], context)
        if rid_clean == "kvpull":
            return await self._kvtier_pull(request, context)
        prompt = await self._validated_prompt(request, context)
        if rid_clean == "embed" or rid_clean.startswith("embed:"):
            # embedding endpoint: 'embed[:mean|last]' returns the pooled
            # final hidden state instead of generated tokens
            pooling = rid_clean.split(":", 1)[1] if ":" in rid_clean \
                else "mean"
            if pooling not in ("mean", "last"):
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"embed pooling must be mean|last, got {pooling!r}")
            root = self._request_span(rid, method="embed", pooling=pooling)
            try:
                vec = await asyncio.to_thread(
                    self._embed_prompt, np.asarray(prompt), pooling)
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    str(e))
            finally:
                root.end()
            return wc.TensorResponse(
                status=f"[lm] ok: embedding dim {vec.shape[-1]}",
                result_tensor=_tensor_msg(vec),
            )
        if rid_clean == "prefill":
            # prefill-export endpoint (disaggregated serving): run ONLY
            # the chunk loop for this prompt and answer with the packed
            # KV payload — the router (or any client) hands it to a
            # decode replica via kvput: + h=. Device work off-loop,
            # cache-guard-fenced, exactly like the embed endpoint.
            root = self._request_span(rid, method="prefill")
            try:
                payload = await asyncio.to_thread(
                    self._prefill_export, np.asarray(prompt))
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    str(e))
            finally:
                root.end()
            return wc.TensorResponse(
                status=f"[lm] ok: prefill kv {payload.size} bytes",
                result_tensor=_tensor_msg(payload),
            )
        tokens = await self._submit_and_await(prompt, rid, context)
        return wc.TensorResponse(
            status=f"[lm] ok: {len(tokens)} tokens",
            result_tensor=_tensor_msg(np.asarray(tokens, np.int32)),
        )

    async def GenerateStream(self, request: pb.TensorRequest, context):
        """Server-streaming generate: one TensorResponse PER TOKEN as it
        commits (result_tensor = [token]); stream end = generation done.
        Client cancellation (disconnect / stream.cancel) sets the request's
        cancel event, and the batcher worker retires the slot at the next
        step boundary — a dropped stream never decodes on to its budget.
        The unary SendTensor front stays untouched for reference
        wire-compat (wire.proto)."""
        prompt = await self._validated_prompt(request, context)
        root = self._request_span(request.request_id,
                                  method="GenerateStream")
        n = 0
        cancel_evt = None
        try:
            max_new, seed, opts = await self._preflight(
                request.request_id, context)
            # streaming requests cannot dedup-join (tokens already
            # stream to one consumer) — drop the key rather than let it
            # reach batcher.submit as an unknown kwarg
            opts.pop("dedup", None)
            # ...but they CAN adopt handed-off KV: resolve h= the same
            # way the unary front does
            await self._resolve_kv_handle(opts, context)
            root.set(max_new=max_new, prompt_len=int(prompt.size))
            loop = asyncio.get_running_loop()
            q: "asyncio.Queue" = asyncio.Queue()
            cancel_evt = threading.Event()

            def on_token(tok):
                loop.call_soon_threadsafe(q.put_nowait, ("tok", tok))

            fut = self.worker.submit(
                np.asarray(prompt, np.int32).reshape(-1), max_new, seed,
                opts=opts, on_token=on_token, cancel_evt=cancel_evt,
                trace=root)

            def _done(f):
                # fires in the worker thread AFTER any on_token calls for
                # this request: call_soon_threadsafe preserves that order,
                # so the "done" sentinel always trails the last token in
                # the queue
                loop.call_soon_threadsafe(q.put_nowait, ("done", f))

            fut.add_done_callback(_done)
            inbound_dl = _tx.extract_deadline(request.request_id)
            timeout_s = self.request_timeout if inbound_dl is None \
                else max(min(self.request_timeout, inbound_dl), 0.001)
            deadline = loop.time() + timeout_s
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    cancel_evt.set()
                    m = obs.metrics()
                    if m is not None:
                        m.inc("serving.deadline_exceeded_total")
                    obs.flight.record(
                        "deadline_miss", method="GenerateStream",
                        timeout_s=timeout_s, tokens=n,
                        trace_id=root.trace_id if root else None)
                    await context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        f"generation exceeded {timeout_s}s")
                try:
                    kind, val = await asyncio.wait_for(q.get(), remaining)
                except asyncio.TimeoutError:
                    continue  # loop re-checks the deadline and aborts
                if kind == "tok":
                    n += 1
                    yield wc.TensorResponse(
                        status=f"[lm] token {n}",
                        result_tensor=_tensor_msg(
                            np.asarray([val], np.int32)),
                    )
                    continue
                await self._result_or_abort(val, context)
                return
        except asyncio.CancelledError:
            # the client went away: free the slot at the next step
            # boundary (None: cancelled during preflight, nothing queued)
            if cancel_evt is not None:
                cancel_evt.set()
            raise
        finally:
            root.end(tokens=n)

    async def HealthCheck(self, request: pb.Empty, context) -> pb.HealthCheckResponse:
        # a DRAINING server reports unhealthy so load balancers and
        # wait_healthy pollers stop routing to it while it finishes
        return pb.HealthCheckResponse(
            is_healthy=self.worker.is_alive() and not self._draining)

    async def SendMessage(self, request: pb.MessageRequest, context) -> pb.MessageReply:
        """Text endpoint. "!stats" (or any text without a tokenizer)
        answers with pool stats; with a tokenizer, the message text is a
        PROMPT and the reply is the generated continuation — the job the
        reference defined this RPC for but never gave it (node.py:111-113,
        no caller). Options ride the sender_id as "gen[:max_new[:seed]]".
        Transport negotiation hellos (comm/transport.py) are declined
        FIRST — prompt payloads are bytes-tiny, so the LM daemon keeps
        the grpc rung, and a hello must never reach the tokenizer as a
        "prompt"."""
        if request.sender_id.startswith(_tx.HELLO_SENDER):
            return pb.MessageReply(
                confirmation_text=_tx.decline_hello(
                    "LM daemon serves grpc only"))
        b = self.batcher
        text = request.message_text
        if self.tokenizer is None or text == "!stats":
            prefix = ""
            if b._prefix_cache is not None:
                prefix = (f", prefix cache: {b.prefix_hits} hits / "
                          f"{b.prefill_chunks_run} chunks run / "
                          f"{len(b._prefix_cache)} entries")
            elif getattr(b, "_prefix_store", None) is not None:
                s = b._prefix_store
                prefix = (f", kvtier: {b.prefix_hits} hits / "
                          f"{s.block_hits} block hits "
                          f"({s.remote_block_hits} remote) / "
                          f"{s.n_blocks} resident blocks")
            return pb.MessageReply(
                confirmation_text=(
                    f"[lm] pool: {b.n_active}/{b.slots} slots active, "
                    f"{len(b.results)} unclaimed results" + prefix))
        ids = self.tokenizer.encode(text)
        if not ids:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "prompt text tokenized to nothing")
        root = self._request_span(request.sender_id, method="SendMessage")
        try:
            tokens = await self._submit_and_await(
                ids, request.sender_id, context, root=root)
            with root.child("detokenize"):  # host-side text assembly
                reply = self.tokenizer.decode([int(t) for t in tokens])
        finally:
            root.end()
        return pb.MessageReply(confirmation_text=reply)

    def close(self):
        self.worker.stop(drain=False)
        self.worker.join(timeout=10)
        if self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None


async def serve_lm(cfg, prepared, *, port: int, **server_kwargs) -> int:
    """Start the LM daemon and block until termination — the LM analog of
    comm.service.serve_stage (reference serve(), node.py:114-133).

    Resilience exits (ISSUE 8): SIGTERM triggers CONNECTION DRAINING —
    admission closes (UNAVAILABLE "draining", retriable), in-flight
    decodes finish within the drain grace, queued work hands back —
    then the server exits cleanly (rc 0). A watchdog wedged-policy
    escalation (`on_wedged=restart|drain`) exits with EXIT_RESTART (43)
    so a supervisor (node --supervise / chaos.supervisor) relaunches
    the process, restoring from the latest good checkpoint."""
    import signal

    servicer = LMServer(cfg, prepared, **server_kwargs)
    server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
    server.add_generic_rpc_handlers((_handlers(servicer),))
    listen = f"[::]:{port}"
    if server.add_insecure_port(listen) == 0:
        raise RuntimeError(f"failed to bind gRPC server to {listen}")
    log.info("gRPC LM server listening on %s (%d slots)", listen,
             servicer.batcher.slots)
    await server.start()
    loop = asyncio.get_running_loop()
    sigterm_drained = False

    def _on_sigterm():
        nonlocal sigterm_drained
        sigterm_drained = True
        obs.flight.record("sigterm_drain")
        log.info("SIGTERM: draining (admission closed, finishing "
                 "in-flight decodes)")
        servicer._drainz()  # background drain -> sets the escalation

    try:
        loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
    except (NotImplementedError, ValueError, RuntimeError):
        pass  # non-main thread / platform without signal support
    async def _wait_escalated():
        # bounded waits so cancellation never strands a thread parked
        # in Event.wait() forever at shutdown
        while not await asyncio.to_thread(servicer._escalated.wait, 1.0):
            pass

    # loop-lag sanitizer (analysis/sanitize.py): env-gated tripwire for
    # event-loop-blocking calls the AST pass can't see — verify paths
    # run with DNN_TPU_LOOP_SANITIZE=1 and read breaches off /debugz
    from dnn_tpu.analysis import sanitize as _sanitize

    lagmon = _sanitize.maybe_install(where="serve_lm")
    esc_task = asyncio.ensure_future(_wait_escalated())
    term_task = asyncio.ensure_future(server.wait_for_termination())
    try:
        await asyncio.wait({esc_task, term_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if servicer._escalated.is_set():
            reason = servicer._escalate_reason or "escalated"
            log.warning("serve_lm exiting on escalation: %s", reason)
            if servicer.on_wedged == "restart" and not sigterm_drained \
                    and not reason.startswith("drained"):
                # restart policy: no drain — the device is wedged and
                # in-flight work cannot finish; the supervisor restarts
                # us from the latest checkpoint
                return EXIT_RESTART
            return 0 if sigterm_drained else EXIT_RESTART
        return 0
    finally:
        # teardown ORDER matters: stop the server FIRST (which lets
        # wait_for_termination complete on its own), THEN reap the
        # watcher tasks — cancelling wait_for_termination while stop()
        # runs makes grpc.aio surface CancelledError out of this
        # finally, clobbering the escalation return code (the verify
        # scenario caught exactly that as rc=1 instead of 43/0)
        if lagmon is not None:
            lagmon.stop()
        esc_task.cancel()
        try:
            await server.stop(grace=1)
        except asyncio.CancelledError:
            pass
        for t in (esc_task, term_task):
            if not t.done():
                t.cancel()
            try:
                await t
            except BaseException:  # noqa: BLE001 — reaped, not consulted
                pass
        servicer.close()


def start_lm_server_in_background(cfg, prepared, *, port: int, **server_kwargs):
    """Test/embedding helper: serve_lm on a daemon thread; returns
    (thread, stop_callback) — mirrors
    comm.service.start_stage_server_in_background."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    async def _run():
        try:
            servicer = LMServer(cfg, prepared, **server_kwargs)
            server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
            server.add_generic_rpc_handlers((_handlers(servicer),))
            if server.add_insecure_port(f"[::]:{port}") == 0:
                servicer.close()
                raise RuntimeError(f"failed to bind gRPC server to [::]:{port}")
            await server.start()
            state["servicer"], state["server"] = servicer, server
            state["done"] = asyncio.Event()
        except BaseException as e:
            state["error"] = e
            raise
        finally:
            started.set()
        await state["done"].wait()
        await asyncio.sleep(0.05)

    def _thread_main():
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(_run())
        except BaseException:
            if "error" not in state:
                raise
            # startup error already recorded and re-raised to the caller

    t = threading.Thread(target=_thread_main, daemon=True)
    t.start()
    if not started.wait(timeout=30):
        raise RuntimeError("LM server failed to start")
    if "error" in state:
        t.join(timeout=5)
        raise RuntimeError(f"LM server failed to start: {state['error']}") \
            from state["error"]

    def stop():
        async def _stop():
            await state["server"].stop(grace=0.2)
            state["done"].set()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=10)
        state["servicer"].close()
        t.join(timeout=5)

    # expose the servicer (tests read e.g. the ephemeral metrics_port=0
    # endpoint via stop.servicer.metrics_server.port)
    stop.servicer = state["servicer"]
    return t, stop
