"""The inference engine: config -> mesh -> staged model -> results.

Rebuilds the reference's per-node runtime (node.py:210-364) as a single
SPMD controller: where the reference starts N OS processes that each parse
the config, load the full checkpoint, keep their slice, and relay tensors
over gRPC (SURVEY §3.1-3.3), this engine parses the same config once, maps
`part_index` onto the mesh "stage" axis, loads + slices the checkpoint per
stage, and runs the whole pipeline as compiled programs with ppermute hops.

Everything is compiled once: per-stage jits and the pipeline callable are
built in __init__ and reused (jit itself handles new input shapes), unlike
the reference which pays torch dispatch per request.

Roles:
  role="full"  — this process drives the whole pipeline (default).
  role="stage" — this process serves exactly one stage behind the gRPC
                 edge (the reference's per-node deployment); no mesh or
                 full-pipeline runtime is built, so an 8-stage config can
                 be served from 1-device hosts.

Runtime selection for role="full" (config key `runtime`, SURVEY §7.4):
  "relay" — device-per-stage sequential relay (reference semantics;
            heterogeneous-friendly; also the 1-device fallback)
  "spmd"  — shard_map + ppermute GPipe pipeline (the TPU-native fast
            path; GPT-family block stacks additionally get per-stage
            HBM-resident weights via the stacked pipeline)
  "auto"  — spmd when the devices exist, else relay
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dnn_tpu.config import TopologyConfig
from dnn_tpu.parallel.mesh import STAGE_AXIS, mesh_from_config
from dnn_tpu.parallel.pipeline import (
    RelayExecutor,
    spmd_pipeline,
    spmd_pipeline_stacked,
)
from dnn_tpu.registry import get_model

log = logging.getLogger("dnn_tpu.engine")

_DTYPES = {"float32": None, "bfloat16": jnp.bfloat16}


def _pick_devices(device_type: str):
    """Consume config.device_type: prefer the requested platform, warn and
    fall back to the default if absent (the reference's cuda-else-cpu
    device pick, node.py:25)."""
    try:
        if device_type in ("tpu", "cpu"):
            devs = [d for d in jax.devices() if d.platform == device_type]
            if devs:
                return devs
            alt = jax.devices(device_type)
            if alt:
                return alt
    except RuntimeError:
        pass
    log.warning("device_type=%s not available; using default %s devices",
                device_type, jax.default_backend())
    return jax.devices()


class PipelineEngine:
    """Load once, run many — the object behind both the CLI (`dnn_tpu.node`)
    and the gRPC edge service."""

    def __init__(
        self,
        config: TopologyConfig,
        *,
        params: Optional[Any] = None,
        devices=None,
        rng_seed: int = 0,
        role: str = "full",
        lora_path: Optional[str] = None,
    ):
        if role not in ("full", "stage"):
            raise ValueError(f"role must be full|stage, got {role}")
        # runtime compile telemetry (dnn_tpu/obs): every XLA compile this
        # engine triggers — construction-time stage jits and any later
        # shape churn — lands in jax_compilations_total, the live
        # cross-check of the static recompile census (analysis PRG004)
        from dnn_tpu import obs

        obs.install_compile_telemetry()
        self.config = config
        self.role = role
        # downstream hop preference for the gRPC edge deployment
        # (role="stage" / --serve): the stage server negotiates
        # device | shm | grpc per hop at handshake (comm/transport.py);
        # serve_stage defaults to this resolved value
        self.transport = config.transport
        self.spec = get_model(config.model)
        if config.num_parts not in self.spec.supported_parts:
            raise ValueError(
                f"model '{config.model}' supports num_parts in "
                f"{self.spec.supported_parts}, config asks for {config.num_parts}"
            )
        if config.dtype not in _DTYPES:
            raise ValueError(f"dtype must be one of {sorted(_DTYPES)}, got {config.dtype}")
        self.compute_dtype = _DTYPES[config.dtype]

        # dtype plumbing: families exposing factories get real bf16 compute;
        # others warn rather than silently ignoring the config key.
        extras = self.spec.extras
        if self.compute_dtype is not None and "make_partition" not in extras:
            log.warning(
                "model '%s' has no dtype-aware factories; dtype=%s ignored",
                config.model, config.dtype,
            )
        if "make_partition" in extras:
            self.stages = list(
                extras["make_partition"](compute_dtype=self.compute_dtype)(config.num_parts)
            )
        else:
            self.stages = list(self.spec.partition(config.num_parts))

        self.params = params if params is not None else self._load_params(rng_seed)
        if lora_path:
            # merge-once LoRA deployment: base checkpoint + adapter npz ->
            # adapted weights, then every runtime below (stage slices,
            # stacked decode, gRPC edge) serves the tuned model at zero
            # inference-time overhead (dnn_tpu/lora.py)
            from dnn_tpu import lora as _lora

            adapters, alpha = _lora.load_lora(lora_path)
            self.params = _lora.merge_lora(self.params, adapters, alpha=alpha)
            log.info("merged LoRA adapters from %s (%d sites%s)",
                     lora_path, len(adapters),
                     f", alpha={alpha}" if alpha is not None else "")
        self.devices = list(devices) if devices is not None else _pick_devices(config.device_type)

        # compiled-once per-stage programs (the unit the gRPC edge serves)
        self._stage_params = [s.slice_params(self.params) for s in self.stages]
        # resolved spmd weight placement ("stage"|"replicated"); None until
        # (unless) the generic spmd runtime is built
        self.param_placement = None
        self._stage_jits = [jax.jit(s.apply) for s in self.stages]

        # Per-part device-resident param cache for run_stage: committed to
        # device on first use (HBM-resident thereafter, the analog of each
        # node loading its slice at startup — node.py:294-317). Lazy so a
        # 1-device stage host only ever uploads the one part it serves.
        self._stage_params_on_device: dict = {}

        if role == "stage":
            self.runtime = "stage"
            self.mesh = None
            self._relay = None
            self._pipeline_fn = None
        else:
            self.runtime = self._pick_runtime()
            if self.runtime == "spmd":
                self.mesh = mesh_from_config(config, self.devices)
                self._relay = None
                self._pipeline_fn = self._build_spmd_fn()
            else:
                self.mesh = None
                self._pipeline_fn = None
                self._relay = RelayExecutor(
                    [s.apply for s in self.stages], self._stage_params, devices=self.devices
                )
        log.info(
            "engine ready: model=%s parts=%d runtime=%s devices=%d dtype=%s",
            config.model, config.num_parts, self.runtime, len(self.devices), config.dtype,
        )

    # ------------------------------------------------------------------

    def _load_params(self, rng_seed: int):
        """Checkpoint path from config (config.json:15, node.py:241,296) or
        fresh init when absent (the reference hard-exits; we degrade to
        random weights so dry runs work without a blob — its weights file
        was stripped from the mirror too, .MISSING_LARGE_BLOBS)."""
        path = self.config.model_weights
        if not path:
            log.warning("no model_weights in config; using random init")
            return self.spec.init(jax.random.PRNGKey(rng_seed))
        from dnn_tpu.io import checkpoint as ckpt

        sd = ckpt.load_checkpoint(path)
        if ckpt.is_native_flat(sd):
            return ckpt.flat_to_params(sd)
        if self.spec.convert_state_dict is None:
            raise ValueError(
                f"checkpoint {path} is in a foreign layout and model "
                f"'{self.spec.name}' has no converter"
            )
        return self.spec.convert_state_dict(sd)

    def _pick_runtime(self) -> str:
        rt = self.config.runtime
        if jax.process_count() > 1:
            # Multi-host: every process must run one SPMD program over the
            # global mesh. The relay runtime device_puts onto explicit
            # devices, which are non-addressable from other hosts — it is
            # host-local by design.
            if rt == "relay":
                raise ValueError(
                    "runtime=relay is host-local; multi-host (distributed) "
                    "runs require runtime=spmd"
                )
            rt = "spmd"
        if rt == "auto":
            if self.config.num_parts == 1:
                return "relay"
            rt = "spmd" if len(self.devices) >= self.config.num_parts else "relay"
        if rt == "spmd" and len(self.devices) < self.config.num_parts:
            raise ValueError(
                f"runtime=spmd needs >= {self.config.num_parts} devices, "
                f"have {len(self.devices)} (use --serve / role='stage' to host "
                "a single stage on a small host)"
            )
        return rt

    # ------------------------------------------------------------------
    # compiled pipeline callables
    # ------------------------------------------------------------------

    def _effective_microbatches(self, batch: int) -> int:
        """Resolve the config's microbatch setting for a concrete batch.
        Explicit values pass through; 0 (auto) picks the largest divisor of
        the batch up to 2*num_parts — enough microbatches that the GPipe
        bubble fraction (S-1)/(M+S-1) drops to ~1/3, without a remainder
        microbatch. A batch of 1 degenerates to 1 (the reference's whole
        operating regime, node.py:147)."""
        m = self.config.microbatches
        if m != 0:
            return m
        desired = max(2 * self.config.num_parts, 1)
        for cand in range(min(desired, batch), 0, -1):
            if batch % cand == 0:
                return cand
        return 1

    def _gpt_stacked_ready(self) -> bool:
        """Dense-GPT fast path: uniform block stacks sharded one-stage-per-
        device, embed/head outside the ring. Needs equal blocks per stage.
        EXACT type match on purpose: subclassed configs (GPTMoEConfig) have
        different block params (no 'mlp'), so they take the generic
        partitioned path instead."""
        from dnn_tpu.models.gpt import GPTConfig

        cfg = self.spec.config
        return (
            type(cfg) is GPTConfig
            and cfg.n_layer % self.config.num_parts == 0
            and self.config.num_parts > 1
        )

    # Auto param-placement threshold: below this total param size the
    # per-device HBM savings of packed placement can't matter (every shipped
    # small model's weights fit everywhere many times over) while its
    # per-scan-step unpack work shows up — measured 10-18% on the cpu-mesh
    # CIFAR pipeline configs. Above it, per-stage HBM residency wins.
    PLACEMENT_AUTO_BYTES = 32 * 1024 * 1024

    def _resolve_param_placement(self) -> str:
        pp = self.config.param_placement
        if pp != "auto":
            return pp
        total = sum(
            l.size * jnp.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(self._stage_params)
        )
        return "stage" if total > self.PLACEMENT_AUTO_BYTES else "replicated"

    def _build_spmd_fn(self):
        if self._gpt_stacked_ready():
            return self._build_gpt_stacked_fn()

        from dnn_tpu.parallel.pipeline import pack_stage_params

        stage_applies = [s.apply for s in self.stages]
        mesh = self.mesh
        self.param_placement = self._resolve_param_placement()

        if self.param_placement == "replicated":
            def run_pipeline(sp, x, microbatches):
                return spmd_pipeline(
                    stage_applies, sp, x,
                    mesh=mesh, num_microbatches=microbatches,
                    axis_name=STAGE_AXIS, param_placement="replicated",
                )

            fn = jax.jit(run_pipeline, static_argnums=2)
            # replicate the params onto the mesh once — plain host arrays as
            # args would re-transfer host->devices on every call
            sp_placed = jax.device_put(
                tuple(self._stage_params), NamedSharding(mesh, P())
            )
            return lambda x: fn(
                sp_placed, x, self._effective_microbatches(x.shape[0])
            )

        # pack ONCE at load (on the host — the full (S, W) array never
        # touches a single device's HBM): each device holds only its own
        # stage's packed weight vector (P(stage)) — the per-stage placement
        # the relay runtime gets for free from explicit devices, now on the
        # SPMD path too
        packed_arr, metas = pack_stage_params(self._stage_params)
        packed_arr = jax.device_put(
            packed_arr, NamedSharding(mesh, P(STAGE_AXIS))
        )
        self._spmd_packed = packed_arr
        # Demote the unpacked model to host memory: per-stage placement only
        # reduces peak per-device HBM if the full-model device copies die.
        # The relay helpers (run_stage) and parity tests still work off the
        # host arrays — they just transfer on use.
        self.params = jax.tree.map(np.asarray, self.params)
        self._stage_params = [
            jax.tree.map(np.asarray, p) for p in self._stage_params
        ]
        stage_shapes = [
            # .dtype/.shape read straight off the (now-host) leaves — no
            # jnp.asarray, which would round-trip the whole model through
            # the default device right after demoting it
            jax.tree.map(lambda l: jax.ShapeDtypeStruct(jnp.shape(l), l.dtype), p)
            for p in self._stage_params
        ]

        def run_pipeline(packed_in, x, microbatches):
            return spmd_pipeline(
                stage_applies, stage_shapes, x,
                mesh=mesh, num_microbatches=microbatches, axis_name=STAGE_AXIS,
                packed=(packed_in, metas),
            )

        fn = jax.jit(run_pipeline, static_argnums=2)
        return lambda x: fn(packed_arr, x, self._effective_microbatches(x.shape[0]))

    def _build_gpt_stacked_fn(self):
        from dnn_tpu.models import gpt

        from dnn_tpu.runtime.generate import prepare_pipeline_stacked

        cfg = self.spec.config
        mesh = self.mesh
        compute_dtype = self.compute_dtype

        # The stacked layout IS per-stage placement (block params sharded
        # P(stage) below); record that so the resolved placement is
        # observable on this path too. An explicit "replicated" request
        # can't apply here — the stacked runtime exists to avoid it.
        if self.config.param_placement == "replicated":
            log.warning(
                "param_placement='replicated' ignored: the stacked GPT "
                "runtime always places block weights per-stage"
            )
        self.param_placement = "stage"

        # One-time, load-side: stack blocks stage-major (S, per_stage, ...)
        # and place each stage's slice on its device (HBM-resident per-stage
        # weights — BASELINE.json north star). prepare_pipeline_stacked is
        # the single owner of this layout; generation consumes the same
        # placement (self._gen_parts).
        stage_major, aux = prepare_pipeline_stacked(
            gpt.prepare_stacked(self.params, cfg), cfg, mesh
        )
        self._gen_parts = (stage_major, aux)

        def block_fn(stage_blocks, h):
            # stage_blocks: (per_stage, ...) — scan this stage's blocks
            return gpt.blocks_scan(
                stage_blocks, h, cfg=cfg, compute_dtype=compute_dtype
            )

        def run_pipeline(stacked, aux_params, ids, microbatches):
            x = gpt.embed(aux_params, ids, cfg=cfg)
            if compute_dtype is not None:
                x = x.astype(compute_dtype)
            h = spmd_pipeline_stacked(
                block_fn, stacked, x,
                mesh=mesh, num_microbatches=microbatches, axis_name=STAGE_AXIS,
            )
            return gpt.head(aux_params, h.astype(jnp.float32), cfg=cfg)

        fn = jax.jit(run_pipeline, static_argnums=3)
        return lambda ids: fn(
            stage_major, aux, ids, self._effective_microbatches(ids.shape[0])
        )

    # ------------------------------------------------------------------

    def run(self, x) -> jax.Array:
        """Full pipeline forward (all stages)."""
        if self.role == "stage":
            raise RuntimeError(
                "engine was built with role='stage' (serves one part); "
                "use run_stage, or build with role='full'"
            )
        if self.runtime == "spmd":
            return self._pipeline_fn(x)
        return self._relay(x)

    def run_stage(self, part_index: int, x) -> jax.Array:
        """One stage only — the unit of work a reference node performs per
        SendTensor (node.py:52-54); used by the gRPC edge service."""
        params = self._stage_params_on_device.get(part_index)
        if params is None:
            if self._relay is not None:
                # the relay executor already committed this stage's params to
                # its stage device — reuse, don't duplicate HBM on device 0
                params = self._relay.stage_params[part_index]
            else:
                params = jax.device_put(
                    self._stage_params[part_index], self.devices[0]
                )
            self._stage_params_on_device[part_index] = params
        return self._stage_jits[part_index](params, x)

    def predict(self, x) -> int:
        """Client-path final step: argmax over the last stage's output
        (node.py:61, 190-192). Spanned end-to-end (the np.asarray pull
        forces device completion, so the span is honest wall time)."""
        from dnn_tpu import obs

        with obs.span("engine.predict", runtime=self.runtime):
            pred = int(np.argmax(np.asarray(self.run(x))))
        m = obs.metrics()
        if m is not None:
            m.inc("engine.predicts_total")
        return pred

    # ------------------------------------------------------------------
    # autoregressive generation (GPT family)
    # ------------------------------------------------------------------

    def make_generator(self, *, max_new_tokens: int, temperature: float = 0.0,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       attn_kernel="auto", kv_dtype=None):
        """Build `generate(ids, rng=None) -> (B, max_new_tokens)` on this
        engine's weights. On the spmd runtime with the GPT stacked layout,
        decode runs PIPELINE-PARALLEL: each stage keeps its KV-cache shard
        with its blocks and the hidden state rides the ppermute ring per
        token (runtime/generate.make_pipeline_generate) — the serving
        capability the reference's partitions stop short of (they emit one
        stateless forward's logits, gpt_model_parts.py:36-50, and cannot
        decode). Other runtimes fall back to the single-program KV-cache
        decoder; both are token-for-token identical. `attn_kernel` is the
        cache-attention routing policy for the single-program decoders
        (kvcache._KernelDispatch): the default "auto" streams
        long-context decode through the Pallas position-clamped kernel
        on TPU and stays on the einsum path everywhere else. `kv_dtype`
        picks the cache storage for the single-program decoders (None
        follows the engine's compute dtype; "int8"/"int4" quantize the
        cache with per-(position, head) scales — runtime/kvcache.py;
        the pipeline-parallel ring decoder keeps its stage shards at
        compute dtype and rejects the override rather than silently
        ignoring it)."""
        from dnn_tpu.models.gpt import GPTConfig
        from dnn_tpu.models.gpt_moe import GPTMoEConfig
        from dnn_tpu.runtime.generate import make_generate, make_pipeline_generate

        cfg = self.spec.config
        self._require_full_role()
        default_rng = jax.random.PRNGKey(0)

        def single_program(gen):
            """Shared tail for every single-program family decoder: cache
            the prepared layout once, default the rng."""
            prepared = self._prepared()
            return lambda ids, rng=None: gen(
                prepared, ids, default_rng if rng is None else rng
            )

        from dnn_tpu.models.llama import LlamaConfig

        if isinstance(cfg, GPTMoEConfig):
            # MoE family decodes through the single-program routed decoder
            # (runtime/generate_moe.py); pipeline-parallel MoE decode is not
            # built, so spmd engines fall back to the local program too.
            from dnn_tpu.runtime.generate_moe import make_generate_moe

            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype is not plumbed through the MoE decoder")
            return single_program(make_generate_moe(
                cfg, max_new_tokens=max_new_tokens, temperature=temperature,
                sample_top_k=top_k, sample_top_p=top_p,
                compute_dtype=self.compute_dtype,
            ))
        if isinstance(cfg, LlamaConfig):
            from dnn_tpu.models import llama

            return single_program(llama.make_generate(
                cfg, max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, compute_dtype=self.compute_dtype,
                attn_kernel=attn_kernel, kv_dtype=kv_dtype,
            ))
        if type(cfg) is not GPTConfig:
            # exact match: the KV-cache decoder assumes dense-GPT block
            # params ('mlp'); unknown subclasses are not decodable through it
            raise ValueError(
                f"generation requires a GPT-family model; "
                f"'{self.config.model}' has config {type(cfg).__name__}"
            )
        if self.runtime == "spmd" and self._gpt_stacked_ready():
            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype applies to the single-program decoders; "
                    "pass kv_dtype on a family adapter for the "
                    "pipeline-parallel ring (generate.GPTPipelineFamily)")
            gen = make_pipeline_generate(
                cfg, self.mesh, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                compute_dtype=self.compute_dtype,
            )
            stage_major, aux = self._gen_parts
            return lambda ids, rng=None: gen(
                stage_major, aux, ids, default_rng if rng is None else rng
            )
        return single_program(make_generate(
            cfg, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, compute_dtype=self.compute_dtype,
            attn_kernel=attn_kernel, kv_dtype=kv_dtype,
        ))

    def _require_full_role(self):
        if self.role == "stage":
            raise RuntimeError(
                "generation needs the full pipeline; this engine was built "
                "with role='stage' (serves one part)"
            )

    def _prepared(self):
        """The stacked decode layout, built once per engine."""
        if not hasattr(self, "_prepared_single"):
            from dnn_tpu.models.gpt import prepare_stacked

            self._prepared_single = prepare_stacked(self.params,
                                                    self.spec.config)
        return self._prepared_single

    def _gen_cache(self) -> dict:
        cache = getattr(self, "_generators", None)
        if cache is None:
            cache = self._generators = {}
        return cache

    def generate(self, ids, *, max_new_tokens: int, temperature: float = 0.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 rng=None) -> jax.Array:
        """One-call generation; caches the compiled generator per
        (max_new_tokens, temperature, top_k) so repeated serving calls reuse
        the jitted program."""
        key = (max_new_tokens, temperature, top_k, top_p)
        cache = self._gen_cache()
        if key not in cache:
            cache[key] = self.make_generator(
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p,
            )
        return cache[key](jnp.asarray(ids, jnp.int32), rng)

    def generate_beam(self, ids, *, max_new_tokens: int, beam_size: int,
                      eos_id: Optional[int] = None,
                      length_penalty: float = 0.0) -> jax.Array:
        """Deterministic beam-search decode on this engine's weights
        (runtime/beam.py; dense GPT family only — the beams run as batch
        rows through the single-program KV-cache decoder). Compiled
        programs cache per parameter tuple like `generate`."""
        from dnn_tpu.models.gpt import GPTConfig
        from dnn_tpu.runtime.beam import make_beam_generate

        cfg = self.spec.config
        self._require_full_role()
        if type(cfg) is not GPTConfig:
            raise ValueError(
                f"beam search requires a dense GPT-family model; "
                f"'{self.config.model}' has config {type(cfg).__name__}")
        key = ("beam", max_new_tokens, beam_size, eos_id, length_penalty)
        cache = self._gen_cache()
        if key not in cache:
            prepared = self._prepared()
            gen = make_beam_generate(
                cfg, max_new_tokens=max_new_tokens, beam_size=beam_size,
                eos_id=eos_id, length_penalty=length_penalty,
                compute_dtype=self.compute_dtype)
            cache[key] = lambda i: gen(prepared, i)
        return cache[key](jnp.asarray(ids, jnp.int32))

    # ------------------------------------------------------------------
    # observability (SURVEY §5: the reference has none — prints only)
    # ------------------------------------------------------------------

    def benchmark(self, x, *, iters: int = 20, warmup: int = 3) -> dict:
        """Measure the BASELINE.json metrics on this engine's pipeline:
        items/sec (images or tokens), p50/p90 end-to-end step latency, and —
        in relay mode, where hops are individually observable — p50
        inter-stage hop latency (device->device transfer, stage 0's host
        ingress excluded) and per-stage compute. Timings force device
        completion via `tracing.device_sync` (block_until_ready is not a
        reliable barrier on tunneled TPUs; timing dispatch alone measures
        nothing)."""
        from dnn_tpu.utils import tracing
        from dnn_tpu.utils.metrics import Metrics

        if self.role == "stage":
            raise RuntimeError(
                "benchmark() needs the full pipeline; this engine was built "
                "with role='stage' (serves one part)"
            )
        m = Metrics()
        xs = np.asarray(x).shape
        # items: tokens (B*T) for integer id inputs, else examples (B) —
        # the BASELINE.json tokens/sec vs images/sec distinction.
        if np.issubdtype(np.asarray(x).dtype, np.integer) and len(xs) == 2:
            batch_items = int(xs[0] * xs[1])
        else:
            batch_items = int(xs[0])
        for _ in range(warmup):
            tracing.device_sync(self.run(x))
        # step latency: un-instrumented runs (one sync per step), so relay
        # numbers are comparable to spmd and to production behavior
        run_once = (lambda: self._relay(x)) if self.runtime == "relay" \
            else (lambda: self._pipeline_fn(x))
        for i in range(iters):
            with tracing.step_span(i, "bench_step"):
                with m.timer("step"):
                    tracing.device_sync(run_once())
        # hop/stage breakdown: separate instrumented relay runs (per-stage
        # syncs perturb the step timing, so they don't share iterations).
        # Hop latency uses the slope-based ping-pong measurement — a naive
        # per-hop device_put+sync sample is dominated by host/tunnel RTT.
        if self.runtime == "relay":
            for _ in range(min(iters, 5)):
                self._relay(x, record_timings=True)
                for st_t in self._relay.last_stage_times or []:
                    m.observe("stage_compute", st_t)
            if len(self.stages) > 1:
                for hop_t in self._relay.measure_hop_latency(x):
                    m.observe("inter_stage_hop", hop_t)
        snap = m.snapshot()
        step = snap["latency"]["step"]
        result = {
            "items_per_sec": batch_items / step["p50"],
            "step_latency_p50_s": step["p50"],
            "step_latency_p90_s": step["p90"],
            "runtime": self.runtime,
            "iters": iters,
        }
        if "inter_stage_hop" in snap["latency"]:
            result["inter_stage_hop_p50_s"] = snap["latency"]["inter_stage_hop"]["p50"]
        if "stage_compute" in snap["latency"]:
            result["stage_compute_p50_s"] = snap["latency"]["stage_compute"]["p50"]
        # mirror the headline gauges into the shared obs registry so a
        # /metrics scrape of a long-lived server reflects the last
        # measured pipeline numbers too
        from dnn_tpu import obs

        m_obs = obs.metrics()
        if m_obs is not None:
            m_obs.set("engine.items_per_sec", result["items_per_sec"])
            m_obs.set("engine.step_latency_p50_seconds", step["p50"])
        return result
