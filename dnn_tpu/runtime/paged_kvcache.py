"""Paged KV cache: a shared block pool + per-slot block tables.

The dense codecs (dnn_tpu/runtime/kvcache.py) reserve `max_len` cache
positions per slot — a pool of S slots costs S x max_len positions of HBM
whether requests use them or not. This module stores K/V in fixed-size
POSITION BLOCKS drawn from one shared pool, with each slot holding a
small int32 table mapping its logical block index -> physical pool block
(the vLLM design, rebuilt TPU-style: the pool and tables are plain
static-shaped arrays, block lookup is a gather, block write is a scatter
— no dynamic shapes anywhere, so the serving runtime keeps its
fixed-program-count compile story).

What this buys a serving pool (tests/test_paged.py measures both):
  * admission by ACTUAL length — a pool sized for 2 full-length requests
    admits 4+ short ones concurrently (sum of ceil(len/bp) blocks, not
    slots x max_len);
  * allocation/free at block granularity per request lifetime, host-side
    (a free-list of ints — no device work to retire a request).

Layout (per K and per V, mirroring the dense cache's (L, B, H, S, D)):

    pool   (L, n_blocks, H, block_len, D)
    tables (L, B, max_blocks)  int32   -- replicated over L so the decode
                                          scan over layers peels tables
                                          alongside the pool leaves
    pos    (B,)                        -- slot lengths, as in dense

The codec interface matches FloatKV (write_rows / attend_rows /
install_row), so GPTFamilyRows / LlamaFamilyRows decode through it
unchanged. Attention gathers the slot's blocks into a (B, H, S_max, D)
view and runs the identical masked einsum — the reference math is the
dense codec's, so token parity is exact. (A Pallas paged-attention kernel
would instead feed the table through the scalar-prefetch index map of
ops/pallas/cached_attention._decode_call, reading blocks straight from
the pool; the einsum path is the correctness baseline.)

No counterpart exists in the reference framework (its only state is a
per-request activation, /root/reference/node.py:45-105 — no cache at
all); this is part of the modern-serving surface built on top of parity.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnn_tpu.runtime.kvcache import band_keep

_NEG_BIG = -1e30

__all__ = ["PagedKV", "BlockAllocator", "InsufficientBlocks",
           "init_paged_cache"]


class InsufficientBlocks(RuntimeError):
    """The pool cannot currently satisfy an admission — a TRANSIENT
    condition (blocks free as running requests retire), distinct from the
    permanent no-free-slot/never-fits errors: queueing fronts (the LM
    daemon worker) catch this and hold the request back instead of
    failing it."""


class BlockAllocator:
    """Host-side free-list over pool block ids. Block 0 is RESERVED as the
    junk target: 0-initialized / unowned table entries point at it, so
    install scribbles and inactive-slot decode writes land there instead
    of aliasing a live block; its content is never attended (the per-row
    position mask stops at each slot's length)."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(1, n_blocks))
        # block id -> reference count. SHARING (paged prefix cache): a
        # block may be held by several slots plus a prefix-cache entry at
        # once; it returns to the free list when the last holder lets go.
        self._rc = {}
        # memory observability (dnn_tpu/obs/mem.py): the pool's
        # high-water mark — max blocks ever simultaneously in use. "How
        # close did the pool come to full" is the capacity-planning
        # number a used-right-now gauge cannot answer after the burst
        # has passed; the serving layer exports all three as gauges.
        self.high_water = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        """Blocks currently held (block 0's permanent reservation is not
        "use"); n_used + n_free == n_blocks - 1 always."""
        return self.n_blocks - 1 - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh block ids (each at refcount 1), or None if the pool
        can't satisfy the request (caller decides whether to queue or
        reject)."""
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        for b in taken:
            self._rc[b] = 1
        if self.n_used > self.high_water:
            self.high_water = self.n_used
        return taken

    def ref(self, blocks: List[int]):
        """Take an additional reference on live blocks (prefix sharing).
        Validates the WHOLE list before mutating: a bad id mid-list must
        not leave earlier refcounts raised (callers treat ref/free as
        atomic when unwinding)."""
        for b in blocks:
            if self._rc.get(b, 0) < 1:
                raise ValueError(f"ref on non-live block {b}")
        for b in blocks:
            self._rc[b] += 1

    def free(self, blocks: List[int]):
        """Release one reference per listed occurrence; blocks whose last
        reference drops return to the free list. Validates the WHOLE list
        (including duplicate occurrences against the refcount) before
        mutating, so a bad id can never leave the allocator half-freed —
        callers unwind by re-freeing lists and must not double-decrement."""
        from collections import Counter

        counts = Counter(blocks)
        for b, n in counts.items():
            if b == 0 or b >= self.n_blocks or self._rc.get(b, 0) < n:
                raise ValueError(f"free of non-live block {b}")
        for b, n in counts.items():
            rc = self._rc[b] - n
            if rc == 0:
                del self._rc[b]
                self._free.append(b)
            else:
                self._rc[b] = rc


def init_paged_cache(cfg, slots: int, max_len: int, *, n_blocks: int,
                     block_len: int = 16, dtype=jnp.float32,
                     kv_heads: Optional[int] = None):
    """Pool + tables pytree for `slots` decode rows of up to `max_len`
    positions each, sharing `n_blocks` physical blocks of `block_len`
    positions. The pytree rides the same lax.scan-over-layers as the
    dense cache (leading L on every leaf). `kv_heads` overrides the
    pool's head width — GQA families store KV heads, not query heads
    (llama.init_cache's narrowing, here applied to the pool).
    dtype="int8" / "int4" build the quantized pools: int8/int4 K/V
    blocks plus per-(position, head) f32 scale blocks, the paged forms
    of kvcache.Int8KV / Int4KV's layouts (int4 stores native jnp.int4,
    two values per byte)."""
    if max_len % block_len:
        raise ValueError(f"max_len {max_len} must tile block_len {block_len}")
    head_dim = cfg.n_embd // cfg.n_head
    heads = kv_heads if kv_heads is not None else cfg.n_head
    nb_max = max_len // block_len
    shape = (cfg.n_layer, n_blocks, heads, block_len, head_dim)
    tables = jnp.zeros((cfg.n_layer, slots, nb_max), jnp.int32)
    if dtype in ("int8", "int4"):
        qdt = jnp.int8 if dtype == "int8" else jnp.int4
        return {
            "k": jnp.zeros(shape, qdt),
            "v": jnp.zeros(shape, qdt),
            "ks": jnp.ones(shape[:-1], jnp.float32),
            "vs": jnp.ones(shape[:-1], jnp.float32),
            "tables": tables,
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "tables": tables,
    }


class PagedKV:
    """Codec over the shared block pool (see module docstring).

    Same call surface the batcher's decode/install paths use on the
    dense codecs (kvcache.FloatKV).

    `window=W` (Mistral-class sliding windows) adds the band's lower
    bound to attend_rows — positions <= pos - W never attend — and is
    what lets the SERVING layer reclaim fully-rolled-out blocks while a
    request still runs (ContinuousBatcher._free_rolled_blocks): a long
    windowed stream holds O(window) pool blocks, not O(stream).

    `use_kernel` routes attend_rows through the fused paged flash-decode
    kernel (ops/pallas/cached_attention.paged_decode_attention): the
    slot's block table rides scalar prefetch and each grid step DMAs its
    PHYSICAL block straight from the pool — no gather_view
    materialization, per-step traffic clamped at each slot's live
    length. True/"interpret" are unconditional; "auto" engages it only
    on TPU against pools whose per-slot logical length reaches
    kvcache.AUTO_KERNEL_MIN_S (the dense codecs' length-aware policy).
    Windowed pools and int4 pools stay on the einsum (the kernel masks
    causally only / sub-byte VMEM loads are not wired)."""

    def __init__(self, block_len: int, window: Optional[int] = None,
                 use_kernel=False):
        self.block_len = block_len
        self.window = window
        self.use_kernel = use_kernel

    def _kernel_on(self, c) -> bool:
        """Resolve use_kernel against a concrete per-layer pool view
        (pool (n_blocks, H, bp, D), tables (B, nb_max)) — the paged
        mirror of kvcache._KernelDispatch._kernel_on."""
        if self.window is not None or c["k"].dtype == jnp.int4:
            return False
        if self.use_kernel == "auto":
            from dnn_tpu.runtime.kvcache import AUTO_KERNEL_MIN_S

            logical = c["tables"].shape[-1] * self.block_len
            return (jax.default_backend() == "tpu"
                    and logical >= AUTO_KERNEL_MIN_S)
        return bool(self.use_kernel)

    # --- decode-row paths (per-layer views: pool (n_blocks, H, bp, D),
    #     tables (B, nb_max)) ------------------------------------------

    def write_rows(self, c, k, v, pos, write_gate):
        """k/v (B, H, 1, D) at per-slot positions pos (B,); write_gate (B,)
        keeps inactive slots' LIVE state untouched. Physical target: block
        tables[b, pos//bp], row pos%bp — one scatter per leaf. An int8
        pool quantizes the incoming rows first (kvcache._quantize_rows)
        and scatters the per-(position, head) scales alongside.

        Gated-off slots are ROUTED TO the reserved junk block (0, row 0)
        rather than restored-in-place: a retired slot's stale table can
        point at a block since REALLOCATED to another request, and a
        duplicate scatter index (stale restore vs the new owner's write)
        has unspecified winner — the restore could resurrect the old
        request's K/V inside the new one's cache. Junk-block collisions
        between gated slots are harmless (block 0 is never owned, never
        attended live)."""
        bp = self.block_len
        blk = jnp.take_along_axis(
            c["tables"], (pos // bp)[:, None], axis=1)[:, 0]  # (B,)
        row = pos % bp
        blk = jnp.where(write_gate, blk, 0)
        row = jnp.where(write_gate, row, 0)
        out = {"tables": c["tables"]}
        if "ks" in c:
            from dnn_tpu.runtime.kvcache import (
                _quantize_rows,
                _quantize_rows_int4,
            )

            quantize = (_quantize_rows_int4 if c["k"].dtype == jnp.int4
                        else _quantize_rows)
            kq, ks = quantize(k)  # (B,H,1,D), (B,H,1)
            vq, vs = quantize(v)
            out["k"] = c["k"].at[blk, :, row].set(kq[:, :, 0])
            out["v"] = c["v"].at[blk, :, row].set(vq[:, :, 0])
            out["ks"] = c["ks"].at[blk, :, row].set(ks[:, :, 0])
            out["vs"] = c["vs"].at[blk, :, row].set(vs[:, :, 0])
            return out
        out["k"] = c["k"].at[blk, :, row].set(k[:, :, 0].astype(c["k"].dtype))
        out["v"] = c["v"].at[blk, :, row].set(v[:, :, 0].astype(c["v"].dtype))
        return out

    def gather_view(self, c, names=("k", "v")):
        """Dense (B, H, S_max, ...) views of every slot's logical cache —
        the einsum attention baseline (a paged Pallas kernel would skip
        this materialization). Handles K/V blocks (…, bp, D) and scale
        blocks (…, bp) alike."""
        tables = c["tables"]  # (B, nb_max)
        b, nb = tables.shape
        out = []
        for name in names:
            leaf = c[name]
            g = jnp.take(leaf, tables.reshape(-1), axis=0)  # (B*nb, H, bp[, D])
            h, bp = g.shape[1], g.shape[2]
            rest = g.shape[3:]
            g = g.reshape(b, nb, h, bp, *rest)
            g = jnp.moveaxis(g, 1, 2)  # (B, H, nb, bp[, D])
            out.append(g.reshape(b, h, nb * bp, *rest))
        return out

    def attend_rows(self, q, c, pos, window=None):
        """q (B, H, R, D); every row of slot b attends logical positions
        <= pos[b] (identical math to kvcache.FloatKV/Int8KV.attend_rows
        on the gathered view — int8 pools fold their per-position scales
        onto the score/probability matrices, never a float cache copy),
        band-limited by the codec's `window` when set. A per-call
        `window` override is the dense codecs' per-LAYER channel
        (alt-window configs) — those are rejected at batcher
        construction for paged pools, so an override here is a
        programming error."""
        if window is not None:
            raise ValueError(
                "PagedKV has no per-layer window channel (alt-window "
                "families are rejected for paged pools); set the codec's "
                "window at construction")
        quant = "ks" in c
        if self._kernel_on(c):
            from dnn_tpu.ops.pallas.cached_attention import (
                paged_decode_attention,
            )

            interp = True if self.use_kernel == "interpret" else None
            out = paged_decode_attention(
                q, c["k"], c["v"], c["tables"], pos,
                ks=c["ks"] if quant else None,
                vs=c["vs"] if quant else None,
                interpret=interp)
            # same output-dtype recipe as the einsum path below
            return out if quant else out.astype(c["v"].dtype)
        if quant:
            k, v, ks, vs = self.gather_view(c, ("k", "v", "ks", "vs"))
        else:
            k, v = self.gather_view(c)
        d = q.shape[-1]
        s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                       k.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if quant:
            s = s * ks[:, :, None, :]
        s = s / jnp.sqrt(d)
        cols = jnp.arange(k.shape[2])
        mask = band_keep(cols[None, None, None, :],
                         pos[:, None, None, None], self.window)
        s = jnp.where(mask, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        if quant:
            p = p * vs[:, :, None, :]
        out = jnp.einsum("bhts,bhsd->bhtd", p.astype(jnp.float32),
                         v.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        return out if quant else out.astype(c["v"].dtype)

    # --- prefill install (full-cache view: pool (L, n_blocks, H, bp, D),
    #     tables (L, B, nb_max)) ---------------------------------------

    def install_row(self, cache, row, blk_ids):
        """Scatter a finished transient row cache (the dense chunked-
        prefill output, leaves (L, 1, H, row_len, D)) into the physical
        blocks `blk_ids` (nb_max,). ALL nb_max logical blocks install
        unconditionally (one compiled program for every prompt length):
        entries the request must not write — unowned tail AND shared
        prefix blocks (another request's live data!) — are routed to the
        reserved junk block 0, whose content is never attended live (the
        per-row position mask), so scribbling it is harmless."""
        bp = self.block_len
        out = {"tables": cache["tables"]}
        nb_max = blk_ids.shape[0]
        for kk in cache:
            if kk == "tables":
                continue
            r = row[kk][:, 0]  # (L, H, row_len[, D]) — scales have no D
            l_, h, rl = r.shape[:3]
            rest = r.shape[3:]
            blocks = r.reshape(l_, h, rl // bp, bp, *rest)[:, :, :nb_max]
            blocks = jnp.moveaxis(blocks, 2, 1)  # (L, nb_max, H, bp[, D])
            out[kk] = cache[kk].at[:, blk_ids].set(
                blocks.astype(cache[kk].dtype))
        return out


def codec_is_paged(cache) -> bool:
    return isinstance(cache, dict) and "tables" in cache
