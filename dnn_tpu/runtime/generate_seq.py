"""Sequence-sharded KV-cache decode — serving beyond one device's cache.

Why: at long context the KV cache, not the weights, is what no longer
fits: a GPT-2-small-shaped model at S=128k, B=8 carries a multi-GB f32
cache. The two sequence-parallel strategies already in the tree
(ring attention, Ulysses — dnn_tpu/parallel/{ring_attention,ulysses}.py)
cover STATELESS forwards; this module is the missing serving bridge
(VERDICT r2, next #8): a decode loop whose cache is sharded over the
"seq" mesh axis, each device owning a contiguous block of positions.

Design (and why it is NOT a ring):

  * Cache layout: device i of n owns global positions
    [i*Sd, (i+1)*Sd), Sd = S_max/n — a (L, B, H, Sd, D) local cache.
    Nothing cache-shaped ever moves between devices.
  * Decode step at position p: the (B, 1, C) hidden state is replicated
    (it is tiny); every device computes q/k/v, but only p's OWNER writes
    k/v into its slice. Attention runs as a DISTRIBUTED SOFTMAX: each
    device reduces its local slice to per-row stats
    (m_i = max score, l_i = sum exp(s − m_i), o_i = exp(s − m_i) @ v),
    then one pmax + two psums combine them exactly:
        M = pmax(m_i);  l = Σ l_i e^{m_i−M};  o = Σ o_i e^{m_i−M};
        out = o / l.
    This is the online-softmax merge (same algebra as flash/ring
    attention) applied once across shards — exact, not approximate.
    A q-side ring (rotating the query past every cache shard, n hops of
    latency per layer) would serve a long QUERY; for single-token decode
    the query is one row, so collapsing each shard to O(B*H*D) stats and
    psum-ing them costs one collective round instead of n hops.
  * Prefill (prototype scope): the prompt's K/V are computed by the
    standard full forward — replicated compute over a TRANSIENT cache of
    the prompt's t positions only (never the decode region), from which
    each device gathers its own columns; peak per-device cache is
    t + S_max/n, and the S_max-sized state only ever exists sharded.
    This is acceptable until prompts themselves exceed one device; a
    production prefill would run the ring-attention forward and write
    shards in place (the two modules compose — same mesh axis).
  * Sampling runs replicated with the same rng on every device, so all
    shards agree on the next token with no extra collective.

Parity contract (tests/test_generate_seq.py): token-for-token equal to
the single-device `make_generate` while each device's cache holds only
S_max/n positions — the criterion that T exceeds one device's cache.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dnn_tpu.models.gpt import GPTConfig, head
from dnn_tpu.ops.attention import merge_heads
from dnn_tpu.ops.nn import gelu, layer_norm, linear
from dnn_tpu.parallel.mesh import SEQ_AXIS
from dnn_tpu.runtime.generate import (
    _embed_at,
    _qkv_heads,
    _sample,
    forward_with_cache,
    init_cache,
)

_NEG_BIG = -1e30

__all__ = ["make_generate_seq_sharded"]


def _local_attn_stats(q, k_local, v_local, local_limit):
    """One shard's partial attention: q (B,H,1,D) vs the local cache
    slice (B,H,Sd,D), masked to local positions <= local_limit (a scalar;
    negative = nothing valid here). Returns (m, l, o): running max (B,H,1),
    exp-sum (B,H,1), unnormalized value sum (B,H,1,D) — the online-softmax
    partials the cross-shard psum combines."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                   k_local.astype(jnp.float32),
                   preferred_element_type=jnp.float32) / jnp.sqrt(d)
    cols = jnp.arange(k_local.shape[2])
    s = jnp.where(cols[None, None, None, :] <= local_limit, s, _NEG_BIG)
    m = jnp.max(s, axis=-1)                      # (B,H,1)
    e = jnp.exp(s - m[..., None])
    # rows with no valid position: m == NEG_BIG and every e == 1; zero
    # them via the mask sum so they contribute nothing after the shift
    e = jnp.where(cols[None, None, None, :] <= local_limit, e, 0.0)
    l = jnp.sum(e, axis=-1)                      # (B,H,1)
    o = jnp.einsum("bhts,bhsd->bhtd", e, v_local.astype(jnp.float32))
    return m, l, o


def make_generate_seq_sharded(cfg: GPTConfig, mesh, *, max_new_tokens: int,
                              temperature: float = 0.0,
                              top_k: Optional[int] = None,
                              top_p: Optional[float] = None,
                              compute_dtype=None,
                              axis_name: str = SEQ_AXIS):
    """Build generate(prepared, ids, rng) with the KV cache sharded over
    `mesh`'s seq axis. The prompt length is static per compilation; the
    total context (prompt + max_new_tokens, padded up to a multiple of the
    axis size) partitions into per-device slices."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    n = mesh.shape[axis_name]

    def per_device(prepared, ids, rng):
        b, t = ids.shape
        s_max = t + max_new_tokens
        sd = -(-s_max // n)  # ceil: each device owns sd positions
        i = lax.axis_index(axis_name)
        lo = i * sd  # my first global position

        # ---- prefill: full forward (replicated), keep my K/V slice.
        # The transient cache covers ONLY the prompt's t positions — never
        # the decode region — so peak per-device cache is t + sd, not the
        # full s_max everywhere (the whole point of sharding). Each device
        # then gathers the columns of its own global range; positions
        # beyond the prompt (or beyond s_max on the ragged last shard)
        # zero out and stay masked until decode writes them. ----
        prompt_cache = init_cache(cfg, b, t, compute_dtype or jnp.float32)
        # attn_kernel pinned off: this forward runs INSIDE shard_map,
        # where the "auto" policy's Pallas engagement is untested (same
        # pin as every other shard_map call site)
        logits, prompt_cache = forward_with_cache(
            prepared, ids, prompt_cache, 0, cfg=cfg,
            compute_dtype=compute_dtype, attn_kernel=False)
        g = lo + jnp.arange(sd)          # my global positions
        in_prompt = g < t
        local = {
            kk: jnp.where(
                in_prompt[None, None, None, :, None],
                jnp.take(prompt_cache[kk], jnp.clip(g, 0, t - 1), axis=3),
                0,
            )
            for kk in ("k", "v")
        }  # (L, B, H, Sd, D) — my positions only
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature=temperature,
                      top_k=top_k, top_p=top_p)

        def block_step(bp, x, lc_k, lc_v, p):
            """One block at decode position p against my cache slice."""
            h = layer_norm(bp["ln_1"], x, eps=cfg.ln_eps)
            q, k, v = _qkv_heads(bp, h, cfg=cfg, compute_dtype=compute_dtype)
            # p's owner writes the new row into its slice
            p_loc = jnp.clip(p - lo, 0, sd - 1)
            own = jnp.logical_and(p >= lo, p < lo + sd)
            lc_k = jnp.where(
                own,
                lax.dynamic_update_slice_in_dim(
                    lc_k, k.astype(lc_k.dtype), p_loc, axis=2),
                lc_k)
            lc_v = jnp.where(
                own,
                lax.dynamic_update_slice_in_dim(
                    lc_v, v.astype(lc_v.dtype), p_loc, axis=2),
                lc_v)
            # distributed softmax over shards: local stats, then combine
            local_limit = jnp.minimum(p - lo, sd - 1)  # < 0 -> no valid pos
            m, l, o = _local_attn_stats(q, lc_k, lc_v, local_limit)
            g_m = lax.pmax(m, axis_name)
            w = jnp.exp(m - g_m)
            g_l = lax.psum(l * w, axis_name)
            g_o = lax.psum(o * w[..., None], axis_name)
            y = g_o / jnp.maximum(g_l, 1e-30)[..., None]
            x = x + linear(bp["attn"]["proj"], merge_heads(y.astype(x.dtype)),
                           compute_dtype=compute_dtype)
            h = layer_norm(bp["ln_2"], x, eps=cfg.ln_eps)
            mlp = linear(bp["mlp"]["proj"],
                         gelu(linear(bp["mlp"]["fc"], h,
                                     compute_dtype=compute_dtype)),
                         compute_dtype=compute_dtype)
            return x + mlp, lc_k, lc_v

        def decode_one(local, tok, rng, p):
            x = _embed_at(prepared, tok[:, None], p,
                          compute_dtype=compute_dtype)

            def layer(carry, layer_in):
                bp, lk, lv = layer_in
                y, lk, lv = block_step(bp, carry, lk, lv, p)
                return y, (lk, lv)

            x, (k_new, v_new) = lax.scan(
                layer, x, (prepared["blocks"], local["k"], local["v"]))
            logits = head(prepared, x.astype(jnp.float32), cfg=cfg,
                          compute_dtype=compute_dtype)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k, top_p=top_p)
            return {"k": k_new, "v": v_new}, nxt, rng

        def step(carry, j):
            local, tok, rng = carry
            local, nxt, rng = decode_one(local, tok, rng, t + j)
            return (local, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (local, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    @jax.jit
    def generate(prepared, ids, rng):
        b, t = ids.shape
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )(prepared, ids, rng)

    return generate
