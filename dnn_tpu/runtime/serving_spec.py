"""Speculative CONTINUOUS batching: draft-assisted decode inside the slot
pool.

`runtime/speculative.py` breaks decode's serial chain for ONE stream (its
batch-1 check points here for throughput); this module lifts the same
construction into the continuous batcher, where it was the one serving
feature that didn't compose (README's composition matrix). The insight
that makes it fit: the batcher already tracks PER-ROW positions, and the
solo design's core trick — "variable acceptance exists only as an
integer, never as a shape" — vectorizes to a (B,) integer: every step,
ALL active slots propose k draft tokens, the target verifies every row's
k+1 positions in one forward, and each slot commits its own m+1 <= k+1
tokens. Static shapes throughout; rejected proposals roll back by not
advancing that row's position (their stale cache entries sit beyond the
per-row attention limit, exactly as in the solo loop and the chunked
prefill's tail pad).

Per step, one compiled program (`spec_step`) runs:
  1. draft sync: idempotent re-feed of each row's previous verify chunk
     at its old positions (fills exactly the draft-cache entries that
     could be missing; recomputing present ones is a no-op);
  2. k draft decode steps propose (B, k) tokens (greedy, or sampled from
     the draft's filtered distribution with each slot's own rng stream);
  3. one target verify over the (B, k+1) chunks [last, p1..pk] at
     per-row positions (GPTFamilyRows.verify_rows);
  4. per-row acceptance — greedy: longest prefix where the draft matches
     the target's argmax (output tokens ARE the target's picks, so
     greedy results are token-identical to the plain batcher: the parity
     contract tests/test_serving_spec.py pins); sampled: the
     rejection-sampling construction of Leviathan et al. 2023 (accept
     with min(1, p_t/p_d), resample the first rejection from the
     normalized residual, bonus sample when all accepted), vectorized
     over rows;
  5. per-row commit: pos += m+1 (inactive rows 0), last = w[m], and the
     (B, k+1) committed-token block + (B,) counts return to the host,
     which appends each slot's tokens (budget/stop/eos checks run per
     token, so a mid-chunk stop retires the slot and discards the rest).

Restrictions (all checked at construction/submit): target and draft
with equal vocabularies — any FAMILY pair works (GPT default; pass
family=/draft_family= adapters with verify_rows, e.g.
llama.LlamaFamilyRows, including cross-family GPT-draft-for-LLaMA
-target), as long as both attend dense (no sliding window / softcap);
float caches (the solo module's reasoning: chunked re-feeds would
re-quantize int8 rows differently from the oracle path), dense
(non-paged) pool, server-level temperature/top_k (the rejection math
runs one distribution transform for the whole pool; per-request
sampling overrides are the dense batcher's feature), prompts of at
least k+1 tokens (the first sync chunk re-feeds the prompt tail), and
len(prompt) + max_new + k <= max_len (verify writes up to k positions of
scratch beyond the last committed token).

`decode_buckets=` COMPOSES (ISSUE 6): the target pool grows through
the ladder exactly as the dense batcher's, the draft pool grows in
lockstep, and every grow covers the verify chunk's +k scratch
(_ensure_cache_len). The spec programs re-trace once per ladder rung —
the same bounded relaxation of the program-count contract the dense
bucketed step accepted in PR 1 — and greedy token identity to the
UNBUCKETED spec pool (and hence to the plain batcher) holds by the
bucket-view argument: a rung differs from the full allocation only in
columns beyond every row's band limit. Acceptance-weighted tokens/step
now multiplies the bucketed bytes/step win instead of forfeiting it
(tests/test_spec_buckets.py pins parity through rung crossings).

The reference framework has no decode at all (SURVEY §3.2); this is the
deepest point of the serving stack built beyond it.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnn_tpu import obs
from dnn_tpu.models.gpt import GPTConfig, prepare_stacked  # noqa: F401
from dnn_tpu.runtime.kvcache import codec_for_cache
from dnn_tpu.runtime.serving import (ContinuousBatcher, GPTFamilyRows,
                                     install_dense_row)
# the ONE sampling transform shared with the solo speculative loop:
# rejection sampling is only exact when draft and target use the
# identical transform, so both paths must import the same function
from dnn_tpu.runtime.speculative import _probs

__all__ = ["SpeculativeBatcher"]


class SpeculativeBatcher(ContinuousBatcher):
    """ContinuousBatcher whose step() advances every active slot by UP TO
    k+1 tokens per call via draft-model speculation. Submit/retire/stop/
    finish-reason surfaces are inherited unchanged."""

    # a verified chunk commits up to k+1 tokens in one device call —
    # per-token grammar masks cannot gate it (submit rejects constraint=)
    _constraints_ok = False

    def __init__(self, cfg: GPTConfig, prepared, draft_cfg: GPTConfig,
                 draft_prepared, *, spec_k: int = 4, draft_family=None,
                 **kw):
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size}")
        if kw.get("kv") == "paged":
            raise ValueError(
                "SpeculativeBatcher pins the dense pool (the spec codecs "
                "attend dense; paged x speculative is not composed)")
        if kw.get("kv") == "auto":
            # the serving-path default resolves to dense here — the
            # parent's auto-paging would hand the spec codecs a block
            # pool they cannot attend. Recorded like every other auto
            # fallback (the README's kv contract: a fallback always
            # leaves a flight event saying why).
            from dnn_tpu import obs

            obs.flight.record(
                "kv_fallback_dense",
                reason="speculative serving pins the dense pool")
            kw["kv"] = "dense"
        for bad in ("ffn", "paged_blocks", "logprobs_k",
                    "attn_kernel", "top_p", "min_p", "repetition_penalty",
                    "lora_adapters", "allow_constraints"):
            # allow_constraints would allocate the (constraint_rows, V)
            # device mask pool for a batcher that rejects every
            # constrained submit (_constraints_ok=False) — fail at
            # construction, not per request
            val = kw.get(bad)
            if val and not (bad == "attn_kernel" and val == "auto"):
                # "auto" is ContinuousBatcher's default mode, not an
                # opt-in: spelling the default out loud is not an error
                raise ValueError(
                    f"SpeculativeBatcher does not support {bad}=")
        # ...but the unsupported kernel path must also not sneak in via
        # the "auto" default on long pools (max_len >= AUTO_KERNEL_MIN_S
        # on TPU would engage it): pin the einsum explicitly
        kw["attn_kernel"] = False
        if kw.get("kv_dtype") == "int8":
            raise ValueError(
                "SpeculativeBatcher pins float caches (chunked re-feeds "
                "would re-quantize int8 rows differently from the oracle "
                "path — see runtime/speculative.py)")
        super().__init__(cfg, prepared, **kw)
        if draft_cfg.block_size < self.max_len:
            # draft positions run to max_len-1 (submit's budget check);
            # past its wpe table the position gather would silently clamp
            # and acceptance would collapse with no error anywhere
            raise ValueError(
                f"draft block_size {draft_cfg.block_size} < max_len "
                f"{self.max_len}; shrink max_len or use a longer draft")
        self.spec_k = int(spec_k)
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.draft_cfg = draft_cfg
        self.draft_prepared = draft_prepared
        self._temperature = float(kw.get("temperature", 0.0) or 0.0)
        self._top_k_opt = kw.get("top_k")
        self._greedy = self._temperature == 0.0

        k = self.spec_k
        cache_dtype = self.cache["k"].dtype
        # family adapters generalize the pair beyond GPT: any adapter
        # with verify_rows (llama.LlamaFamilyRows included) serves as
        # target (kw family=) or draft (draft_family=) — cross-family
        # pairs only need matching vocabularies. Windowed/softcapped
        # families are rejected: the spec codecs attend dense.
        if draft_family is None and not isinstance(draft_cfg, GPTConfig):
            # defaulting a LLaMA-class draft onto the GPT adapter would
            # fail deep inside the jitted spec_step trace (missing wpe,
            # no ln_eps) — fail at construction with the fix instead
            raise ValueError(
                f"draft_cfg is {type(draft_cfg).__name__}, not GPTConfig "
                "— pass draft_family= (e.g. llama.LlamaFamilyRows("
                "draft_cfg)) for non-GPT drafts")
        d_family = draft_family or GPTFamilyRows(
            draft_cfg, compute_dtype=self.family.compute_dtype)
        for fam, which in ((self.family, "target"), (d_family, "draft")):
            # paged_ok is the family's "attends plain causal" capability
            # flag (False for window/softcap/alt-window configs —
            # llama.LlamaFamilyRows) — exactly the condition the dense
            # spec codecs need; absent attribute (GPT) means True
            if not getattr(fam, "paged_ok", True):
                raise ValueError(
                    f"speculative serving supports dense-attention "
                    f"families only (the {which} family has a sliding "
                    "window or attention softcap)")
            if not hasattr(fam, "verify_rows"):
                raise ValueError(
                    f"the {which} family adapter has no verify_rows — "
                    "speculative serving needs the per-row block-verify "
                    "program")
        # the draft needs the same scratch headroom past max_len the
        # target gets via the submit budget check (verify/propose write
        # up to k positions beyond the last committed token). On a
        # bucketed pool (decode_buckets= now composes — the spec
        # programs re-trace once per ladder rung, the same bounded
        # relaxation the dense step accepted in PR 1) the draft cache
        # starts at the target's first bucket and grows in LOCKSTEP
        # through _ensure_cache_len, so both sides' verify blocks always
        # cover pos + k.
        self.d_cache = d_family.init_cache(self.slots, self._cache_len,
                                           cache_dtype)
        self._d_family = d_family
        d_codec = codec_for_cache(self.d_cache)
        t_codec = codec_for_cache(self.cache)
        t_family = self.family

        # per-slot draft-sync chunk: the previous verify block + its start
        self.prev_chunk = jnp.zeros((self.slots, k + 1), jnp.int32)
        self.prev_pos = jnp.zeros((self.slots,), jnp.int32)
        # acceptance telemetry
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

        greedy = self._greedy
        temperature, top_k = self._temperature, self._top_k_opt

        def _spec_core(t_prepared, d_prepared, t_cache, d_cache, tok, pos,
                       active, keys, prev_chunk, prev_pos):
            b = tok.shape[0]
            # 1. draft sync (write-only; logits discarded)
            _, d_cache = d_family.verify_rows(
                d_prepared, d_cache, prev_chunk, prev_pos, active, d_codec)

            # 2. k draft proposal steps
            def d_step(carry, i):
                cache, last, kk = carry
                logits, cache = d_family.decode_rows(
                    d_prepared, cache, last, pos + i, active, d_codec)
                if greedy:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    rows = jnp.zeros((b, 1), jnp.float32)  # placeholder
                    new_k = kk
                else:
                    split = jax.vmap(jax.random.split)(kk)
                    new_k, subs = split[:, 0], split[:, 1]
                    rows = _probs(logits, temperature=temperature,
                                  top_k=top_k)  # (B, V)
                    nxt = jax.vmap(
                        lambda r, s: jax.random.categorical(s, jnp.log(r))
                    )(rows, subs).astype(jnp.int32)
                nxt = jnp.where(active, nxt, last)
                return (cache, nxt, new_k), (nxt, rows)

            (d_cache, _, keys), (props_t, d_rows_t) = lax.scan(
                d_step, (d_cache, tok, keys), jnp.arange(k))
            props = jnp.moveaxis(props_t, 0, 1)      # (B, k)
            d_rows = jnp.moveaxis(d_rows_t, 0, 1)    # (B, k, V) or (B,k,1)

            # 3. target verify over [last, p1..pk]
            chunk = jnp.concatenate([tok[:, None], props], axis=1)
            t_logits, t_cache = t_family.verify_rows(
                t_prepared, t_cache, chunk, pos, active, t_codec)
            rows = t_logits  # (B, k+1, V); row i predicts pos+i+1

            if greedy:
                t_toks = jnp.argmax(rows, axis=-1).astype(jnp.int32)
                match = props == t_toks[:, :k]
                m = jnp.where(match.all(axis=1), k,
                              jnp.argmax(~match, axis=1)).astype(jnp.int32)
                w = t_toks  # (B, k+1): committed tokens ARE target picks
            else:
                split = jax.vmap(lambda kk: jax.random.split(kk, 3))(keys)
                keys, r_acc, r_rep = split[:, 0], split[:, 1], split[:, 2]
                t_dist = _probs(rows, temperature=temperature, top_k=top_k)
                idx = jnp.arange(k)
                t_probs = jnp.take_along_axis(
                    t_dist[:, :k], props[:, :, None], axis=2)[..., 0]
                d_probs = jnp.take_along_axis(
                    d_rows, props[:, :, None], axis=2)[..., 0]
                ratio = t_probs / jnp.maximum(d_probs, 1e-30)
                u = jax.vmap(lambda r: jax.random.uniform(r, (k,)))(r_acc)
                accept = u < jnp.minimum(ratio, 1.0)  # (B, k)
                m = jnp.where(accept.all(axis=1), k,
                              jnp.argmax(~accept, axis=1)).astype(jnp.int32)
                d_row_m = jnp.where(
                    (m < k)[:, None],
                    jnp.take_along_axis(
                        d_rows, jnp.minimum(m, k - 1)[:, None, None],
                        axis=1)[:, 0],
                    jnp.zeros_like(d_rows[:, 0]))
                t_row_m = jnp.take_along_axis(
                    t_dist, m[:, None, None], axis=1)[:, 0]
                resid = jnp.maximum(t_row_m - d_row_m, 0.0)
                z = resid.sum(axis=-1, keepdims=True)
                resid = jnp.where(z > 0, resid / jnp.maximum(z, 1e-30),
                                  t_row_m)
                rep = jax.vmap(
                    lambda r, s: jax.random.categorical(s, jnp.log(r))
                )(resid, r_rep).astype(jnp.int32)
                props_ext = jnp.concatenate(
                    [props, jnp.zeros((b, 1), jnp.int32)], axis=1)
                w = jnp.where(jnp.arange(k + 1)[None, :] == m[:, None],
                              rep[:, None], props_ext)

            committed = jnp.where(active, m + 1, 0)
            last = jnp.take_along_axis(w, m[:, None], axis=1)[:, 0]
            last = jnp.where(active, last, tok)
            new_prev_chunk = jnp.where(active[:, None], chunk, prev_chunk)
            new_prev_pos = jnp.where(active, pos, prev_pos)
            return (t_cache, d_cache, last, pos + committed, keys,
                    new_prev_chunk, new_prev_pos, w, m)

        def spec_step(t_prepared, d_prepared, t_cache, d_cache, tok, pos,
                      active, keys, prev_chunk, prev_pos):
            return _spec_core(t_prepared, d_prepared, t_cache, d_cache,
                              tok, pos, active, keys, prev_chunk,
                              prev_pos)

        # donate BOTH caches and every per-slot vector the step returns
        # (tok, pos, keys, prev_chunk, prev_pos) — `active` is read-only
        # through the step and host-updated between calls, so it stays
        # undonated. Aliasing coverage is asserted by the analysis gate
        # (analysis/program.audit_serving_decode).
        self._spec_step = jax.jit(spec_step,
                                  donate_argnums=(2, 3, 4, 5, 7, 8, 9))

        # interleaved chunked prefill (ISSUE 12), speculative shape: the
        # spec step program grows BOTH prefill legs — one target chunk
        # and one draft chunk for the admitting request fold into the
        # same compiled program as every active slot's draft/verify
        # round, and the fused finish installs both rows, samples the
        # first token on device, and seeds the draft-sync state
        # (prev_chunk/prev_pos) in one dispatch.
        self._spec_mixed = None
        self._spec_ilv_finish = None
        if self._ilv:
            def spec_mixed(t_prepared, d_prepared, t_cache, d_cache,
                           tok, pos, active, keys, prev_chunk, prev_pos,
                           row, d_row, chunk, chunk_start):
                out = _spec_core(t_prepared, d_prepared, t_cache,
                                 d_cache, tok, pos, active, keys,
                                 prev_chunk, prev_pos)
                pf_logits, new_row = t_family.prefill(
                    t_prepared, chunk, row, chunk_start)
                _, new_d_row = d_family.prefill(
                    d_prepared, chunk, d_row, chunk_start)
                return out + (pf_logits, new_row, new_d_row)

            self._spec_mixed_donate = (2, 3, 4, 5, 7, 8, 9, 10, 11)
            self._spec_mixed = jax.jit(
                spec_mixed, donate_argnums=self._spec_mixed_donate)

            parent_fin = self._ilv_finish_core
            kk1 = k + 1

            def spec_ilv_finish(cache, d_cache, row, d_row, logits,
                                last_local, slot, rng, slot_key, pos,
                                tok, active, keys, temp_v, tk_v, tp_v,
                                mp_v, rep_v, seen, bias_buf, t, kk_, p,
                                mp_, rp, seen_row, b_row, prompt_len,
                                install_ids, crow, c_row, ctable,
                                ctrans, tail, prev_chunk, prev_pos):
                out = parent_fin(cache, row, logits, last_local, slot,
                                 rng, slot_key, pos, tok, active, keys,
                                 temp_v, tk_v, tp_v, mp_v, rep_v, seen,
                                 bias_buf, t, kk_, p, mp_, rp, seen_row,
                                 b_row, prompt_len, install_ids, crow,
                                 c_row, ctable, ctrans)
                # draft-row install: the one shared clamped install
                # (serving.install_dense_row)
                d_cache = install_dense_row(d_cache, d_row, slot)
                # first sync chunk: the prompt's own tail at its own
                # positions — an exact no-op re-feed
                prev_chunk = prev_chunk.at[slot].set(tail)
                prev_pos = prev_pos.at[slot].set(prompt_len - kk1)
                return out + (d_cache, prev_chunk, prev_pos)

            # the spec batcher never enables constraints
            # (_constraints_ok=False), so the parent core passes crow
            # through untouched — not donated (args 29-32 are the
            # constraint tail, all placeholders here)
            donate = [0, 1, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
                      34, 35]
            if self._allow_bias:
                donate.append(19)
            self._spec_ilv_finish_donate = tuple(sorted(donate))
            self._spec_ilv_finish = jax.jit(
                spec_ilv_finish,
                donate_argnums=self._spec_ilv_finish_donate)

        # draft-side chunked prefill (the target side reuses the parent's
        # programs); the install is the parent's dense slice-install
        # shape, clamped at the CACHE's current position count (the
        # bucketed draft pool may sit below max_len — the row's overhang
        # holds nothing but tail-pad garbage, exactly as in
        # serving.prefill_finish)
        def d_prefill_chunk(prepared, row, chunk, chunk_start):
            return d_family.prefill(prepared, chunk, row, chunk_start)

        def d_install(cache, row, slot):
            return install_dense_row(cache, row, slot)

        self._d_prefill_chunk = jax.jit(d_prefill_chunk,
                                        donate_argnums=(1,))
        # the row (arg 1) is sliced, never returned whole — donating it
        # would alias nothing (serving.py's prefill_finish lesson)
        self._d_install = jax.jit(d_install, donate_argnums=(0,))

    # ------------------------------------------------------------------

    def _ensure_cache_len(self, need: int):
        """Bucketed growth with the spec path's scratch headroom: the
        verify/propose chunk writes up to spec_k positions past the last
        committed token, so every grow covers `need + k` — and the DRAFT
        pool grows in lockstep (both sides' chunks write the same
        positions). The submit budget check (prompt + max_new + k <=
        max_len) guarantees the padded need never exceeds the ladder
        top."""
        if self._buckets is None:
            return
        super()._ensure_cache_len(min(need + self.spec_k, self.max_len))
        d_len = jax.tree.leaves(self.d_cache)[0].shape[3]
        if d_len < self._cache_len:
            self.d_cache = self._grow_cache(self.d_cache, self._cache_len)

    def jit_programs(self):
        """Parent programs plus the spec path's own — a speculative
        daemon's compile-cache budget must count the programs it
        actually churns (_d_prefill_chunk recompiles per prompt-length
        bucket, exactly like the parent's chunk program)."""
        fns = super().jit_programs() + [
            self._spec_step, self._d_prefill_chunk, self._d_install]
        if self._spec_mixed is not None:
            fns += [self._spec_mixed, self._spec_ilv_finish]
        return fns

    def submit(self, prompt, max_new_tokens: int,
               seed: Optional[int] = None, **opts) -> int:
        for bad in ("temperature", "top_k", "top_p", "min_p",
                    "repetition_penalty", "logit_bias", "logprobs"):
            # explicit-None check: temperature=0.0 / top_k=0 are real
            # overrides and must be rejected too, not slip past truthiness
            # — but an EMPTY logit_bias dict is a no-op everywhere else
            # and must not hard-fail only here
            v = opts.get(bad)
            if v is None or v is False or (isinstance(v, dict) and not v):
                continue
            raise ValueError(
                "SpeculativeBatcher uses the server-level sampling "
                f"configuration; per-request {bad}= is the dense "
                "batcher's feature")
        if opts.get("prefilled") is not None:
            # KV adoption (dnn_tpu/control) would install the TARGET
            # cache only — the draft cache would never see the prompt
            # and every verify chunk would diverge
            raise ValueError(
                "prefilled= (disaggregated KV adoption) does not "
                "compose with speculative serving: the draft cache "
                "needs its own prompt prefill")
        prompt_arr = np.asarray(prompt, np.int32).reshape(-1)
        k = self.spec_k
        if len(prompt_arr) < k + 1:
            raise ValueError(
                f"prompt length {len(prompt_arr)} < spec_k+1 ({k + 1}) — "
                "the first draft-sync chunk re-feeds the prompt tail")
        if len(prompt_arr) + max_new_tokens + k > self.max_len:
            raise ValueError(
                f"prompt {len(prompt_arr)} + max_new {max_new_tokens} + "
                f"spec_k {k} exceeds max_len {self.max_len} (the verify "
                "chunk writes up to k scratch positions)")
        rid = super().submit(prompt_arr, max_new_tokens, seed=seed, **opts)
        # slot the parent picked; a budget-1 request already retired at
        # submit (the prefill-sampled token was its whole budget) and
        # needs no draft state at all
        slot = next((i for i, r in enumerate(self._slot_req)
                     if r is not None and r["rid"] == rid), None)
        if slot is None:
            return rid
        if self._ilv:
            # interleaved admission: the parent enqueued the pending
            # prefill; attach the draft side — its transient row (grown
            # chunk-by-chunk in lockstep through spec_mixed) and the
            # prompt tail the fused finish seeds prev_chunk with
            p = self._slot_req[slot].get("pending")
            if p is not None:
                p["d_row"] = self._d_family.init_cache(
                    1, self._ilv_row_len, self.d_cache["k"].dtype)
                p["tail"] = jnp.asarray(prompt_arr[-(k + 1):])
            return rid
        # draft prefill: same chunk loop as the parent, through the draft
        p_pad = self.prompt_pad
        n_chunks = -(-len(prompt_arr) // p_pad)
        padded = np.zeros((1, n_chunks * p_pad), np.int32)
        padded[0, : len(prompt_arr)] = prompt_arr
        d_row = self._d_family.init_cache(
            1, self._row_len, self.d_cache["k"].dtype)
        for c in range(n_chunks):
            _, d_row = self._d_prefill_chunk(
                self.draft_prepared, d_row,
                jnp.asarray(padded[:, c * p_pad:(c + 1) * p_pad]),
                jnp.int32(c * p_pad))
        self.d_cache = self._d_install(self.d_cache, d_row, slot)
        # first sync chunk: the prompt's own tail at its own positions —
        # an exact no-op re-feed
        tail = prompt_arr[-(k + 1):]
        self.prev_chunk = self.prev_chunk.at[slot].set(jnp.asarray(tail))
        self.prev_pos = self.prev_pos.at[slot].set(
            len(prompt_arr) - (k + 1))
        return rid

    def _ilv_after_chunk(self, ilv, pf_logits, rows, s_idx):
        """Speculative override of the interleave bookkeeping: `rows`
        is the (target row, draft row) pair the spec mixed program
        returned; the final chunk dispatches the fused finish that
        installs BOTH rows, samples the first token on device, and
        seeds the draft-sync state."""
        req, p, slot = ilv["req"], ilv["p"], ilv["slot"]
        new_row, new_d_row = rows
        self.prefill_chunks_run += 1
        m = obs.metrics()
        if m is not None:
            m.inc("serving.prefill_chunks_total")
        if not ilv["last"]:
            p["row"], p["d_row"] = new_row, new_d_row
            p["next"] += 1
            return
        self._pending_q.pop(0)
        fin = self._spec_ilv_finish(
            self.cache, self.d_cache, new_row, new_d_row, pf_logits,
            jnp.int32(p["last_local"]), jnp.int32(slot),
            p["prefill_key"], p["slot_key"],
            self.pos, self.tok, self.active, self.keys,
            self._temp, self._topk, self._topp, self._minp, self._rep,
            self._seen, self._bias,
            jnp.float32(p["t"]), jnp.int32(p["k"]), jnp.float32(p["p"]),
            jnp.float32(p["mp"]), jnp.float32(p["rp"]),
            p["seen_row"], p["b_row"], jnp.int32(req["prompt_len"]),
            p["install_ids"], self._crow, jnp.int32(0),
            self._ctable, self._ctrans,
            p["tail"], self.prev_chunk, self.prev_pos)
        (self.cache, self.pos, self.tok, self.active, self.keys,
         self._temp, self._topk, self._topp, self._minp, self._rep,
         self._seen, self._bias, self._crow, first) = fin[:14]
        # the parent core appends logprob outputs only when logprobs_k
        # is compiled in — the spec batcher bans it, so the tail is
        # exactly (d_cache, prev_chunk, prev_pos)
        self.d_cache, self.prev_chunk, self.prev_pos = fin[14:]
        req["first_dev"] = (first, None)
        req["install_step"] = s_idx
        del req["pending"]

    def _commit_spec(self, s_idx, w_np, m_np, rec, sc):
        """Commit one completed speculative step (the chunk block `w`
        and acceptance counts `m`), with the same install gating as the
        dense _commit_step: slots whose fused finish landed at
        install_step >= s_idx had no verify leg in that dispatch."""
        self.spec_steps += 1
        obs_m = obs.metrics()
        t_now = time.perf_counter() if obs_m is not None else 0.0
        n_adv = 0
        it_samples: list = []
        out = {}
        for slot, req in enumerate(self._slot_req):
            if req is None or req.get("pending") is not None:
                continue
            inst = req.get("install_step")
            emitted = []
            if inst is not None:
                if s_idx <= inst:
                    continue
                del req["install_step"]
                fd = req.pop("first_dev", None)
                if fd is not None:
                    tok0 = int(np.asarray(fd[0]))
                    req["emitted"].append(tok0)
                    emitted.append(tok0)
                    if obs_m is not None \
                            and (g := self.goodput) is not None:
                        g.on_prefill(req["prompt_len"])
                    self._retire_if_done(slot)
            if self._slot_req[slot] is req:
                n_commit = int(m_np[slot]) + 1
                self.spec_proposed += self.spec_k
                self.spec_accepted += int(m_np[slot])
                for t in [int(x) for x in w_np[slot, :n_commit]]:
                    req["emitted"].append(t)
                    emitted.append(t)
                    self._retire_if_done(slot)
                    if self._slot_req[slot] is None:
                        break  # budget/stop/eos mid-chunk: rest discarded
            if not emitted:
                continue
            # shared obs bookkeeping (serving.ContinuousBatcher helpers):
            # the inter-token gap spreads over the committed chunk; the
            # decode span closes at retire like the dense path. Skipped
            # for a request that retired mid-chunk — its span is already
            # closed and must not reopen on a dead slot.
            n_adv += len(emitted)
            if self._slot_req[slot] is req:
                self._obs_commit(req, obs_m, t_now, n_new=len(emitted),
                                 samples=it_samples)
            out[req["rid"]] = emitted
        if rec is not None:
            rec.marks.append(("commit", time.perf_counter()))
        self._obs_step_end(obs_m, n_adv, it_samples)
        if rec is not None:
            rec.marks.append(("obs", time.perf_counter()))
            sc.end(rec, n_adv)
        return out

    def flush_overlap(self):
        """Speculative flush: the inflight struct holds the chunk block
        and acceptance counts (never donated by later dispatches, so
        bare refs suffice — no copy needed)."""
        if self._inflight is None:
            return {}
        sc = self.step_clock
        rec = sc.begin() if sc is not None else None
        s_idx, w_ref, m_ref = self._inflight
        self._inflight = None
        w_np, m_np = np.asarray(w_ref), np.asarray(m_ref)
        if rec is not None:
            rec.marks.append(("wait", time.perf_counter()))
        return self._commit_spec(s_idx, w_np, m_np, rec, sc)

    def step(self):
        """One speculative step: every active slot advances by its own
        1..k+1 committed tokens. Returns {rid: [tokens...]}. Interleave
        and overlap compose exactly as in the dense step: a pending
        admission's chunk folds into the spec program, and overlap=True
        dispatches step N while committing step N-1."""
        if self.n_active == 0:
            return self.flush_overlap()
        # step-timeline clock: same phase protocol as the dense step
        # (serving.ContinuousBatcher.step) — one speculative step's
        # "wait" is the draft+verify chunk's device->host sync
        sc = self.step_clock
        rec = sc.begin() if sc is not None else None
        if self._buckets is not None:
            # this step verifies at pos..pos+k for every active slot
            # (pos = prompt_len + emitted - 1); _ensure_cache_len adds
            # the +k scratch itself and grows the draft pool in
            # lockstep. Host-uncommitted tokens count too — a deferred
            # first, plus up to k+1 per in-flight step under overlap
            # (the shared _uncommitted_need accounting).
            need = self._uncommitted_need(self.spec_k + 1)
            if need:
                self._ensure_cache_len(need)
        ilv = self._ilv_next() if self._ilv else None
        if rec is not None:
            rec.marks.append(("host", time.perf_counter()))
        if ilv is None:
            (self.cache, self.d_cache, self.tok, self.pos, self.keys,
             self.prev_chunk, self.prev_pos, w, m) = self._spec_step(
                self.prepared, self.draft_prepared, self.cache,
                self.d_cache, self.tok, self.pos, self.active,
                self.keys, self.prev_chunk, self.prev_pos)
        else:
            p = ilv["p"]
            (self.cache, self.d_cache, self.tok, self.pos, self.keys,
             self.prev_chunk, self.prev_pos, w, m, pf_logits, new_row,
             new_d_row) = self._spec_mixed(
                self.prepared, self.draft_prepared, self.cache,
                self.d_cache, self.tok, self.pos, self.active,
                self.keys, self.prev_chunk, self.prev_pos,
                p["row"], p["d_row"], ilv["chunk"], ilv["start"])
        if rec is not None:
            rec.marks.append(("dispatch", time.perf_counter()))
            rec.mixed = ilv is not None
        s_idx = self._step_idx
        self._step_idx += 1
        if ilv is not None:
            self._ilv_after_chunk(ilv, pf_logits, (new_row, new_d_row),
                                  s_idx)
        if self._overlap:
            if sc is not None:
                sc.overlap_depth = 1
            keep = (s_idx, w, m)
            prev, self._inflight = self._inflight, keep
            if prev is None:
                return self._pipeline_fill_end(rec, sc)
            s_prev, w_prev, m_prev = prev
            w_np, m_np = np.asarray(w_prev), np.asarray(m_prev)
            if rec is not None:
                rec.marks.append(("wait", time.perf_counter()))
            return self._commit_spec(s_prev, w_np, m_np, rec, sc)
        w_np, m_np = np.asarray(w), np.asarray(m)
        if rec is not None:
            rec.marks.append(("wait", time.perf_counter()))
        return self._commit_spec(s_idx, w_np, m_np, rec, sc)
