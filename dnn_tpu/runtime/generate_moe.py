"""KV-cache generation for the MoE GPT family (dense-routed and
expert-parallel).

The reference has neither MoE nor any decode loop (SURVEY.md §2 "no MoE
modules exist"; §5 "no KV-cache even" — /root/reference/node.py:137-200 is
one stateless forward). This module closes the round-2 gap where
`gpt_moe` could train and forward but not serve: it reuses the dense
family's cached-attention machinery (dnn_tpu/runtime/generate.py) and
swaps the block MLP for the routed MoE FFN (dnn_tpu/parallel/moe.py).

Routing granularity during decode: the MoE FFN routes over whatever
tokens a forward sees. Prefill routes the whole prompt as one group
(identical to the stateless forward at batch 1); each decode step routes
the B current tokens. Per-token top-k routing is batch-independent as
long as no token is dropped for capacity, so decode output matches the
full-sequence forward exactly whenever capacity is not exceeded — the
contract `tests/test_generate_moe.py` pins with a generous
capacity_factor. (Capacity drops are batch-dependent by construction in
any capacity-based MoE; that caveat is inherent, not an artifact of the
cache.)

Expert-parallel decode (`make_generate_moe_ep`) runs the WHOLE generate —
prefill + `lax.scan` decode — as one shard_map program on the expert
mesh axis: batch shards over the axis (each device's local batch is its
routing group, so the local KV cache lives with the tokens it serves),
expert weights shard on their leading E axis, and tokens travel to their
experts via `jax.lax.all_to_all` per step. Greedy EP decode equals the
dense path with groups == axis size token-for-token; sampled EP decode
folds the device index into the rng stream (per-device local sampling),
so it matches the dense path in distribution, not draw-for-draw.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dnn_tpu.models.gpt import head
from dnn_tpu.models.gpt_moe import GPTMoEConfig
from dnn_tpu.parallel.mesh import EXPERT_AXIS
from dnn_tpu.parallel.moe import moe_capacity, moe_ffn, moe_ffn_local
from dnn_tpu.runtime.generate import (
    _embed_at,
    _sample,
    forward_with_cache,
    init_cache,
    make_generate,
)

__all__ = [
    "moe_cache_ffn",
    "forward_with_cache_moe",
    "make_generate_moe",
    "make_generate_moe_ep",
    "make_pipeline_generate_moe",
    "make_pipeline_generate_moe_ep",
]


def moe_cache_ffn(cfg: GPTMoEConfig, *, groups: int = 1, compute_dtype=None):
    """The `ffn(bp, h)` hook that turns any dense cached decoder
    (forward_with_cache / make_generate / ContinuousBatcher) into its MoE
    counterpart: routes h's tokens through bp["moe"] in `groups` groups."""

    def ffn(bp, h):
        return moe_ffn(
            bp["moe"], h, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor, groups=groups,
            compute_dtype=compute_dtype,
        )

    return ffn


def forward_with_cache_moe(prepared, ids, cache, start_pos, *,
                           cfg: GPTMoEConfig, compute_dtype=None,
                           groups: int = 1):
    """MoE analog of generate.forward_with_cache: ids (B, T) at positions
    [start_pos, start_pos+T), routed in `groups` groups per layer."""
    return forward_with_cache(
        prepared, ids, cache, start_pos, cfg=cfg,
        compute_dtype=compute_dtype,
        ffn=moe_cache_ffn(cfg, groups=groups, compute_dtype=compute_dtype),
    )


def make_generate_moe(cfg: GPTMoEConfig, *, max_new_tokens: int,
                      temperature: float = 0.0,
                      sample_top_k: Optional[int] = None,
                      sample_top_p: Optional[float] = None,
                      compute_dtype=None, groups: int = 1):
    """Jitted generate(prepared, ids, rng) for the MoE family — the dense
    family's make_generate with the routed FFN plugged in. `sample_top_k`
    is the SAMPLING truncation (cfg.top_k is the ROUTING fan-out)."""
    return make_generate(
        cfg, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=sample_top_k, top_p=sample_top_p, compute_dtype=compute_dtype,
        ffn=moe_cache_ffn(cfg, groups=groups, compute_dtype=compute_dtype),
    )


def make_pipeline_generate_moe(cfg: GPTMoEConfig, mesh, *,
                               max_new_tokens: int,
                               temperature: float = 0.0,
                               sample_top_k: Optional[int] = None,
                               compute_dtype=None, groups: int = 1,
                               axis_name=None, kv_dtype=None):
    """Pipeline-parallel MoE decode over the STAGE axis: each stage holds
    its block stack (attention + its layers' full expert sets) and its
    cache shard; the hidden state rides the ppermute ring per token with
    the routed FFN plugged into the cached block. Experts are NOT sharded
    here — this is PP x dense-MoE (per-stage expert replication); for
    experts sharded within each stage use make_pipeline_generate_moe_ep.
    Token-parity vs make_generate_moe on the same grouping."""
    from dnn_tpu.runtime.generate import (
        GPTPipelineFamily,
        make_pipeline_generate,
    )

    fam = GPTPipelineFamily(
        cfg, compute_dtype=compute_dtype, kv_dtype=kv_dtype,
        ffn=moe_cache_ffn(cfg, groups=groups, compute_dtype=compute_dtype))
    return make_pipeline_generate(
        cfg, mesh, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=sample_top_k, axis_name=axis_name, family=fam)


def make_pipeline_generate_moe_ep(cfg: GPTMoEConfig, mesh, *,
                                  max_new_tokens: int,
                                  temperature: float = 0.0,
                                  sample_top_k: Optional[int] = None,
                                  sample_top_p: Optional[float] = None,
                                  compute_dtype=None,
                                  stage_axis: Optional[str] = None,
                                  expert_axis: str = EXPERT_AXIS):
    """EP x PP 2D MoE decode: layers shard over the STAGE axis (the
    ppermute decode ring) while each stage's experts shard over the
    EXPERT axis — the 2D composition the dense-expert pipeline decoder
    leaves out.

    Mesh {stage: S, expert: n}: the batch and its KV cache shard over the
    expert axis (each expert column is a routing group, exactly the EP
    forward's layout), each stage column holds 1/S of the layers with 1/n
    of every layer's experts, tokens reach their experts via all_to_all
    WITHIN the stage row while the hidden state rides the stage ring —
    both collectives per decode step, each on its own mesh axis.

    generate(stage_blocks, aux, ids, rng): `stage_blocks` from
    prepare_pipeline_stacked (this function re-places the expert leaves
    over the expert axis); ids (B, T), B divisible by the expert axis.
    Greedy output equals make_generate_moe(groups=n) token-for-token
    (same per-column routing groups, same stage math).

    NOTE on the deliberate duplication: the stage-ring schedule below
    mirrors generate.make_pipeline_generate's (where-gated cache merge,
    ppermute hop, stage-0 psum token broadcast). It cannot ride that
    builder's family adapter because the EP FFN is capacity-dependent —
    a DIFFERENT compiled ffn for the prefill chunk vs the decode step —
    while the adapter protocol fixes one block function; and the 2D
    specs shard the batch/rng over a second axis the generic builder
    doesn't model. If the ring schedule in generate.py changes, change
    it here too (both are pinned by token-parity tests against the solo
    decoders, which is what actually catches drift).
    """
    from jax.sharding import NamedSharding

    from dnn_tpu.parallel.mesh import STAGE_AXIS
    from dnn_tpu.runtime.generate import _block_with_cache

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    s_axis = stage_axis or STAGE_AXIS
    num_stages = mesh.shape[s_axis]
    n_exp = mesh.shape[expert_axis]
    if cfg.n_layer % num_stages:
        raise ValueError(
            f"n_layer {cfg.n_layer} not divisible by {num_stages} stages")
    if cfg.n_experts % n_exp:
        raise ValueError(
            f"n_experts {cfg.n_experts} not divisible by expert axis {n_exp}")
    per_stage = cfg.n_layer // num_stages
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    # stage_blocks leaves carry (S, per_stage, ...); MoE expert stacks add
    # their E axis right after -> P(stage, None, expert); router + dense
    # block leaves replicate across expert columns
    def _spec(path, leaf):
        keys = [getattr(p, "key", None) for p in path]
        if "moe" in keys and "router" not in keys:
            return P(s_axis, None, expert_axis)
        return P(s_axis)

    def _place(stage_blocks):
        specs = jax.tree_util.tree_map_with_path(_spec, stage_blocks)
        return jax.device_put(
            stage_blocks,
            jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                         is_leaf=lambda x: isinstance(x, P)),
        ), specs

    def per_device(stage_blocks, aux, ids_local, rng):
        local = jax.tree.map(lambda p: p[0], stage_blocks)  # (per, ...)
        d = lax.axis_index(s_axis)
        b, t = ids_local.shape  # local batch = this expert column's group
        s_max = t + max_new_tokens
        cache = init_cache(
            _stage_cfg(cfg, per_stage), b, s_max,
            compute_dtype or jnp.float32)

        def ffn_for(tokens_per_group):
            capacity = moe_capacity(tokens_per_group, cfg.n_experts,
                                    cfg.top_k, cfg.capacity_factor)

            def ffn(bp, h):
                dd = h.shape[-1]
                return moe_ffn_local(
                    bp["moe"], h.reshape(-1, dd), top_k=cfg.top_k,
                    capacity=capacity, axis_name=expert_axis,
                    compute_dtype=compute_dtype,
                ).reshape(h.shape)

            return ffn

        def ring_pass(x, cache, start_pos, ffn):
            def sub(carry, s):
                h, cache = carry

                def layer(carry2, layer_in):
                    bp, layer_cache = layer_in
                    return _block_with_cache(
                        bp, carry2, layer_cache, start_pos, cfg=cfg,
                        compute_dtype=compute_dtype, ffn=ffn)

                h2, cache2 = lax.scan(layer, h, (local, cache))
                active = d == s
                cache = jax.tree.map(
                    lambda new, old: jnp.where(active, new, old),
                    cache2, cache)
                h = lax.ppermute(h2, s_axis, perm)
                return (h, cache), None

            (h, cache), _ = lax.scan(sub, (x, cache), jnp.arange(num_stages))
            return h, cache

        def sample_last(h, sub_rng):
            logits = head(aux, h[:, -1:].astype(jnp.float32), cfg=cfg,
                          compute_dtype=compute_dtype)
            tok = _sample(logits[:, -1], sub_rng, temperature=temperature,
                          top_k=sample_top_k, top_p=sample_top_p)
            return lax.psum(
                jnp.where(d == 0, tok, jnp.zeros_like(tok)), s_axis)

        rng = jax.random.fold_in(rng, lax.axis_index(expert_axis))
        x = _embed_at(aux, ids_local, 0, compute_dtype=compute_dtype)
        h, cache = ring_pass(x, cache, 0, ffn_for(b * t))
        rng, sub = jax.random.split(rng)
        tok = sample_last(h, sub)
        step_ffn = ffn_for(b)

        def step(carry, i):
            cache, tok, rng = carry
            x = _embed_at(aux, tok[:, None], t + i,
                          compute_dtype=compute_dtype)
            h, cache = ring_pass(x, cache, t + i, step_ffn)
            rng, sub = jax.random.split(rng)
            nxt = sample_last(h, sub)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    compiled = {}  # one jitted program per param-tree structure; repeat
    # calls with the same shapes reuse it (the make_* builder contract)

    def generate(stage_blocks, aux, ids, rng):
        b, t = ids.shape
        if b % n_exp:
            raise ValueError(
                f"batch {b} not divisible by expert-axis size {n_exp}")
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        # device_put is a no-op for already-correctly-placed arrays, so
        # long-lived callers that keep the returned placement pay it once
        placed, specs = _place(stage_blocks)
        key = jax.tree_util.tree_structure(stage_blocks)
        if key not in compiled:
            compiled[key] = jax.jit(jax.shard_map(
                per_device, mesh=mesh,
                in_specs=(specs, P(), P(expert_axis), P()),
                out_specs=P(expert_axis),
                check_vma=False,
            ))
        return compiled[key](placed, aux, ids, rng)

    return generate


def _stage_cfg(cfg, per_stage):
    import dataclasses

    return dataclasses.replace(cfg, n_layer=per_stage)


def make_generate_moe_ep(cfg: GPTMoEConfig, mesh, *, max_new_tokens: int,
                         temperature: float = 0.0,
                         sample_top_k: Optional[int] = None,
                         compute_dtype=None, axis_name: str = EXPERT_AXIS):
    """Expert-parallel KV-cache generation over `mesh`'s expert axis.

    generate(prepared, ids, rng): ids (B, T), B divisible by the axis
    size. Batch and KV cache shard over the axis; expert weights shard on
    E; tokens reach their experts via all_to_all inside every prefill and
    decode-step forward. Greedy output equals
    make_generate_moe(groups=axis_size) token-for-token.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    n = mesh.shape[axis_name]
    if cfg.n_experts % n:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by axis size {n}")

    moe_spec = {"router": {"kernel": P()},
                "wi": P(None, axis_name), "bi": P(None, axis_name),
                "wo": P(None, axis_name), "bo": P(None, axis_name)}
    param_specs = {
        "wte": {"embedding": P()}, "wpe": {"embedding": P()},
        "ln_f": {"scale": P(), "bias": P()}, "lm_head": {"kernel": P()},
        "blocks": {
            "ln_1": {"scale": P(), "bias": P()},
            "attn": {"qkv": {"kernel": P(), "bias": P()},
                     "proj": {"kernel": P(), "bias": P()}},
            "ln_2": {"scale": P(), "bias": P()},
            "moe": moe_spec,
        },
    }

    def per_device(prep_local, ids_local, rng):
        b, t = ids_local.shape  # local batch = this device's routing group
        s_max = t + max_new_tokens
        cache = init_cache(cfg, b, s_max, compute_dtype or jnp.float32)

        def ffn_for(tokens_per_group):
            capacity = moe_capacity(
                tokens_per_group, cfg.n_experts, cfg.top_k,
                cfg.capacity_factor)

            def ffn(bp, h):
                d = h.shape[-1]
                return moe_ffn_local(
                    bp["moe"], h.reshape(-1, d), top_k=cfg.top_k,
                    capacity=capacity, axis_name=axis_name,
                    compute_dtype=compute_dtype,
                ).reshape(h.shape)

            return ffn

        logits, cache = forward_with_cache(
            prep_local, ids_local, cache, 0, cfg=cfg,
            compute_dtype=compute_dtype, ffn=ffn_for(b * t),
            attn_kernel=False)  # inside shard_map: keep the einsum
        # per-device stream: local rows sample locally (greedy ignores rng)
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature=temperature,
                      top_k=sample_top_k)

        step_ffn = ffn_for(b)

        def step(carry, i):
            cache, tok, rng = carry
            logits, cache = forward_with_cache(
                prep_local, tok[:, None], cache, t + i, cfg=cfg,
                compute_dtype=compute_dtype, ffn=step_ffn,
                attn_kernel=False)
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature=temperature,
                          top_k=sample_top_k)
            return (cache, nxt, rng), tok

        (_, last, _), toks = lax.scan(
            step, (cache, tok, rng), jnp.arange(max_new_tokens - 1))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([toks, last[:, None]], axis=1)

    @jax.jit
    def generate(prepared, ids, rng):
        b, t = ids.shape
        if b % n:
            raise ValueError(f"batch {b} not divisible by expert-axis size {n}")
        if t + max_new_tokens > cfg.block_size:
            raise ValueError(
                f"prompt {t} + max_new_tokens {max_new_tokens} exceeds "
                f"block_size {cfg.block_size}")
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(param_specs, P(axis_name), P()),
            out_specs=P(axis_name),
            check_vma=False,
        )(prepared, ids, rng)

    return generate
