"""Embedding extraction: pooled hidden states as a serving product.

The reference can only argmax-classify (node.py:186-192); a modern
serving stack also EXPORTS representations — retrieval, clustering,
reranking all consume the final hidden state rather than logits. This
module is that endpoint's compute: the model's stacked forward minus the
lm_head, normed and pooled.

Design notes:
  * `hidden == HF last_hidden_state`: both GPT-2 and the LLaMA family
    apply their final norm at the top of the stack (transformers
    GPT2Model.ln_f / LlamaModel.norm), so parity tests compare directly
    (tests/test_embeddings.py).
  * Padding is FREE under causal attention: pad tokens sit after the
    real ones and real positions never attend forward, so hidden states
    of real tokens are pad-invariant; pooling masks with the true
    `lengths`. This is what lets the daemon pad prompts up to a chunk
    multiple and reuse ONE compiled program per padded length.
  * Pooling: "mean" (masked average — the standard sentence-embedding
    choice), "last" (final real token — decoder-LM convention), "none"
    (the full (B, T, C) hidden sequence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["make_embed"]


def _hidden_fn(cfg, compute_dtype):
    """Family dispatch on the config type (LLaMA-family configs are
    LlamaConfig instances; GPT configs are GPTConfig). Each family OWNS
    its hidden-state forward (make_hidden_stacked, defined next to its
    logits forward so the two cannot drift)."""
    from dnn_tpu.models import gpt, llama

    family = llama if isinstance(cfg, llama.LlamaConfig) else gpt
    return family.make_hidden_stacked(cfg, compute_dtype=compute_dtype)


def make_embed(cfg, *, pooling: str = "mean", compute_dtype=None):
    """Jitted `embed(prepared, ids, lengths) -> (B, C) f32` (or
    (B, T, C) for pooling="none").

    `ids` (B, T) may be padded past each row's true length; `lengths`
    (B,) marks the real extents — pad content is irrelevant (causal
    attention; see module docstring). Works for any registered GPT- or
    LLaMA-family config, Gemma's alternating windows included."""
    if pooling not in ("mean", "last", "none"):
        raise ValueError(
            f"pooling must be mean|last|none, got {pooling!r}")
    hidden = _hidden_fn(cfg, compute_dtype)

    @jax.jit
    def embed(prepared, ids, lengths):
        h = hidden(prepared, ids)  # (B, T, C) f32
        if pooling == "none":
            return h
        t = ids.shape[1]
        lengths_ = jnp.asarray(lengths, jnp.int32)
        if pooling == "mean":
            mask = (jnp.arange(t)[None, :] < lengths_[:, None])
            s = (h * mask[..., None]).sum(axis=1)
            return s / jnp.maximum(lengths_, 1)[:, None]
        idx = jnp.clip(lengths_ - 1, 0, t - 1)  # "last"
        return jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]

    return embed
